"""Node observability plane tests (ISSUE 9): probe parse + nodes.jsonl
schema round-trip and rejection matrix, gap-marker honesty across a
partition window, quarantine skip + breaker transitions + advisory
health, clock-offset normalization of log-event timestamps, the
log-scanner taxonomy, the merged check-offsets skew series, Perfetto
node-track validity, anomaly excerpts naming node events, Prometheus
exposition, and the seeded clusterless e2e with a wgl verdict carrying
a finite clock-skew-bound."""

import json
import random

import pytest

from jepsen_tpu import checker as jchecker
from jepsen_tpu import client as jclient
from jepsen_tpu import core, nodeprobe, testing, util, web
from jepsen_tpu import generator as gen
from jepsen_tpu import store as jstore
from jepsen_tpu.control.core import (Action, Remote, Session,
                                     TransportError)
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import History, op
from jepsen_tpu.reports import explain
from jepsen_tpu.reports import nodes as rnodes
from jepsen_tpu.reports import trace as rtrace
from jepsen_tpu.workloads import register as register_wl

LOG = "/var/log/db.log"


def _probe_test(nodes=("n1", "n2"), seed=7, **kw):
    util.init_relative_time()
    t = {"nodes": list(nodes), "ssh": {"dummy": True},
         "remote": DummyRemote(nodeprobe.synthetic_responder(seed)),
         "node_log_files": [LOG]}
    t.update(kw)
    return t


def _ticks(test, n=5):
    p = nodeprobe.NodeProbe(test, interval_s=0.01)
    for _ in range(n):
        for node in test["nodes"]:
            p.tick(node)
    p.stop()
    return p


# ---------------------------------------------------------------------------
# Parse + schema
# ---------------------------------------------------------------------------

class TestProbeParse:
    def test_synthetic_round_trip(self):
        p = _ticks(_probe_test())
        recs = p.records()
        assert nodeprobe.validate_records(recs) == len(recs)
        kinds = {r["kind"] for r in recs}
        assert kinds == {"sample", "log"}
        samples = [r for r in recs if r["kind"] == "sample"
                   and r["node"] == "n1"]
        # the first tick has no rates (no previous counters — never a
        # made-up zero), later ticks do
        assert "cpu" not in samples[0]
        assert 0.0 <= samples[1]["cpu"]["busy"] <= 1.0
        assert samples[1]["mem"]["total_kb"] > 0
        assert samples[1]["net"]["rx_bytes_s"] >= 0
        assert isinstance(samples[1]["clock_offset_s"], float)

    def test_crlf_log_offsets_do_not_drift(self):
        """CRLF logs: the \\r bytes survive the reply's line split, so
        the byte-offset accounting stays exact and no line is ever
        re-scanned (no duplicate events, ever-growing offsets)."""
        content = {"text": ""}

        def crlf_responder(node, action):
            cmd = action.cmd
            if nodeprobe.MARK not in cmd:
                return None
            import re as _re

            out = [f"{nodeprobe.MARK} clock", "1.0"]
            for off, path in _re.findall(r"tail -c \+(\d+) (\S+)",
                                         cmd):
                chunk = content["text"].encode()[int(off) - 1:]
                out.append(f"{nodeprobe.MARK} log {path}")
                out.append(chunk.decode() + nodeprobe.EOT)
            return "\n".join(out)

        t = _probe_test(nodes=["n1"])
        t["remote"] = DummyRemote(crlf_responder)
        p = nodeprobe.NodeProbe(t, interval_s=0.01)
        content["text"] = "panic: first\r\n"
        p.tick("n1")
        content["text"] += "plain line\r\npanic: second\r\n"
        p.tick("n1")
        p.tick("n1")  # nothing new: must emit nothing
        p.stop()
        logs = [r for r in p.records() if r["kind"] == "log"]
        assert [r["line"] for r in logs] == ["panic: first",
                                            "panic: second"]
        assert p._states["n1"].offsets[LOG] == len(
            content["text"].encode())

    def test_log_tailer_no_duplicates_across_ticks(self):
        """Byte-offset tailing: each seeded log line is scanned once,
        even though every tick re-probes."""
        p = _ticks(_probe_test(), n=6)
        logs = [r for r in p.records() if r["kind"] == "log"]
        assert logs
        assert len(logs) == len({(r["node"], r["line"])
                                 for r in logs})
        classes = {r["class"] for r in logs}
        assert classes == {"election", "oom-kill"}

    def test_bare_dummy_remote_yields_honest_no_data_gap(self):
        """A reachable-but-mute node (the bare dummy remote's empty
        success) is a gap, not a zeroed sample."""
        t = _probe_test()
        t["remote"] = DummyRemote()  # no responder: empty replies
        p = _ticks(t, n=2)
        recs = p.records()
        assert recs and all(r["kind"] == "gap" for r in recs)
        assert {r["reason"] for r in recs} == {"no-data"}
        assert nodeprobe.validate_records(recs) == len(recs)

    def test_jsonl_round_trip(self, tmp_path):
        t = _probe_test()
        p = nodeprobe.NodeProbe(t, interval_s=0.01)
        p.start(tmp_path / nodeprobe.NODES_FILE)
        # threads are running, but ticks here are deterministic too
        for _ in range(3):
            p.tick("n1")
        p.stop()
        loaded = nodeprobe.load_records(tmp_path)
        assert loaded
        assert nodeprobe.validate_records(loaded) == len(loaded)
        assert loaded == json.loads(json.dumps(loaded))


class TestSchemaRejection:
    def _good(self):
        return [
            {"kind": "sample", "node": "n1", "t": 10,
             "mem": {"total_kb": 1, "free_kb": 1, "used_frac": 0.0},
             "clock_offset_s": 0.5},
            {"kind": "gap", "node": "n1", "t": 20,
             "reason": "unreachable"},
            {"kind": "log", "node": "n1", "t": 30, "class": "oom-kill",
             "file": LOG, "line": "x", "ts": "observed"},
            {"kind": "breaker", "node": "n1", "t": 40,
             "state": "open"},
        ]

    def test_good_records_pass(self):
        assert nodeprobe.validate_records(self._good()) == 4

    @pytest.mark.parametrize("mutate", [
        lambda r: r[0].pop("node"),
        lambda r: r[0].__setitem__("kind", "mystery"),
        lambda r: r[0].__setitem__("t", -1),
        lambda r: r[0].__setitem__("t", 1.5),
        lambda r: r[0].__setitem__("cpu", {"busy": "hot"}),
        lambda r: r[0].__setitem__("clock_offset_s", "skewed"),
        lambda r: r[1].__setitem__("reason", "felt-like-it"),
        lambda r: r[2].__setitem__("class", "novel-anomaly"),
        lambda r: r[2].__setitem__("ts", "guessed"),
        lambda r: r[2].pop("line"),
        lambda r: r[3].__setitem__("state", "ajar"),
        # a sample whose time regresses against its node's series
        lambda r: r.append({"kind": "sample", "node": "n1", "t": 5}),
    ])
    def test_validate_rejects_bad_records(self, mutate):
        recs = self._good()
        mutate(recs)
        with pytest.raises(ValueError):
            nodeprobe.validate_records(recs)


# ---------------------------------------------------------------------------
# Gap honesty + quarantine + breaker + advisory
# ---------------------------------------------------------------------------

class _Cut:
    def __init__(self):
        self.nodes = set()


class CuttingRemote(Remote):
    """Wraps another remote; nodes in `cut.nodes` raise
    TransportError on every command — a partition the probe must
    report as gaps, never interpolate across."""

    def __init__(self, inner, cut: _Cut):
        self.inner = inner
        self.cut = cut

    def connect(self, conn_spec):
        inner = self.inner.connect(conn_spec)
        node = conn_spec.get("host")
        cut = self.cut

        class S(Session):
            def execute(self, action):
                if node in cut.nodes:
                    raise TransportError("partitioned", node=node)
                return inner.execute(action)

            def disconnect(self):
                inner.disconnect()

        return S()


class TestGapHonesty:
    def test_partition_window_yields_gaps_never_interpolation(self):
        cut = _Cut()
        t = _probe_test(nodes=["n1"])
        t["remote"] = CuttingRemote(t["remote"], cut)
        p = nodeprobe.NodeProbe(t, interval_s=0.01)
        p.tick("n1")                      # healthy
        p.tick("n1")
        cut.nodes.add("n1")               # partition window opens
        p.tick("n1")
        p.tick("n1")
        cut.nodes.discard("n1")           # heals
        p.tick("n1")
        p.stop()
        recs = p.records()
        assert nodeprobe.validate_records(recs) == len(recs)
        shape = [r["kind"] for r in recs if r["kind"] in
                 ("sample", "gap")]
        assert shape == ["sample", "sample", "gap", "gap", "sample"]
        assert all(r.get("reason") == "unreachable"
                   for r in recs if r["kind"] == "gap")
        # honesty: nothing sampled inside the window — the gap records
        # ARE the observation, no values were invented. (Log events
        # are excluded: their normalized node-clock times may precede
        # the tick that observed them.)
        ts = [r["t"] for r in recs if r["kind"] in ("sample", "gap")]
        assert ts == sorted(ts)

    def test_quarantined_node_skipped_without_transport_traffic(self):
        from jepsen_tpu.control.health import HealthRegistry

        hr = HealthRegistry(threshold=1, cooldown_s=3600)
        seen = []

        def counting(node, action):
            seen.append((node, action.cmd))
            return None

        t = _probe_test(nodes=["n1"])
        t["remote"] = DummyRemote(counting)
        t["health"] = hr
        hr.breaker("n1").failure()        # circuit opens
        assert hr.breaker("n1").is_open
        p = nodeprobe.NodeProbe(t, interval_s=0.01)
        p.tick("n1")
        p.stop()
        recs = p.records()
        gaps = [r for r in recs if r["kind"] == "gap"]
        assert gaps and gaps[0]["reason"] == "quarantined"
        assert not seen                   # zero commands issued
        # the breaker transition was recorded for the web badge
        assert [r["state"] for r in recs
                if r["kind"] == "breaker"] == ["open"]

    def test_breaker_states_and_half_open_counter(self):
        from jepsen_tpu import telemetry
        from jepsen_tpu.control.health import CircuitBreaker

        telemetry.reset()
        b = CircuitBreaker("n1", threshold=1, cooldown_s=0.0)
        assert b.state() == "closed"
        b.failure()
        # cooldown 0: immediately eligible for a probe
        assert b.state() == "half-open"
        assert b.admit() is True          # granted as THE probe
        assert telemetry.get().counters()[
            "control.quarantine.half-open"] == 1
        b.success()
        assert b.state() == "closed"

    def test_advisory_warns_never_trips(self):
        from jepsen_tpu.control.health import HealthRegistry

        hr = HealthRegistry()
        t = _probe_test(nodes=["n1"], health=hr)
        p = nodeprobe.NodeProbe(t, interval_s=0.01)
        st = p._states["n1"]
        sample = {"kind": "sample", "node": "n1", "t": 1,
                  "mem": {"total_kb": 1000, "free_kb": 10,
                          "used_frac": 0.99},
                  "cpu": {"busy": 0.999}}
        p._advise("n1", st, sample)
        p._advise("n1", st, sample)       # repeated: warned once
        adv = hr.advisories()
        assert set(adv["n1"]) == {"low-memory", "cpu-saturated"}
        # advisory only: no breaker exists, nothing quarantined
        assert hr.quarantined() == []
        assert hr.states().get("n1", "closed") == "closed"


# ---------------------------------------------------------------------------
# Log taxonomy + clock normalization
# ---------------------------------------------------------------------------

class TestLogTaxonomy:
    @pytest.mark.parametrize("line,cls", [
        ("panic: runtime error: index out of range", "panic-assert"),
        ("Assertion failed: (x > 0), function f", "panic-assert"),
        ("Out of memory: Killed process 1234 (db)", "oom-kill"),
        ("raft: node 3 elected leader at term 7", "election"),
        ("stepping down as leader", "election"),
        ("detected data corruption in block 9", "corruption"),
        ("checksum mismatch on sstable 12", "corruption"),
        ("Starting server, version 5.1", "restart"),
        ("received signal SIGTERM, shutting down", "restart"),
        ("slow query: select * from t", None),
        ("", None),
    ])
    def test_classify(self, line, cls):
        assert nodeprobe.classify_line(line) == cls

    def test_first_match_wins(self):
        # a panic that mentions the leader is a panic
        assert nodeprobe.classify_line(
            "panic: leader election raced") == "panic-assert"


class TestClockNormalization:
    def test_parsed_timestamp_normalized_by_measured_offset(self):
        """A log line stamped by a clock 300s in the future lands at
        its TRUE run-relative time once the measured offset is
        subtracted."""
        import calendar

        util.init_relative_time()
        p = nodeprobe.NodeProbe(_probe_test(nodes=["n1"]))
        p.origin_epoch = calendar.timegm((2026, 8, 3, 12, 0, 0))
        skew = 300.0
        # the node thinks it's 12:00:10 + 5m; really 12:00:10
        line = "2026-08-03 12:05:10.500 W | Out of memory: Killed"
        rec = p._log_event("n1", LOG, line, "oom-kill", t=999,
                           clock_offset_s=skew)
        assert rec["ts"] == "parsed"
        assert rec["t"] == int(10.5 * 1e9)
        assert rec["t_node_s"] == pytest.approx(
            p.origin_epoch + 310.5, abs=0.01)

    def test_unparseable_timestamp_stamped_at_observation(self):
        p = nodeprobe.NodeProbe(_probe_test(nodes=["n1"]))
        rec = p._log_event("n1", LOG, "panic: no timestamp here",
                           "panic-assert", t=1234,
                           clock_offset_s=50.0)
        assert rec["ts"] == "observed" and rec["t"] == 1234

    def test_pre_run_timestamp_clamps_not_negative(self):
        p = nodeprobe.NodeProbe(_probe_test(nodes=["n1"]))
        p.origin_epoch = 2e9
        rec = p._log_event("n1", LOG, "[1000000000.5] panic: old",
                           "panic-assert", t=7, clock_offset_s=0.0)
        assert rec["t"] == 0 and rec["ts"] == "parsed"


# ---------------------------------------------------------------------------
# Skew series: probe + check-offsets merge
# ---------------------------------------------------------------------------

def _offsets_history():
    return History([
        op(type="info", process="nemesis", f="check-offsets",
           value=None, time=100),
        op(type="info", process="nemesis", f="check-offsets",
           value=None, time=200,
           **{"clock-offsets": {"n1": 0.75, "n2": -0.1}}),
    ])


class TestSkewSeries:
    def test_check_offsets_merge_into_series(self):
        recs = [{"kind": "sample", "node": "n1", "t": 500,
                 "clock_offset_s": 0.2}]
        series = nodeprobe.clock_series(recs, _offsets_history())
        assert series["n1"] == [[200, 0.75], [500, 0.2]]
        assert series["n2"] == [[200, -0.1]]

    def test_bound_is_worst_absolute_offset(self):
        recs = [{"kind": "sample", "node": "n1", "t": 1,
                 "clock_offset_s": -0.3}]
        assert nodeprobe.clock_skew_bound(
            recs, _offsets_history()) == 0.75
        assert nodeprobe.clock_skew_bound(recs, None) == 0.3
        # an unmeasured run claims NO bound, not a zero one
        assert nodeprobe.clock_skew_bound([], History([])) is None

    def test_stamp_hits_realtime_verdicts_only(self):
        results = {
            "valid?": True,
            "linear": {"valid?": True,
                       "anomaly-classes": {"nonlinearizable": "clean"}},
            "elle": {"valid?": True,
                     "anomaly-classes": {"G0": "clean",
                                         "G1a": "clean"}},
            "stats": {"valid?": True, "count": 3},
        }
        n = nodeprobe.stamp_results(results, 0.5)
        assert n == 2
        assert results["linear"]["clock-skew-bound"] == 0.5
        assert results["elle"]["clock-skew-bound"] == 0.5
        assert "clock-skew-bound" not in results["stats"]

    def test_clock_plot_merges_probe_series(self, tmp_path):
        from jepsen_tpu.reports import clock as rclock

        t = {"store_dir": str(tmp_path)}
        with open(tmp_path / nodeprobe.NODES_FILE, "w") as f:
            f.write(json.dumps({"kind": "sample", "node": "n1",
                                "t": int(3e9),
                                "clock_offset_s": 0.4}) + "\n")
        hist = _offsets_history()
        merged = rclock.merge_nodeprobe(
            rclock.history_to_datasets(hist), t)
        pts = merged["n1"]
        assert [3.0, 0.4] in pts
        assert any(v == 0.75 for _t, v in pts)


# ---------------------------------------------------------------------------
# Perfetto node tracks
# ---------------------------------------------------------------------------

class TestPerfetto:
    def test_node_tracks_validate(self):
        p = _ticks(_probe_test(), n=5)
        recs = p.records()
        recs.append({"kind": "gap", "node": "n1",
                     "t": util.relative_time_nanos(),
                     "reason": "unreachable"})
        doc = rtrace.chrome_trace({}, History([]), [], noderecs=recs)
        assert rtrace.validate_chrome_trace(doc) > 0
        procs = {e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M"
                 and e["name"] == "process_name"}
        assert {"node n1", "node n2"} <= procs
        counters = {e["name"] for e in doc["traceEvents"]
                    if e.get("ph") == "C"}
        assert {"cpu_busy", "mem_used_frac",
                "clock_offset_ms"} <= counters
        instants = {e["name"] for e in doc["traceEvents"]
                    if e.get("ph") == "i"
                    and e.get("cat", "").startswith("node")}
        assert "gap:unreachable" in instants
        assert any(n.startswith("log:") for n in instants)

    def test_check_offsets_render_without_probe_samples(self):
        """Satellite fix: a run with only check-offsets history still
        gets a per-node clock-offset counter track."""
        doc = rtrace.chrome_trace({}, _offsets_history(), [],
                                  noderecs=[])
        assert rtrace.validate_chrome_trace(doc) > 0
        cs = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
        assert {e["args"]["clock_offset_ms"] for e in cs} == \
            {750.0, -100.0}

    def test_counter_event_with_bad_args_rejected(self):
        doc = {"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
             "args": {"name": "x"}},
            {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
             "args": {"name": "c"}},
            {"ph": "C", "name": "c", "pid": 1, "tid": 1, "ts": 1,
             "args": {"c": "fast"}}]}
        with pytest.raises(ValueError):
            rtrace.validate_chrome_trace(doc)


# ---------------------------------------------------------------------------
# Excerpts + renderers + prometheus
# ---------------------------------------------------------------------------

class TestNodeContext:
    def _noderecs(self):
        return [
            {"kind": "log", "node": "n1", "t": int(1.5e9),
             "class": "election", "file": LOG,
             "line": "raft: became leader", "ts": "parsed"},
            {"kind": "gap", "node": "n2", "t": int(2e9),
             "reason": "unreachable"},
            {"kind": "log", "node": "n1", "t": int(500e9),
             "class": "restart", "file": LOG,
             "line": "way outside the window", "ts": "observed"},
        ]

    def test_window_filter_and_format(self):
        lines = explain.node_context_lines(self._noderecs(),
                                           int(1e9), int(3e9))
        joined = "\n".join(lines)
        assert "election" in joined and "became leader" in joined
        assert "probe gap: unreachable" in joined
        assert "way outside" not in joined

    def test_excerpt_names_node_events(self, tmp_path):
        from jepsen_tpu import tracing

        tr = tracing.Tracer(enabled=True)
        from jepsen_tpu.history import Op

        for i in (0, 2):
            o = Op(index=i, time=i, type="invoke", process=0, f="txn",
                   value=None)
            with tr.op_span(o):
                pass
        result = {"anomalies": {"G1a": [{"op-indices": [0, 2]}]}}
        paths = explain.write_trace_excerpts(
            tmp_path, result, optrace=tr.records(),
            noderecs=[{"kind": "log", "node": "n1", "t": 1,
                       "class": "oom-kill", "file": LOG,
                       "line": "Out of memory: Killed process 42",
                       "ts": "parsed"}])
        body = open(paths[0]).read()
        assert "node events in the op window" in body
        assert "oom-kill" in body and "Killed process 42" in body


class TestRenderers:
    def test_nodes_text_table(self):
        p = _ticks(_probe_test(), n=5)
        txt = rnodes.nodes_text(p.records())
        assert "n1" in txt and "n2" in txt
        assert "clock-skew-bound" in txt
        assert "election" in txt

    def test_lanes_html_marks_faults_gaps_and_events(self):
        p = _ticks(_probe_test(), n=4)
        recs = p.records()
        recs.append({"kind": "gap", "node": "n1",
                     "t": util.relative_time_nanos(),
                     "reason": "quarantined"})
        t_max = max(r["t"] for r in recs)
        html = rnodes.lanes_html(
            recs, faults=[{"kind": "partition",
                           "windows": [[0, t_max // 2]]}])
        assert "<h2>nodes</h2>" in html and "partition" in html
        assert "gap: quarantined" in html
        assert "clock-skew-bound" in html

    def test_prometheus_lines_scrape_parse(self):
        from jepsen_tpu.reports.profile import \
            validate_prometheus_text

        p = _ticks(_probe_test(), n=4)
        lines = nodeprobe.prometheus_lines(p.records())
        assert validate_prometheus_text("\n".join(lines) + "\n") > 0
        joined = "\n".join(lines)
        assert "jepsen_tpu_node_cpu_busy" in joined
        assert "jepsen_tpu_node_log_events" in joined


# ---------------------------------------------------------------------------
# End-to-end: seeded clusterless run (the ISSUE-9 acceptance path)
# ---------------------------------------------------------------------------

class TestEndToEnd:
    def _run(self, tmp_path, corrupt=False):
        from jepsen_tpu.checker import models

        state = testing.AtomState()
        inner = testing.AtomClient(state)
        reads = [0]

        class MaybeCorrupting(jclient.Client):
            def open(self, test, node):
                return self

            def invoke(self, test, op_):
                out = inner.invoke(test, op_)
                if corrupt and op_.f == "read" and out.type == "ok":
                    reads[0] += 1
                    if reads[0] == 5:
                        return out.copy(value=999)
                return out

        rng = random.Random(7)
        t = testing.noop_test()
        t.update(
            name="nodeplane-e2e", store_base=str(tmp_path),
            nodes=["n1", "n2"], concurrency=4,
            remote=DummyRemote(nodeprobe.synthetic_responder(11)),
            node_log_files=[LOG],
            client=MaybeCorrupting(),
            checker=jchecker.compose({
                "stats": jchecker.stats(),
                "linear": jchecker.linearizable(
                    {"model": models.cas_register(),
                     "algorithm": "wgl"})}),
            generator=gen.clients(gen.stagger(0.01, gen.limit(
                30, lambda: register_wl.cas_op_mix(rng,
                                                   n_values=3)))))
        t["nodeprobe?"] = True
        t["nodeprobe_interval_s"] = 0.02
        t["trace?"] = True
        return core.run(t)

    def test_clean_run_stamps_finite_skew_bound(self, tmp_path):
        test = self._run(tmp_path)
        d = jstore.path(test)
        recs = jstore.load_nodes(d)
        # schema-valid nodes.jsonl with >= 1 tagged log event
        assert nodeprobe.validate_records(recs) == len(recs)
        assert any(r["kind"] == "log" for r in recs)
        res = test["results"]
        # the wgl-realtime verdict carries a FINITE clock-skew-bound
        bound = res["linear"].get("clock-skew-bound")
        assert isinstance(bound, float) and 0 < bound < 10
        assert res.get("clock-skew-bound") == bound
        # Perfetto export with node tracks validates
        doc = json.load(open(rtrace.write_trace(d)))
        assert rtrace.validate_chrome_trace(doc) > 0
        procs = {e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M"
                 and e["name"] == "process_name"}
        assert {"node n1", "node n2"} <= procs
        # the web run page renders the lanes
        rel = f"nodeplane-e2e/{d.name}"
        html = web.dir_html(rel + "/", d)
        assert "<h2>nodes</h2>" in html

    def test_seeded_anomaly_excerpt_names_node_event(self, tmp_path):
        test = self._run(tmp_path, corrupt=True)
        res = test["results"]["linear"]
        assert res["valid?"] is False
        assert res.get("clock-skew-bound", 0) > 0
        body = open(res["trace-excerpt"]).read()
        # the anomaly excerpt names the node events in its op window
        assert "node events in the op window" in body
        assert "election" in body or "oom-kill" in body
