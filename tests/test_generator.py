"""Generator semantics tests.

Ported case-for-case from the reference's
jepsen/test/jepsen/generator_test.clj (32 deftests); assertions that
depended on JVM RNG tie-breaking are relaxed to order-insensitive
invariants.
"""

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu.generator import PENDING
from jepsen_tpu.generator import test_support as gt
from jepsen_tpu.generator.context import all_but, make_thread_filter
from jepsen_tpu.history import Op


def tup(ops, *fields):
    return [tuple(o.get(f) for f in fields) for o in ops]


def test_nil():
    assert gt.perfect(None) == []


def test_map_once():
    ops = gt.perfect({"f": "write"})
    assert tup(ops, "time", "process", "type", "f", "value") == [
        (0, 0, "invoke", "write", None)]


def test_map_concurrent():
    ops = gt.perfect(gen.repeat(6, {"f": "write"}))
    assert [o.time for o in ops] == [0, 0, 0, 10, 10, 10]
    assert sorted(str(o.process) for o in ops[:3]) == ["0", "1", "nemesis"]
    assert sorted(str(o.process) for o in ops[3:]) == ["0", "1", "nemesis"]


def test_map_all_threads_busy():
    ctx = gt.default_context()
    for t in ctx.all_thread_names():
        ctx = ctx.busy_thread(0, t)
    res = gen.op({"f": "write"}, {}, ctx)
    assert res == (PENDING, {"f": "write"})


def test_limit():
    ops = gt.quick(gen.limit(2, gen.repeat({"f": "write", "value": 1})))
    assert tup(ops, "time", "f", "value") == [(0, "write", 1), (0, "write", 1)]
    assert sorted(o.process for o in ops) == [0, 1]


def test_repeat():
    gens = ({"value": x} for x in range(100))
    ops = gt.perfect(gen.repeat(3, gens))
    assert [o.value for o in ops] == [0, 0, 0]


def test_delay():
    ops = gt.perfect(
        gen.limit(5, gen.delay(3e-9, gen.repeat({"f": "write"}))))
    assert [o.time for o in ops] == [0, 3, 6, 10, 13]


def test_seq_vectors():
    ops = gt.quick([{"value": 1}, {"value": 2}, {"value": 3}])
    assert [o.value for o in ops] == [1, 2, 3]


def test_seq_nested():
    ops = gt.quick([[{"value": 1}, {"value": 2}],
                    [[{"value": 3}], {"value": 4}],
                    {"value": 5}])
    assert [o.value for o in ops] == [1, 2, 3, 4, 5]


def test_seq_updates_propagate_to_first_generator():
    g = gen.clients([gen.until_ok(gen.repeat({"f": "read"})),
                     {"f": "done"}])
    types = iter(["fail", "fail", "ok", "ok"] + ["info"] * 10)

    def complete(ctx, op):
        return op.copy(time=op.time + 10, type=next(types))

    ops = gt.simulate(g, complete)
    got = tup(ops, "time", "f", "type")
    # Both threads read; both fail; both retry; one ok leads to :done.
    assert got[:2] == [(0, "read", "invoke"), (0, "read", "invoke")]
    assert ("done", "invoke") in {(o.f, o.type) for o in ops}
    # After the first :ok read, no new :read invocations are issued.
    first_ok = next(i for i, o in enumerate(ops)
                    if o.type == "ok" and o.f == "read")
    later_reads = [o for o in ops[first_ok:]
                   if o.type == "invoke" and o.f == "read"]
    assert later_reads == []


def test_fn_returning_nil():
    assert gt.quick(lambda: None) == []


def test_fn_returning_literal_map():
    import random
    ops = gt.perfect(gen.limit(5, lambda: {"f": "write",
                                           "value": random.randint(0, 9)}))
    assert len(ops) == 5
    assert all(0 <= o.value <= 9 for o in ops)
    assert {str(o.process) for o in ops} == {"0", "1", "nemesis"}


def test_fn_returning_repeat_maps():
    import random
    ops = gt.perfect(gen.limit(
        5, lambda: gen.repeat({"f": "write", "value": random.randint(0, 9)})))
    assert len(ops) == 5
    assert len({o.value for o in ops}) == 1


def test_on_update_and_promise():
    p = gen.Promise()

    def updater(this, test, ctx, event):
        if event.type == "ok" and event.f == "write":
            p.deliver({"f": "confirm", "value": event.value})
        return this

    g = gen.on_threads({0, 1},
                       gen.limit(5, gen.on_update(
                           updater,
                           gen.any_gen(p, [{"f": "read"},
                                           {"f": "write", "value": "x"},
                                           gen.repeat({"f": "hold"})]))))
    ops = gt.quick_ops(g)
    invokes = [o for o in ops if o.type == "invoke"]
    fs = [o.f for o in invokes]
    assert fs[0:2] == ["read", "write"]
    assert "confirm" in fs
    # Confirm op carries the written value.
    confirm = next(o for o in invokes if o.f == "confirm")
    assert confirm.value == "x"


def test_delayed():
    seen_ctx = {}

    def make(test, ctx):
        seen_ctx.setdefault("time", ctx.time)  # first-call ctx, like the
        return {"f": "delayed"}                # reference's promise

    d = gen.Delayed(lambda: gen.limit(3, make))
    ops = gt.perfect(gen.clients(gen.phases({"f": "write"}, {"f": "read"}, d)))
    assert [(o.f, o.time) for o in ops] == [
        ("write", 0), ("read", 10), ("delayed", 20), ("delayed", 20),
        ("delayed", 30)]
    assert seen_ctx["time"] == 20


def test_synchronize():
    def make(test, ctx):
        p = ctx.some_free_process()
        delay = {0: 2, 1: 1, "nemesis": 2}[p]
        return {"f": "a", "process": p, "time": ctx.time + delay}

    g = [gen.limit(3, make), gen.synchronize(gen.repeat(2, {"f": "b"}))]
    ops = gt.perfect(g)
    assert [o.f for o in ops] == ["a", "a", "a", "b", "b"]
    # All :a ops complete (latest at 5+10=15) before :b starts.
    assert ops[3].time == 15
    assert ops[4].time == 15


def test_clients():
    ops = gt.perfect(gen.limit(5, gen.clients(gen.repeat({}))))
    assert {o.process for o in ops} == {0, 1}


def test_phases():
    ops = gt.perfect(gen.clients(gen.phases(gen.repeat(2, {"f": "a"}),
                                            gen.repeat(1, {"f": "b"}),
                                            gen.repeat(3, {"f": "c"}))))
    assert tup(ops, "f", "time") == [
        ("a", 0), ("a", 0), ("b", 10), ("c", 20), ("c", 20), ("c", 30)]


def test_any():
    g = gen.any_gen(
        gen.on_threads({0}, gen.delay(20e-9, gen.repeat({"f": "a"}))),
        gen.on_threads({1}, gen.delay(20e-9, gen.repeat({"f": "b"}))))
    ops = gt.perfect(gen.limit(4, g))
    got = tup(ops, "f", "process", "time")
    assert sorted(got[:2]) == [("a", 0, 0), ("b", 1, 0)]
    assert sorted(got[2:]) == [("a", 0, 20), ("b", 1, 20)]


def test_each_thread():
    ops = gt.perfect(gen.each_thread([{"f": "a"}, {"f": "b"}]))
    assert [o.time for o in ops] == [0, 0, 0, 10, 10, 10]
    assert all(o.f == "a" for o in ops[:3])
    assert all(o.f == "b" for o in ops[3:])
    assert sorted(str(o.process) for o in ops[:3]) == ["0", "1", "nemesis"]


def test_each_thread_collapses_when_exhausted():
    res = gen.op(gen.each_thread(gen.limit(0, {"f": "read"})), {},
                 gt.default_context())
    assert res is None


def test_stagger_rate():
    n, dt = 1000, 20
    ops = gt.perfect(gen.stagger(
        dt * 1e-9, [{"f": "write", "value": x} for x in range(n)]))
    max_time = ops[-1].time
    rate = n / max_time
    assert 0.9 <= rate / (1 / dt) <= 1.1


def test_f_map():
    ops = gt.perfect(gen.f_map({"a": "b"}, {"f": "a", "value": 2}))
    assert tup(ops, "type", "process", "time", "f", "value") == [
        ("invoke", 0, 0, "b", 2)]


def test_filter():
    g = gen.gfilter(lambda o: o.value % 2 == 0,
                    gen.limit(10, ({"value": x} for x in range(100))))
    ops = gt.perfect(g)
    assert [o.value for o in ops] == [0, 2, 4, 6, 8]


def test_log():
    ops = gt.perfect(gen.phases(gen.log("first"), {"f": "a"},
                                gen.log("second"), {"f": "b"}))
    assert [o.f for o in ops] == ["a", "b"]


def test_mix():
    ops = gt.perfect(gen.mix([gen.repeat(5, {"f": "a"}),
                              gen.repeat(10, {"f": "b"})]))
    fs = [o.f for o in ops]
    assert fs.count("a") == 5
    assert fs.count("b") == 10
    assert fs != ["a"] * 5 + ["b"] * 10  # actually mixed


def test_process_limit():
    ops = gt.perfect_info(gen.clients(gen.process_limit(
        5, ({"value": x} for x in range(100)))))
    # 5 distinct processes, each crashing spawns the next.
    assert len(ops) == 5
    assert len({o.process for o in ops}) == 5
    assert [o.value for o in ops] == list(range(5))


def test_time_limit():
    g = [gen.time_limit(20e-9, gen.repeat({"value": "a"})),
         gen.time_limit(10e-9, gen.repeat({"value": "b"}))]
    ops = gt.perfect(g)
    assert tup(ops, "time", "value") == [
        (0, "a"), (0, "a"), (0, "a"),
        (10, "a"), (10, "a"), (10, "a"),
        (20, "b"), (20, "b"), (20, "b")]


def integers(**kv):
    x = 0
    while True:
        yield dict(value=x, **kv)
        x += 1


def test_reserve_default_only():
    ops = gt.perfect(gen.limit(3, gen.reserve(integers(f="a"))))
    assert [o.value for o in ops] == [0, 1, 2]
    assert sorted(str(o.process) for o in ops) == ["0", "1", "nemesis"]


def test_reserve_three_ranges():
    ops = gt.perfect(
        gen.limit(15, gen.reserve(2, integers(f="a"),
                                  3, integers(f="b"),
                                  integers(f="c"))),
        ctx=gt.n_plus_nemesis_context(5))
    by_f = {}
    for o in ops:
        by_f.setdefault(o.f, []).append(o)
    # Threads 0-1 run a, 2-4 run b, nemesis runs c.
    assert {o.process for o in by_f["a"]} <= {0, 1}
    assert {o.process for o in by_f["b"]} <= {2, 3, 4}
    assert {o.process for o in by_f["c"]} == {"nemesis"}
    # Values per class are sequential.
    for f, l in by_f.items():
        assert [o.value for o in l] == list(range(len(l)))


def test_at_least_one_ok():
    # until-ok with a failing system retries until success.
    types = iter(["fail"] * 4 + ["ok"] * 100)

    def complete(ctx, op):
        return op.copy(time=op.time + 10, type=next(types))

    g = gen.clients(gen.until_ok(gen.repeat({"f": "read"})))
    ops = gt.simulate(g, complete)
    oks = [o for o in ops if o.type == "ok"]
    assert len(oks) >= 1


def test_flip_flop():
    g = gen.flip_flop(({"f": "a", "value": x} for x in range(100)),
                      [{"f": "b", "value": 0}, {"f": "b", "value": 1}])
    ops = gt.quick(gen.limit(5, g))
    assert tup(ops, "f", "value") == [
        ("a", 0), ("b", 0), ("a", 1), ("b", 1), ("a", 2)]


def test_concat():
    g = [gen.limit(2, integers(f="a")), gen.limit(2, integers(f="b"))]
    ops = gt.quick(g)
    assert tup(ops, "f", "value") == [
        ("a", 0), ("a", 1), ("b", 0), ("b", 1)]


def test_cycle():
    g = gen.cycle(gen.limit(2, integers(f="a")), times=2)
    ops = gt.quick(g)
    assert [o.f for o in ops] == ["a"] * 4


def test_cycle_times():
    g = gen.cycle_times(5e-9, gen.repeat({"f": "a"}),
                        10e-9, gen.repeat({"f": "b"}))
    ops = gt.perfect(gen.limit(12, gen.on_threads({0}, g)))
    # a-window [0,5), b-window [5,15), a [15,20), b [20,30) ...
    for o in ops:
        phase = o.time % 15
        assert (o.f == "a") == (phase < 5), (o.time, o.f)


def test_validate_rejects_bad_op():
    class Bad(gen.Generator):
        def op(self, test, ctx):
            return Op(type="bogus", process=0, time=0), None

    with pytest.raises(gen.InvalidOp):
        gt.quick(Bad())


def test_friendly_exceptions():
    def boom():
        raise ValueError("boom")

    with pytest.raises(gen.GeneratorError):
        gen.op(gen.friendly_exceptions(boom), {}, gt.default_context())


def test_until_ok_stops_after_ok():
    g = gen.clients(gen.until_ok(gen.repeat({"f": "read"})))
    ops = gt.simulate(
        g, lambda c, o: o.copy(type="ok", time=o.time + 10))
    # Two threads may have one in flight each; after first ok both stop.
    assert len([o for o in ops if o.type == "invoke"]) <= 2


def test_pending_returned_when_no_free_process():
    ctx = gt.default_context()
    for t in ctx.all_thread_names():
        ctx = ctx.busy_thread(0, t)
    res = gen.op(gen.repeat({"f": "x"}), {}, ctx)
    assert res[0] is PENDING


def test_context_with_next_process():
    ctx = gt.default_context()
    assert ctx.thread_to_process(0) == 0
    ctx = ctx.with_next_process(0)
    # 2 int threads -> process 0 becomes 2.
    assert ctx.thread_to_process(0) == 2
    assert ctx.process_to_thread_name(2) == 0
    assert ctx.process_to_thread_name(0) is None
    ctx = ctx.with_next_process(0)
    assert ctx.thread_to_process(0) == 4


def test_context_filter_keeps_thread_zero():
    ctx = gt.default_context()
    f = make_thread_filter(all_but("nemesis"), ctx)
    c2 = f(ctx)
    assert set(map(str, c2.all_thread_names())) == {"0", "1"}


def test_nemesis_route():
    g = gen.nemesis(gen.limit(3, gen.repeat({"f": "break"})))
    ops = gt.perfect(g)
    assert all(o.process == "nemesis" for o in ops)


def test_fn_generator_constant_depth():
    """Fn generators re-invoked thousands of times must not accumulate
    nested Seq continuations (blew the recursion limit past ~400 ops
    before tail flattening)."""
    import sys

    n = 0

    def fn():
        return {"f": "w", "value": n}

    import inspect

    limit = sys.getrecursionlimit()
    try:
        # fixed headroom above the *current* depth, so harness stack
        # depth (pytest plugins, coverage, ...) can't starve the budget
        sys.setrecursionlimit(len(inspect.stack()) + 180)
        ops = gt.quick(gen.limit(3000, fn))
    finally:
        sys.setrecursionlimit(limit)
    assert len(ops) == 3000


def test_per_test_rng_isolation():
    """Two tests with the same seed get identical schedules even when
    interleaved; different seeds diverge (VERDICT r2: module-global
    set_seed let concurrent tests perturb each other)."""

    def schedule(seed, interleave_with=None):
        test = {"concurrency": 3, "seed": seed}
        ctx = gen.context(test)
        g = gen.mix([gen.repeat({"f": "a"}), gen.repeat({"f": "b"}),
                     gen.repeat({"f": "c"})])
        out = []
        for _ in range(30):
            o, g = gen.op(g, test, ctx)
            out.append(o.f)
            if interleave_with is not None:
                # another test consuming ITS OWN context rng must not
                # perturb this schedule
                ot, gt = interleave_with
                gen.op(gt, ot, gen.context(ot))
        return out

    base = schedule(7)
    other = ({"concurrency": 3, "seed": 99},
             gen.mix([gen.repeat({"f": "x"}), gen.repeat({"f": "y"})]))
    assert schedule(7, interleave_with=other) == base
    assert schedule(8) != base


def test_seedless_contexts_honor_set_seed():
    """Contexts without test['seed'] must keep using the module
    fallback RNG so simulate()'s set_seed stays deterministic
    (round-3 review finding)."""
    g = lambda: gen.mix([gen.repeat({"f": "a"}), gen.repeat({"f": "b"}),
                         gen.repeat({"f": "c"})])
    a = [o.f for o in gt.perfect(gen.limit(25, g()))]
    b = [o.f for o in gt.perfect(gen.limit(25, g()))]
    assert a == b
