"""Multi-host wiring + profiling hook tests (conftest pins an
8-device virtual CPU mesh, so global_mesh exercises the real mesh
path without hardware)."""

import os

from jepsen_tpu import util
from jepsen_tpu.tpu import dist


def test_no_env_is_single_host_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_MULTIHOST", raising=False)
    monkeypatch.setattr(dist, "_initialized", False)
    assert dist.multihost_requested() is False
    assert dist.ensure_initialized() is False


def test_late_init_degrades_to_single_host(monkeypatch):
    """After JAX computed, a late initialize must warn + degrade, not
    crash the check (round-3 review finding)."""
    import jax.numpy as jnp

    (jnp.arange(4) + 1).block_until_ready()  # backend is live
    monkeypatch.setenv("JEPSEN_TPU_MULTIHOST", "1")
    monkeypatch.setattr(dist, "_initialized", False)
    assert dist.ensure_initialized() in (False, True)  # never raises


def test_process_info_shape():
    info = dist.process_info()
    assert info["process_count"] >= 1
    assert info["global_devices"] >= info["local_devices"] >= 1


def test_ensemble_mesh_still_works():
    from jepsen_tpu.tpu import ensemble

    mesh = ensemble.default_mesh()
    assert mesh.axis_names == ("b",)


def test_profile_trace_writes_xplane(tmp_path):
    import jax.numpy as jnp

    with util.profile_trace(tmp_path / "xprof"):
        (jnp.arange(128) * 2).block_until_ready()
    files = list((tmp_path / "xprof").rglob("*"))
    assert any(f.is_file() for f in files), files


def test_profile_trace_noop_without_dir():
    with util.profile_trace(None):
        pass
