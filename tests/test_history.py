"""Op/History data structure tests (pairing, SoA encoding, predicates)."""

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu.history import History, Op


def test_op_map_like():
    o = h.op(type="invoke", process=0, f="write", value=3, time=5,
             extra="x")
    assert o["f"] == "write"
    assert o.get("extra") == "x"
    assert o.get("missing", 42) == 42
    assert "extra" in o
    o2 = o.copy(type="ok", error="nope")
    assert o2.type == "ok"
    assert o2.error == "nope"
    assert o.type == "invoke"  # original unchanged


def test_history_pairing():
    hist = History([
        dict(type="invoke", process=0, f="w", value=1, time=0),
        dict(type="invoke", process=1, f="r", value=None, time=1),
        dict(type="ok", process=0, f="w", value=1, time=2),
        dict(type="info", process=1, f="r", value=None, time=3),
        dict(type="invoke", process=2, f="r", value=None, time=4),
    ])
    pair = hist.pair_index()
    assert pair[0] == 2 and pair[2] == 0
    assert pair[1] == 3 and pair[3] == 1
    assert pair[4] == -1  # never completed
    assert hist.completion(hist[0]).type == "ok"
    assert hist.invocation(hist[3]).index == 1


def test_history_filters():
    hist = History([
        dict(type="invoke", process=0, f="w", time=0),
        dict(type="ok", process=0, f="w", time=1),
        dict(type="invoke", process="nemesis", f="start", time=2),
        dict(type="info", process="nemesis", f="start", time=3),
    ])
    assert len(hist.client_ops()) == 2
    assert len(hist.nemesis_ops()) == 2
    assert len(hist.oks()) == 1
    assert len(hist.invokes()) == 2


def test_soa_encoding():
    hist = History([
        dict(type="invoke", process=0, f="w", value=1, time=10),
        dict(type="ok", process=0, f="w", value=1, time=20),
        dict(type="invoke", process="nemesis", f="start", time=30),
    ])
    soa = hist.to_soa()
    assert soa.time.tolist() == [10, 20, 30]
    assert soa.type.tolist() == [0, 1, 0]
    assert soa.process[2] < 0  # named process encoded negative
    assert soa.f_codes["w"] == 0
    assert soa.pair.tolist() == [1, 0, -1]
