"""Report checkers: perf graphs, timeline HTML, clock plot — artifacts
written into the store dir, plus the pure math helpers."""

import pytest

from jepsen_tpu import checker, util
from jepsen_tpu.reports import clock as clock_mod
from jepsen_tpu.reports import perf as perf_mod
from jepsen_tpu.reports import timeline as timeline_mod
from jepsen_tpu.history import History, op


def sec(s):
    return util.secs_to_nanos(s)


def register_history():
    events = []
    i = 0
    for t in range(20):
        events.append(op(index=i, time=sec(t) + 1, type="invoke",
                         process=t % 3, f="read", value=None))
        typ = ["ok", "ok", "ok", "fail", "info"][t % 5]
        events.append(op(index=i + 1, time=sec(t) + int(2e8), type=typ,
                         process=t % 3, f="read", value=1))
        i += 2
    # a nemesis interval for shading
    events.append(op(index=i, time=sec(5), type="info",
                     process="nemesis", f="start", value=None))
    events.append(op(index=i + 1, time=sec(12), type="info",
                     process="nemesis", f="stop", value=None))
    events.sort(key=lambda o: o.time)
    return History(events, assign_indices=False)


def test_bucketing():
    assert perf_mod.bucket_time(10, 3) == 5.0
    assert perf_mod.bucket_time(10, 17) == 15.0
    got = perf_mod.bucket_points(10, [[1, "a"], [2, "b"], [11, "c"]])
    assert got == {5.0: [[1, "a"], [2, "b"]], 15.0: [[11, "c"]]}


def test_quantiles():
    q = perf_mod.quantiles([0.5, 1.0], [5, 1, 3, 2, 4])
    assert q == {0.5: 3, 1.0: 5}
    assert perf_mod.quantiles([0.5], []) == {}


def test_latencies_to_quantiles():
    pts = [[t, float(t)] for t in range(20)]
    q = perf_mod.latencies_to_quantiles(10, [1.0], pts)
    assert q[1.0] == [[5.0, 9.0], [15.0, 19.0]]


def test_invokes_by_f_type():
    h = register_history()
    by = perf_mod.invokes_by_f_type(h)
    assert set(by) == {"read"}
    assert sum(len(v) for v in by["read"].values()) == 20
    assert len(by["read"]["fail"]) == 4
    assert len(by["read"]["info"]) == 4


def test_perf_checker_writes_artifacts(tmp_path):
    test = {"name": "perf-test", "store_dir": str(tmp_path),
            "nodes": ["n1"],
            "plot": {"nemeses": [{"name": "nemesis",
                                  "start": {"start"}, "stop": {"stop"},
                                  "color": "#E9DCA0"}]}}
    res = checker.check_safe(checker.perf(), test, register_history())
    assert res["valid?"] is True
    files = (res["latency-graph"]["files"]
             + res["rate-graph"]["files"])
    names = {f.split("/")[-1] for f in files}
    assert names == {"latency-raw.png", "latency-quantiles.png",
                     "rate.png"}
    for f in files:
        import os
        assert os.path.getsize(f) > 1000


def bank_history():
    """Transfers + reads over 3 accounts, balances conserved."""
    events = []
    bal = {0: 10, 1: 10, 2: 10}
    i = 0
    for t in range(12):
        if t % 3 == 2:
            frm, to = t % 2, (t % 2) + 1
            bal[frm] -= 1
            bal[to] += 1
            v = {"from": frm, "to": to, "amount": 1}
            events.append(op(index=i, time=sec(t), type="invoke",
                             process=0, f="transfer", value=v))
            events.append(op(index=i + 1, time=sec(t) + int(1e8),
                             type="ok", process=0, f="transfer",
                             value=v))
        else:
            events.append(op(index=i, time=sec(t), type="invoke",
                             process=1, f="read", value=None))
            events.append(op(index=i + 1, time=sec(t) + int(1e8),
                             type="ok", process=1, f="read",
                             value=dict(bal)))
        i += 2
    return History(events, assign_indices=False)


def test_bank_balance_plot_renders(tmp_path):
    """ISSUE-4 satellite: the bank workload's balance-over-time plot
    (bank.clj:150-176 analog) renders into the store dir."""
    from jepsen_tpu.workloads import bank

    test = {"name": "bank-plot", "store_dir": str(tmp_path),
            "nodes": ["n1"], "total-amount": 30}
    w = bank.workload({"total-amount": 30})
    res = checker.check_safe(w["checker"], test, bank_history())
    assert res["valid?"] is True, res
    files = res["balance-plot"]["files"]
    assert [f.split("/")[-1] for f in files] == ["bank-balances.png"]
    import os
    assert os.path.getsize(files[0]) > 1000
    # and the conservation verdict still rides alongside
    assert res["bank"]["valid?"] is True


def test_bank_balance_plot_skips_without_reads(tmp_path):
    test = {"name": "bank-plot-empty", "store_dir": str(tmp_path)}
    res = checker.check_safe(perf_mod.balance_graph(), test,
                             History([]))
    assert res["valid?"] is True and res["files"] == []


def test_perf_checker_skips_without_store():
    res = checker.check_safe(checker.perf(), {"nodes": []},
                             register_history())
    assert res["valid?"] is True
    assert res["latency-graph"]["skipped"]


def test_timeline_pairs():
    h = History([
        op(type="invoke", process=0, f="w", value=1),
        op(type="invoke", process=1, f="r", value=None),
        op(type="ok", process=0, f="w", value=1),
        op(type="info", process=1, f="r", value=None),
        op(type="info", process="nemesis", f="start", value=None),
    ])
    prs = timeline_mod.pairs(h)
    shapes = {(str(p[0].process), len(p)) for p in prs}
    assert ("0", 2) in shapes and ("1", 2) in shapes
    assert ("nemesis", 1) in shapes


def test_timeline_html(tmp_path):
    test = {"name": "tl", "store_dir": str(tmp_path)}
    res = checker.check_safe(timeline_mod.html(), test,
                             register_history())
    assert res["valid?"] is True
    text = (tmp_path / "timeline.html").read_text()
    assert "op ok" in text and "op fail" in text and "op info" in text
    assert text.count("class=\"op ") == 22  # 20 client pairs + 2 nemesis
    assert "Truncated" not in text


def test_timeline_truncates(tmp_path, monkeypatch):
    monkeypatch.setattr(timeline_mod, "OP_LIMIT", 5)
    test = {"name": "tl", "store_dir": str(tmp_path)}
    checker.check_safe(timeline_mod.html(), test, register_history())
    text = (tmp_path / "timeline.html").read_text()
    assert "Truncated to 5 operations" in text


def test_clock_datasets():
    h = History([
        op(index=0, time=sec(1), type="info", process="nemesis",
           f="check-offsets", value=None,
           **{"clock-offsets": {"n1": 0.5, "n2": -0.25}}),
        op(index=1, time=sec(3), type="info", process="nemesis",
           f="bump", value=None, **{"clock-offsets": {"n1": 2.0}}),
        op(index=2, time=sec(4), type="ok", process=0, f="read",
           value=1),
    ], assign_indices=False)
    ds = clock_mod.history_to_datasets(h)
    assert ds["n1"] == [[1.0, 0.5], [3.0, 2.0], [4.0, 2.0]]
    assert ds["n2"] == [[1.0, -0.25], [4.0, -0.25]]


def test_short_node_names():
    got = clock_mod.short_node_names(
        ["n1.cluster.local", "n2.cluster.local"])
    assert got == ["n1", "n2"]
    assert clock_mod.short_node_names(["a", "b"]) == ["a", "b"]


def test_clock_plot_writes(tmp_path):
    test = {"name": "clock", "store_dir": str(tmp_path)}
    h = History([
        op(index=0, time=sec(1), type="info", process="nemesis",
           f="check-offsets", value=None,
           **{"clock-offsets": {"n1": 0.0, "n2": 0.1}}),
        op(index=1, time=sec(5), type="info", process="nemesis",
           f="bump", value=None, **{"clock-offsets": {"n1": 8.0}}),
    ], assign_indices=False)
    res = checker.check_safe(checker.clock_plot(), test, h)
    assert res["valid?"] is True
    assert (tmp_path / "clock-skew.png").stat().st_size > 1000


def test_clock_plot_empty_history_ok(tmp_path):
    test = {"name": "clock", "store_dir": str(tmp_path)}
    res = checker.check_safe(checker.clock_plot(), test, History([]))
    assert res["valid?"] is True
