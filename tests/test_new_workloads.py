"""End-to-end tests for the round-4 workload classes: locks (plain /
owner / fenced / reentrant / semaphore), upsert uniqueness, scheduler
run-coverage, pages, multimonotonic, lost-updates, version-divergence.

Each workload gets a healthy run (valid? True) and a seeded-bug run
that must be detected — the suite-level analog of the reference's
checker unit tests."""

from __future__ import annotations

import pytest

from jepsen_tpu import core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.workloads import (lock, lost_updates, multimonotonic,
                                  pages, scheduler, upsert,
                                  version_divergence)


def run_workload(w, client, concurrency=4, nodes=None):
    test = testing.noop_test()
    g = gen.clients(gen.stagger(0.0003, w["generator"]))
    final = w.get("final_generator")
    if final is not None:
        g = gen.phases(g, gen.clients(final))
    test.update(nodes=nodes or ["n1", "n2"], concurrency=concurrency,
                client=client, checker=w["checker"], generator=g)
    return core.run(test)


# ---------------------------------------------------------------------------
# Locks
# ---------------------------------------------------------------------------

class TestLock:
    def test_healthy_plain_lock_valid(self):
        t = run_workload(lock.lock_workload({"ops": 80}),
                         testing.LockClient(fences=False))
        assert t["results"]["valid?"] is True

    def test_healthy_owner_lock_valid(self):
        t = run_workload(lock.owner_lock_workload({"ops": 80}),
                         testing.LockClient(fences=False))
        assert t["results"]["valid?"] is True

    def test_healthy_fenced_lock_valid(self):
        t = run_workload(lock.fenced_lock_workload({"ops": 80}),
                         testing.LockClient())
        assert t["results"]["valid?"] is True

    def test_stolen_lock_detected(self):
        """A service that grants a busy lock breaks mutual exclusion."""
        t = run_workload(
            lock.owner_lock_workload({"ops": 120}),
            testing.LockClient(fences=False, steal_every=3))
        assert t["results"]["valid?"] is False

    def test_stale_fence_detected(self):
        """Steals reuse the current fence: even when mutual exclusion
        alone can't always prove it, the non-monotonic token can."""
        t = run_workload(
            lock.fenced_lock_workload({"ops": 120}),
            testing.LockClient(steal_every=3))
        assert t["results"]["valid?"] is False

    def test_healthy_reentrant_valid(self):
        t = run_workload(
            lock.reentrant_lock_workload({"ops": 80}),
            testing.LockClient(reentrant_limit=2))
        assert t["results"]["valid?"] is True

    def test_non_reentrant_service_fails_cleanly(self):
        """A non-reentrant service under the reentrant workload just
        fails nested acquires -> history stays consistent."""
        t = run_workload(
            lock.reentrant_lock_workload({"ops": 80}),
            testing.LockClient(reentrant_limit=1))
        assert t["results"]["valid?"] is True

    def test_healthy_semaphore_valid(self):
        t = run_workload(
            lock.semaphore_workload({"ops": 100, "permits": 2}),
            testing.LockClient(testing.LockState(permits=2),
                               semaphore=True))
        assert t["results"]["valid?"] is True

    def test_overgranting_semaphore_detected(self):
        """3 permits handed out by a service that promised 2."""
        t = run_workload(
            lock.semaphore_workload({"ops": 140, "permits": 2}),
            testing.LockClient(testing.LockState(permits=3),
                               semaphore=True),
            concurrency=6)
        assert t["results"]["valid?"] is False

    def test_fenced_mutex_model_unit(self):
        from jepsen_tpu.history import Op

        m = lock.FencedMutex()
        m = m.step(Op(type="invoke", process=0, f="acquire",
                      value={"fence": 5}))
        assert m.owner == 0 and m.max_fence == 5
        m2 = m.step(Op(type="invoke", process=1, f="release",
                       value=None))
        assert lock.models.is_inconsistent(m2)
        m = m.step(Op(type="invoke", process=0, f="release",
                      value=None))
        bad = m.step(Op(type="invoke", process=1, f="acquire",
                        value={"fence": 5}))
        assert lock.models.is_inconsistent(bad)
        ok = m.step(Op(type="invoke", process=1, f="acquire",
                       value={"fence": 6}))
        assert ok.owner == 1


# ---------------------------------------------------------------------------
# Upsert
# ---------------------------------------------------------------------------

class TestUpsert:
    def test_healthy_upserts_valid(self):
        t = run_workload(upsert.workload({"key_count": 6}),
                         testing.UpsertClient())
        res = t["results"]
        assert res["valid?"] is True

    def test_double_create_detected(self):
        t = run_workload(upsert.workload({"key_count": 8}),
                         testing.UpsertClient(race_every=3))
        assert t["results"]["valid?"] is False

    def test_checker_unit(self):
        from jepsen_tpu.history import Op

        ok = upsert.check_upsert([
            Op(type="ok", process=0, f="upsert", value=7),
            Op(type="ok", process=1, f="read", value=[7]),
        ])
        assert ok["valid?"] is True
        two = upsert.check_upsert([
            Op(type="ok", process=0, f="upsert", value=7),
            Op(type="ok", process=1, f="upsert", value=8),
            Op(type="ok", process=2, f="read", value=[7, 8]),
        ])
        assert two["valid?"] is False
        assert two["ok-upsert-count"] == 2


# ---------------------------------------------------------------------------
# Scheduler run-coverage
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_targets(self):
        job = {"name": 0, "start": 100.0, "interval": 50.0,
               "count": 5, "epsilon": 10.0, "duration": 5.0}
        # read at 300: finish = 285; targets at 100, 150, 200, 250
        ts = scheduler.job_targets(300.0, job)
        assert [t[0] for t in ts] == [100.0, 150.0, 200.0, 250.0]
        assert ts[0][1] == 100.0 + 10.0 + scheduler.EPSILON_FORGIVENESS
        # count caps targets even for far-future reads
        ts = scheduler.job_targets(10_000.0, job)
        assert len(ts) == 5

    def test_greedy_matching(self):
        targets = [(0.0, 10.0), (20.0, 30.0), (40.0, 50.0)]
        a, unsat = scheduler.match_targets(targets, [5.0, 22.0, 41.0])
        assert not unsat and len(a) == 3
        # one run cannot satisfy two targets
        a, unsat = scheduler.match_targets(
            [(0.0, 10.0), (5.0, 15.0)], [7.0])
        assert len(a) == 1 and len(unsat) == 1
        # overlapping windows: deadline order finds the max matching
        a, unsat = scheduler.match_targets(
            [(0.0, 100.0), (0.0, 10.0)], [8.0, 50.0])
        assert not unsat

    def test_healthy_schedule_valid(self):
        t = run_workload(scheduler.workload({"jobs": 10, "seed": 3,
                                             "stagger": 0.0005}),
                         testing.SchedulerClient())
        res = t["results"]
        assert res["valid?"] is True
        assert not res["incomplete"]

    def test_missed_runs_detected(self):
        t = run_workload(scheduler.workload({"jobs": 10, "seed": 3,
                                             "stagger": 0.0005}),
                         testing.SchedulerClient(miss_every=4))
        res = t["results"]
        assert res["valid?"] is False
        bad = [s for s in res["jobs"].values() if not s["valid?"]]
        assert bad and bad[0]["unsatisfied-targets"]

    def test_late_runs_detected(self):
        t = run_workload(scheduler.workload({"jobs": 8, "seed": 5,
                                             "stagger": 0.0005}),
                         testing.SchedulerClient(late_every=3))
        assert t["results"]["valid?"] is False

    def test_never_read_unknown(self):
        w = scheduler.workload({"jobs": 4})
        w.pop("final_generator")
        t = run_workload(w, testing.SchedulerClient())
        assert t["results"]["valid?"] == "unknown"


# ---------------------------------------------------------------------------
# Pages
# ---------------------------------------------------------------------------

class TestPages:
    def test_healthy_pages_valid(self):
        t = run_workload(
            pages.workload({"key_count": 3, "ops_per_key": 40,
                            "elements": 500, "seed": 1}),
            testing.PagesClient())
        assert t["results"]["valid?"] is True

    def test_torn_group_detected(self):
        t = run_workload(
            pages.workload({"key_count": 3, "ops_per_key": 60,
                            "elements": 500, "seed": 1}),
            testing.PagesClient(tear_every=2))
        assert t["results"]["valid?"] is False

    def test_read_errs_unit(self):
        idx = {1: frozenset({1, 2}), 2: frozenset({1, 2}),
               3: frozenset({3})}
        assert pages.read_errs(idx, {1, 2, 3}) == []
        errs = pages.read_errs(idx, {1, 3})
        assert errs == [{"expected": [1, 2], "found": [1]}]


# ---------------------------------------------------------------------------
# Multimonotonic
# ---------------------------------------------------------------------------

class TestMultimonotonic:
    def test_healthy_valid(self):
        t = run_workload(
            multimonotonic.workload({"ops": 200, "writers": 2}),
            testing.MultiRegClient(), concurrency=4)
        res = t["results"]
        assert res["valid?"] is True
        assert res["ts-order"]["valid?"] is True
        assert res["read-skew"]["valid?"] is True

    def test_stale_reads_detected(self):
        t = run_workload(
            multimonotonic.workload({"ops": 300, "writers": 2}),
            testing.MultiRegClient(stale_every=3), concurrency=4)
        assert t["results"]["ts-order"]["valid?"] is False

    def test_read_skew_checker_unit(self):
        from jepsen_tpu.history import Op

        # r1 sees x=1,y=0; r2 sees x=0,y=1: incompatible orders
        hist = [
            Op(index=0, type="ok", process=0, f="read",
               value={"ts": 1, "registers": {"x": 1, "y": 0}}),
            Op(index=1, type="ok", process=1, f="read",
               value={"ts": 2, "registers": {"x": 0, "y": 1}}),
        ]
        res = multimonotonic.check_read_skew(hist)
        assert res["valid?"] is False and res["cycles"]
        # compatible observations: no cycle
        hist2 = [
            Op(index=0, type="ok", process=0, f="read",
               value={"ts": 1, "registers": {"x": 0, "y": 0}}),
            Op(index=1, type="ok", process=1, f="read",
               value={"ts": 2, "registers": {"x": 1, "y": 1}}),
        ]
        assert multimonotonic.check_read_skew(hist2)["valid?"] is True


# ---------------------------------------------------------------------------
# Lost updates / version divergence
# ---------------------------------------------------------------------------

class TestLostUpdates:
    def test_healthy_valid(self):
        t = run_workload(
            lost_updates.workload({"key_count": 3, "group_size": 4,
                                   "ops_per_key": 40}),
            testing.VersionedSetClient())
        assert t["results"]["valid?"] is True

    def test_lost_update_detected(self):
        t = run_workload(
            lost_updates.workload({"key_count": 3, "group_size": 4,
                                   "ops_per_key": 60}),
            testing.VersionedSetClient(lose_every=5))
        assert t["results"]["valid?"] is False


class TestVersionDivergence:
    def test_healthy_valid(self):
        t = run_workload(
            version_divergence.workload({"key_count": 3,
                                         "ops_per_key": 60}),
            testing.VersionRegClient(), concurrency=6)
        res = t["results"]
        assert res["valid?"] is True
        assert any(r["versions-observed"] > 0
                   for r in res["results"].values()) \
            if "results" in res else True

    def test_divergence_detected(self):
        t = run_workload(
            version_divergence.workload({"key_count": 2,
                                         "ops_per_key": 80}),
            testing.VersionRegClient(diverge_every=4), concurrency=6)
        assert t["results"]["valid?"] is False

    def test_checker_unit(self):
        from jepsen_tpu.history import Op

        res = version_divergence.check_multiversion([
            Op(type="ok", process=0, f="read",
               value={"value": 1, "version": 3}),
            Op(type="ok", process=1, f="read",
               value={"value": 2, "version": 3}),
        ])
        assert res["valid?"] is False and res["multis"]


# ---------------------------------------------------------------------------
# CLI registry
# ---------------------------------------------------------------------------

def test_all_new_workloads_registered():
    from jepsen_tpu import __main__ as main_mod
    from jepsen_tpu import workloads

    for name in ("lock", "owner-lock", "fenced-lock", "reentrant-lock",
                 "semaphore", "upsert", "run-coverage", "pages",
                 "multimonotonic", "lost-updates",
                 "version-divergence"):
        assert name in workloads.REGISTRY
        assert name in main_mod.CLIENTS
