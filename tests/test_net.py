"""Net layer tests: exact command lines per fault, via dummy sessions.

Mirrors the reference's approach of asserting iptables/tc invocations
(jepsen/src/jepsen/net.clj:67-270); the dummy remote records every
Action so we can check both the command string and the sudo wrapper.
"""

import pytest

from jepsen_tpu import net
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.nemesis import core as nemesis


def responder(node, action):
    """Canned command output: IP resolution + device discovery."""
    cmd = action.cmd
    if cmd.startswith("getent ahostsv4"):
        host = cmd.split()[-1]
        return f"10.0.0.{host[1:]}   STREAM {host}\n10.0.0.{host[1:]}   DGRAM"
    if cmd == "ip -o link show":
        return ("1: lo: <LOOPBACK,UP> mtu 65536\n"
                "2: eth0: <BROADCAST,MULTICAST,UP> mtu 1500")
    return None


@pytest.fixture()
def test_map():
    net.clear_ip_cache()
    remote = DummyRemote(responder)
    nodes = ["n1", "n2", "n3", "n4", "n5"]
    t = {"nodes": nodes, "ssh": {}, "remote": remote}
    t["sessions"] = {n: remote.connect({"host": n}) for n in nodes}
    return t


def cmds(test, node):
    """Sudo'd command strings logged on a node's session."""
    out = []
    for a in test["sessions"][node].log:
        if not isinstance(a, tuple):
            out.append((a.cmd, a.sudo))
    return out


def clear_logs(test):
    for s in test["sessions"].values():
        s.log.clear()


def test_drop(test_map):
    net.iptables.drop(test_map, "n1", "n2")
    assert ("iptables -A INPUT -s 10.0.0.1 -j DROP -w", "root") in \
        cmds(test_map, "n2")
    assert not [c for c, _ in cmds(test_map, "n1") if "iptables" in c]


def test_heal(test_map):
    net.iptables.heal(test_map)
    for n in test_map["nodes"]:
        got = [c for c, s in cmds(test_map, n) if s == "root"]
        assert "iptables -F -w" in got
        assert "iptables -X -w" in got


def test_drop_all_fast_path(test_map):
    grudge = {"n1": {"n2", "n3"}, "n2": {"n1"}, "n3": set()}
    net.iptables.drop_all(test_map, grudge)
    assert ("iptables -A INPUT -s 10.0.0.2,10.0.0.3 -j DROP -w", "root") \
        in cmds(test_map, "n1")
    assert ("iptables -A INPUT -s 10.0.0.1 -j DROP -w", "root") in \
        cmds(test_map, "n2")
    # empty grudge entry -> no iptables call on n3
    assert not [c for c, _ in cmds(test_map, "n3") if "iptables" in c]


def test_drop_all_fallback_expands_pairs(test_map):
    """A Net without a drop_all override expands the grudge into
    (src, dst) drop calls (net.clj:26-42)."""
    calls = []

    class MinimalNet(net.Net):
        def drop(self, test, src, dest):
            calls.append((src, dest))

    MinimalNet().drop_all(test_map, {"n1": ["n2", "n3"], "n2": ["n1"]})
    assert sorted(calls) == [("n1", "n2"), ("n2", "n1"), ("n3", "n1")]


def test_slow_flaky_fast(test_map):
    net.iptables.slow(test_map)
    assert ("/sbin/tc qdisc add dev eth0 root netem delay 50ms 10ms "
            "distribution normal", "root") in cmds(test_map, "n1")
    clear_logs(test_map)
    net.iptables.slow(test_map, mean=100, variance=5,
                      distribution="pareto")
    assert ("/sbin/tc qdisc add dev eth0 root netem delay 100ms 5ms "
            "distribution pareto", "root") in cmds(test_map, "n1")
    clear_logs(test_map)
    net.iptables.flaky(test_map)
    assert ("/sbin/tc qdisc add dev eth0 root netem loss 20% 75%",
            "root") in cmds(test_map, "n2")
    clear_logs(test_map)
    net.iptables.fast(test_map)
    assert ("/sbin/tc qdisc del dev eth0 root", "root") in \
        cmds(test_map, "n3")


def test_behaviors_to_netem_defaults():
    assert net.behaviors_to_netem({"delay": {}}) == [
        "delay", "50ms", "10ms", "25%", "distribution", "normal"]
    assert net.behaviors_to_netem({"rate": {}}) == ["rate", "1mbit"]
    assert net.behaviors_to_netem({"loss": {"percent": "5%"}}) == [
        "loss", "5%", "75%"]
    # reorder pulls in default delay (net.clj:100-104)
    got = net.behaviors_to_netem({"reorder": {}})
    assert got[:6] == ["delay", "50ms", "10ms", "25%", "distribution",
                       "normal"]
    assert got[6:] == ["reorder", "20%", "75%"]


def test_shape(test_map):
    out = net.iptables.shape(test_map, ["n2"], {"delay": {}})
    assert out[0] == "shaped"
    # every node deletes its root qdisc first
    for n in test_map["nodes"]:
        assert ("/sbin/tc qdisc del dev eth0 root", "root") in \
            cmds(test_map, n)
    # non-target n1 installs prio + netem + a filter to n2
    got1 = [c for c, _ in cmds(test_map, "n1")]
    assert ("/sbin/tc qdisc add dev eth0 root handle 1: prio bands 4 "
            "priomap 1 2 2 2 1 2 0 0 1 1 1 1 1 1 1 1") in got1
    assert ("/sbin/tc qdisc add dev eth0 parent 1:4 handle 40: netem "
            "delay 50ms 10ms 25% distribution normal") in got1
    assert ("/sbin/tc filter add dev eth0 parent 1:0 protocol ip prio 3 "
            "u32 match ip dst 10.0.0.2 flowid 1:4") in got1
    # target n2 shapes traffic to everyone else
    got2 = [c for c, _ in cmds(test_map, "n2")]
    for other in ("10.0.0.1", "10.0.0.3", "10.0.0.4", "10.0.0.5"):
        assert (f"/sbin/tc filter add dev eth0 parent 1:0 protocol ip "
                f"prio 3 u32 match ip dst {other} flowid 1:4") in got2


def test_shape_no_behavior_resets(test_map):
    out = net.iptables.shape(test_map, [], {})
    assert out[0] == "reliable"
    got = [c for c, _ in cmds(test_map, "n1")]
    assert got == ["ip -o link show", "/sbin/tc qdisc del dev eth0 root"]


def test_ip_memoized(test_map):
    from jepsen_tpu import control

    with control.with_session(test_map, "n1"):
        assert net.ip("n3") == "10.0.0.3"
        assert net.ip("n3") == "10.0.0.3"
    getents = [c for c, _ in cmds(test_map, "n1")
               if c.startswith("getent")]
    assert len(getents) == 1


def test_ip_blank_raises(test_map):
    from jepsen_tpu import control

    net.clear_ip_cache()
    t = dict(test_map)
    t["remote"] = DummyRemote()  # no responder: blank getent output
    t["sessions"] = {"n1": t["remote"].connect({"host": "n1"})}
    with control.with_session(t, "n1"):
        with pytest.raises(net.BlankGetentIP):
            net.ip("n9")


def test_ipfilter_drop(test_map):
    net.ipfilter.drop(test_map, "n1", "n2")
    assert ("echo block in from n1 to any | ipf -f -", "root") in \
        cmds(test_map, "n2")


def test_partitioner_end_to_end(test_map):
    """Partitioner start/stop now actually applies grudges through the
    net layer (VERDICT round 1: 'partitions literally cannot be
    injected today')."""
    from jepsen_tpu.history import op

    test_map["net"] = net.iptables
    nem = nemesis.partition_halves().setup(test_map)
    start = op(type="info", process="nemesis", f="start", value=None)
    done = nem.invoke(test_map, start)
    assert done.value[0] == "isolated"
    # n1..n2 vs n3..n5: the majority drops the minority and vice versa
    assert ("iptables -A INPUT -s 10.0.0.3,10.0.0.4,10.0.0.5 -j DROP -w",
            "root") in cmds(test_map, "n1")
    assert ("iptables -A INPUT -s 10.0.0.1,10.0.0.2 -j DROP -w",
            "root") in cmds(test_map, "n3")
    clear_logs(test_map)
    stop = op(type="info", process="nemesis", f="stop", value=None)
    done = nem.invoke(test_map, stop)
    assert done.value == "network healed"
    assert ("iptables -F -w", "root") in cmds(test_map, "n4")
