"""VoltDB suite tests: cluster bootstrap command emission via the
dummy remote, an in-memory voltdb speaking the suite's sqlcmd batches,
clusterless end-to-end register/dirty-read runs, and the suite's
histories driven through the fleet under the durability-chaos rig
(mirrors voltdb/src/jepsen/voltdb/*.clj; doc/robustness.md)."""

import re
import threading
import time

import pytest

from jepsen_tpu import chaos as jchaos
from jepsen_tpu import control, core, independent, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.checker import models
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.fleet import client as fclient
from jepsen_tpu.fleet import server as fserver
from jepsen_tpu.history import op as make_op
from jepsen_tpu.suites import voltdb as vdb
from jepsen_tpu.tpu import certify, wgl


def responder(node, action):
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "voltdb-community-6.8"
    return None


def make_test(nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return core.prepare_test(t)


def cmds(test, node):
    return " ; ".join(a.cmd for a in test["sessions"][node].log
                      if isinstance(a, Action))


class TestDB:
    def test_setup_creates_cluster_and_schema_once(self):
        test = make_test()
        db = vdb.VoltdbDB()
        control.on_nodes(test, lambda t, n: db.setup(t, n))
        got1, got2 = cmds(test, "n1"), cmds(test, "n2")
        for got in (got1, got2):
            assert "openjdk-8-jdk" in got
            assert "voltdb-community-6.8.tar.gz" in got
            assert "create --deployment /opt/voltdb/deployment.xml" \
                in got
            assert "--host n1" in got  # everyone meshes on primary
            # 3 nodes tolerate a minority: kfactor 1
            assert 'kfactor="1"' in got
            assert 'synchronous="true"' in got  # command logging
        # schema once, on the primary
        assert "CREATE TABLE registers" in got1
        assert "PARTITION TABLE registers" in got1
        assert "CREATE TABLE" not in got2

    def test_explicit_kfactor_wins(self):
        test = make_test()
        db = vdb.VoltdbDB(kfactor=2)
        control.on_nodes(test, lambda t, n: db.setup(t, n))
        assert 'kfactor="2"' in cmds(test, "n1")

    def test_teardown_removes_state(self):
        test = make_test()
        db = vdb.VoltdbDB()
        with control.with_session(test, "n2"):
            db.teardown(test, "n2")
        got = cmds(test, "n2")
        assert "org.voltdb.VoltDB" in got
        assert "rm -rf /opt/voltdb" in got

    def test_restart_rejoins(self):
        test = make_test()
        db = vdb.VoltdbDB()
        with control.with_session(test, "n2"):
            db.start(test, "n2")
        got = cmds(test, "n2")
        assert "create --deployment" in got and "--host n1" in got


# ---------------------------------------------------------------------------
# in-memory voltdb
# ---------------------------------------------------------------------------

class FakeVolt:
    """In-memory store executing the suite's sqlcmd batches atomically
    — a perfectly linearizable 'voltdb'. DML answers with its
    modified-tuple count like the real sqlcmd output."""

    def __init__(self):
        self.lock = threading.Lock()
        self.registers: dict = {}
        self.dirty: set = set()

    def run(self, sql: str) -> str:
        with self.lock:
            out = []
            for stmt in filter(None,
                               (s.strip() for s in sql.split(";"))):
                line = self._stmt(stmt)
                if line is not None:
                    out.append(line)
            return "\n".join(out)

    def _stmt(self, s):
        m = re.match(r"SELECT 'v=' \|\| CAST\(value AS VARCHAR\) "
                     r"FROM registers WHERE id = (\d+)", s)
        if m:
            v = self.registers.get(int(m.group(1)))
            return None if v is None else f"v={v}"
        m = re.match(r"UPSERT INTO registers \(id, value\) VALUES "
                     r"\((\d+), (-?\d+)\)", s)
        if m:
            self.registers[int(m.group(1))] = int(m.group(2))
            return "1"
        m = re.match(r"UPDATE registers SET value = (-?\d+) WHERE "
                     r"id = (\d+) AND value = (-?\d+)", s)
        if m:
            new, k, old = (int(m.group(1)), int(m.group(2)),
                           int(m.group(3)))
            if self.registers.get(k) == old:
                self.registers[k] = new
                return "1"
            return "0"
        m = re.match(r"INSERT INTO dirty_reads \(id\) VALUES "
                     r"\((\d+)\)", s)
        if m:
            self.dirty.add(int(m.group(1)))
            return "1"
        m = re.match(r"SELECT 'v=' \|\| CAST\(id AS VARCHAR\) FROM "
                     r"dirty_reads WHERE id = (\d+)", s)
        if m:
            k = int(m.group(1))
            return f"v={k}" if k in self.dirty else None
        if s.startswith("SELECT 'i=' || CAST(id AS VARCHAR) "
                        "FROM dirty_reads"):
            return "\n".join(f"i={k}" for k in sorted(self.dirty))
        raise AssertionError(f"fake voltdb can't parse: {s!r}")


class FakeSqlFactory:
    def __init__(self, state=None):
        self.state = state or FakeVolt()

    def __call__(self, test, node, timeout=10.0):
        factory = self

        class _S:
            def run(self, sql):
                return factory.state.run(sql)

            def close(self):
                pass

        return _S()


def run_register(opts, factory):
    w = vdb.register_workload(opts)
    w["client"].sql_factory = factory
    test = testing.noop_test()
    test.update(nodes=["n1", "n2"],
                concurrency=opts.get("concurrency", 6),
                client=w["client"], checker=w["checker"],
                generator=gen.clients(
                    gen.stagger(0.0004, w["generator"])))
    return core.run(test)


class TestEndToEnd:
    def test_register_valid(self):
        test = run_register({"concurrency": 6, "keys": 2,
                             "ops_per_key": 60, "seed": 7},
                            FakeSqlFactory())
        assert test["results"]["valid?"] is True
        fs = {op.f for op in test["history"]}
        assert fs == {"read", "write", "cas"}

    def test_phantom_read_detected(self):
        """A value outside the 0..4 write domain returned on late
        reads must fail the linearizable checker."""

        class PhantomVolt(FakeVolt):
            def __init__(self):
                super().__init__()
                self.reads = 0

            def _stmt(self, s):
                if s.startswith("SELECT 'v='") and "registers" in s:
                    self.reads += 1
                    if self.reads >= 20:
                        return "v=99"
                return super()._stmt(s)

        test = run_register({"concurrency": 4, "keys": 1,
                             "ops_per_key": 80, "seed": 3},
                            FakeSqlFactory(PhantomVolt()))
        assert test["results"]["valid?"] is False

    def _run_dirty(self, factory, ops=120):
        w = vdb.dirty_read_workload({"concurrency": 6, "ops": ops,
                                     "seed": 5})
        w["client"].sql_factory = factory
        test = testing.noop_test()
        test.update(nodes=["n1", "n2"], concurrency=6,
                    client=w["client"], checker=w["checker"],
                    generator=gen.phases(
                        gen.clients(gen.stagger(
                            0.0004, w["generator"])),
                        gen.clients(w["final_generator"])))
        return core.run(test)

    def test_dirty_read_valid(self):
        test = self._run_dirty(FakeSqlFactory())
        res = test["results"]
        assert res["valid?"] is True
        assert res["strong-read-count"] > 0

    def test_dirty_read_detected(self):
        """An insert whose ack was lost but whose row leaked to
        readers — and which no strong read contains — is the dirty
        read the checker must flag."""

        class LeakyVolt(FakeVolt):
            def _stmt(self, s):
                m = re.match(r"INSERT INTO dirty_reads \(id\) "
                             r"VALUES \((\d+)\)", s)
                if m:
                    # visible to probes, never acked, and dropped
                    # before the strong reads (an aborted txn's
                    # uncommitted row)
                    self.dirty.add(int(m.group(1)))
                    return "0"
                if s.startswith("SELECT 'i='"):
                    return None  # strong reads: nothing committed
                return super()._stmt(s)

        test = self._run_dirty(FakeSqlFactory(LeakyVolt()))
        res = test["results"]
        assert res["valid?"] is False
        assert res["dirty-count"] > 0


class TestCli:
    def test_registry_entry(self):
        from jepsen_tpu import suites

        assert suites.SUITES["voltdb"] == "jepsen_tpu.suites.voltdb"
        assert suites.load("voltdb") is vdb

    def test_test_map_shape(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                "ssh": {"dummy": True}, "time_limit": 5,
                "workload": "register", "seed": 1}
        test = vdb.voltdb_test(opts)
        assert test["name"] == "voltdb-register"
        assert isinstance(test["db"], vdb.VoltdbDB)

    def test_dirty_read_final_phase_present(self):
        opts = {"nodes": ["n1"], "concurrency": 4,
                "ssh": {"dummy": True}, "workload": "dirty-read"}
        test = vdb.voltdb_test(opts)
        assert test["name"] == "voltdb-dirty-read"

    def test_count_parser(self):
        assert vdb._count("1\n") == 1
        assert vdb._count("(Returned 1 rows)\n0\n") == 0
        assert vdb._count("v=3\n") == 0


# ---------------------------------------------------------------------------
# the suite under the fleet's chaos/quarantine settings
# ---------------------------------------------------------------------------

def suite_register_history(seed=11, ops_per_key=80):
    """A cas-register history produced by the SUITE's own workload
    (key 0's subhistory, re-indexed) — the bridge from suite runs to
    the fleet's streaming checkers."""
    test = run_register({"concurrency": 6, "keys": 1,
                         "ops_per_key": ops_per_key, "seed": seed},
                        FakeSqlFactory())
    ops = []
    for o in test["history"]:
        if o.f not in ("read", "write", "cas"):
            continue
        if independent.key_(o.value) != 0:
            continue
        ops.append(make_op(
            index=len(ops), time=len(ops), type=o.type,
            process=o.process, f=o.f,
            value=independent.value_(o.value)))
    return ops


class TestUnderChaos:
    def test_fleet_verdict_matches_solo_under_durability_chaos(
            self, tmp_path):
        """The suite's history streamed through the fleet while the
        durability-chaos rig tears checkpoints and fails WAL writes:
        the server sheds (never crashes), the run completes through
        client retries, and the verdict matches the solo check. The
        fleet breaker stays closed and nothing gets quarantined —
        durability faults are not device failures."""
        hist = suite_register_history()
        solo = wgl.analysis(models.cas_register(), hist, certify=True)
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            with jchaos.DurabilityChaos(
                    seed=9,
                    wal_rates={"enospc": 0.25, "eio": 0.1},
                    ckpt_rates={"torn-ckpt": 0.3, "eio": 0.2}):
                c = fclient.FleetClient(srv.addr, "volt", "r0",
                                        model="cas-register")
                deadline = time.monotonic() + 120
                i = 0
                while i < len(hist):
                    try:
                        c.send_chunk(hist[i:i + 40])
                        i += 40
                    except fclient.FleetError:
                        if time.monotonic() > deadline:
                            raise
                        time.sleep(0.1)
                env = c.finish(timeout_s=60.0)
                c.close()
            result = env["result"]
            assert result["valid?"] == solo["valid?"]
            certify.validate(hist, result["certificate"])
            st = srv.stats()
            assert st["scheduler"]["quarantine"] == []
            assert st["scheduler"]["breaker_open"] is False
        finally:
            srv.stop()

    def test_poison_neighbor_cannot_starve_suite_run(
            self, tmp_path, monkeypatch):
        """The suite's run shares the fleet with a poison tenant whose
        history kills every device launch it rides in: attribution
        quarantines the poison run to the solo host lane, the voltdb
        verdict is unaffected, and the fleet breaker stays closed."""
        hist = suite_register_history(seed=13)
        solo = wgl.analysis(models.cas_register(), hist, certify=True)
        # the poison is marked by a sentinel value: wire round-trips
        # rebuild ops server-side, so identity can't tag it
        MARK = 777777
        poison = []
        for f, v in [("write", MARK), ("read", MARK)] * 10:
            poison.append(make_op(
                index=len(poison), time=len(poison), type="invoke",
                process=0, f=f, value=v if f == "write" else None))
            poison.append(make_op(
                index=len(poison), time=len(poison), type="ok",
                process=0, f=f, value=v))
        real = wgl.analysis_batch_streamed

        def selective(model, hists, **kw):
            for h in hists:
                if any(o.f == "write" and o.value == MARK
                       for o in h):
                    raise RuntimeError("injected poison launch death")
            return real(model, hists, **kw)

        monkeypatch.setattr(wgl, "analysis_batch_streamed", selective)
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            cp = fclient.FleetClient(srv.addr, "poison", "rbad",
                                     model="cas-register")
            cp.send_chunk(poison)
            cv = fclient.FleetClient(srv.addr, "volt", "r1",
                                     model="cas-register")
            for i in range(0, len(hist), 40):
                cv.send_chunk(hist[i:i + 40])
            envp = cp.finish(timeout_s=120.0)
            envv = cv.finish(timeout_s=120.0)
            cp.close()
            cv.close()
            assert envv["result"]["valid?"] == solo["valid?"]
            certify.validate(hist, envv["result"]["certificate"])
            # the poison run still got a verdict — from the host lane
            assert envp["result"]["valid?"] is True
            st = srv.stats()["scheduler"]
            assert [q["run"] for q in st["quarantine"]] == ["rbad"]
            assert st["breaker_open"] is False
        finally:
            srv.stop()
