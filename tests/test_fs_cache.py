"""fs-cache tests: atomic writes, typed load/save, remote deploy over
the dummy remote (mirror jepsen/src/jepsen/fs_cache.clj)."""

import threading

import pytest

from jepsen_tpu import control, fs_cache, testing
from jepsen_tpu.control.core import Action
from jepsen_tpu.control.dummy import DummyRemote


@pytest.fixture(autouse=True)
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_CACHE_DIR", str(tmp_path / "cache"))
    yield


def test_string_roundtrip():
    assert not fs_cache.cached_p(["foo", 1])
    assert fs_cache.load_string(["foo", 1]) is None
    fs_cache.save_string("hello", ["foo", 1])
    assert fs_cache.cached_p(["foo", 1])
    assert fs_cache.load_string(["foo", 1]) == "hello"


def test_data_roundtrip():
    fs_cache.save_data({"a": [1, 2], "b": None}, ["db", "license"])
    assert fs_cache.load_data(["db", "license"]) == {"a": [1, 2],
                                                    "b": None}


def test_path_encoding_weird_parts():
    fs_cache.save_string("x", ["a/b", True, 3, None])
    assert fs_cache.load_string(["a/b", True, 3, None]) == "x"
    # slash must not escape the cache dir
    f = fs_cache.file(["a/b"])
    assert "a%2Fb" in str(f)


def test_file_roundtrip(tmp_path):
    src = tmp_path / "artifact.bin"
    src.write_bytes(b"\x00\x01payload")
    fs_cache.save_file(src, ["bin", "v1"])
    got = fs_cache.load_file(["bin", "v1"])
    assert got is not None and got.read_bytes() == b"\x00\x01payload"


def test_atomic_write_no_partial_on_error(tmp_path):
    class Boom(Exception):
        pass

    with pytest.raises(Boom):
        with fs_cache._atomic(fs_cache.file(["x"])) as tmp:
            tmp.write_text("partial")
            raise Boom()
    assert not fs_cache.cached_p(["x"])


def test_deploy_remote(tmp_path):
    src = tmp_path / "bin"
    src.write_text("binary!")
    fs_cache.save_file(src, ["tool"])
    remote = DummyRemote()
    test = testing.noop_test()
    test.update(nodes=["n1"],
                remote=remote,
                sessions={"n1": remote.connect({"host": "n1"})})
    with control.with_session(test, "n1"):
        fs_cache.deploy_remote(["tool"], "/opt/bin/tool")
    log = test["sessions"]["n1"].log
    cmds = [a.cmd for a in log if isinstance(a, Action)]
    assert any("rm -rf /opt/bin/tool" in c for c in cmds)
    assert any("mkdir -p /opt/bin" in c for c in cmds)
    uploads = [e for e in log if isinstance(e, tuple) and e[0] == "upload"]
    assert uploads and uploads[0][2] == "/opt/bin/tool"


def test_deploy_uncached_raises():
    with pytest.raises(RuntimeError):
        fs_cache.deploy_remote(["nope"], "/opt/x")


def test_deploy_suspicious_path_raises(tmp_path):
    src = tmp_path / "f"
    src.write_text("x")
    fs_cache.save_file(src, ["f"])
    with pytest.raises(ValueError):
        fs_cache.deploy_remote(["f"], "/etc")


def test_locking_serializes():
    order = []

    def worker(i):
        with fs_cache.locking(["expensive"]):
            order.append(("in", i))
            order.append(("out", i))

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # never two 'in's without an 'out' between them
    depth = 0
    for kind, _ in order:
        depth += 1 if kind == "in" else -1
        assert 0 <= depth <= 1


class TestReviewRegressions:
    def test_dotdot_cannot_escape_cache(self):
        fs_cache.save_string("x", ["..", "evil"])
        f = fs_cache.file(["..", "evil"])
        base = fs_cache._base().resolve()
        assert base in f.resolve().parents

    def test_relative_deploy_path_rejected(self, tmp_path):
        src = tmp_path / "f"
        src.write_text("x")
        fs_cache.save_file(src, ["g"])
        with pytest.raises(ValueError):
            fs_cache.deploy_remote(["g"], "tmp/sub/file")

    def test_scalar_and_list_paths_share_a_lock(self):
        import time

        order = []

        def one(spelling):
            with fs_cache.locking(spelling):
                order.append("in")
                time.sleep(0.01)
                order.append("out")

        ts = [threading.Thread(target=one, args=(s,))
              for s in ("same", ["same"])]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert order == ["in", "out", "in", "out"]

    def test_save_data_rejects_non_json(self):
        from pathlib import Path

        with pytest.raises(TypeError):
            fs_cache.save_data({"v": Path("/x")}, ["bad"])
        assert not fs_cache.cached_p(["bad"])
