"""RethinkDB suite tests: registry, DB command emission via the dummy
remote, query-reply classification, and clusterless end-to-end
document-CAS runs (mirrors aphyr/jepsen rethinkdb document.clj)."""

import threading

from jepsen_tpu import control, core, suites, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import rethinkdb as rdb


class TestRegistry:
    def test_rethinkdb_registered(self):
        assert "rethinkdb" in suites.SUITES
        assert suites.load("rethinkdb") is rdb


class TestDB:
    def test_setup_commands(self):
        remote = DummyRemote()
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"], remote=remote,
                    sessions={n: remote.connect({"host": n})
                              for n in ["n1", "n2", "n3"]})
        db = rdb.RethinkDB()
        with control.with_session(test, "n2"):
            db.setup(test, "n2")
        got = " ; ".join(a.cmd for a in test["sessions"]["n2"].log
                         if isinstance(a, Action))
        assert "rethinkdb" in got
        assert "service rethinkdb restart" in got
        # the uploaded query helper, and the conf written via the
        # control plane
        assert rdb.QUERY in got
        assert rdb.CONF in got

    def test_conf_joins_every_other_node(self):
        test = {"nodes": ["n1", "n2", "n3"]}
        body = rdb.conf_body(test, "n2")
        assert f"join=n1:{rdb.CLUSTER_PORT}" in body
        assert f"join=n3:{rdb.CLUSTER_PORT}" in body
        assert f"join=n2:{rdb.CLUSTER_PORT}" not in body
        assert "server-name=n2" in body

    def test_setup_primary_passes_acks_and_replicas(self):
        remote = DummyRemote()
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"], remote=remote,
                    sessions={n: remote.connect({"host": n})
                              for n in ["n1", "n2", "n3"]})
        db = rdb.RethinkDB(write_acks="single", read_mode="single")
        db.setup_primary(test, "n1")
        got = " ; ".join(a.cmd for a in test["sessions"]["n1"].log
                         if isinstance(a, Action))
        assert "setup single single 3" in got


class FakeRethink:
    """The single document, speaking the query helper's reply
    protocol, atomically under a lock."""

    def __init__(self):
        self.lock = threading.Lock()
        self.val = None

    def run(self, *args):
        op = args[0]
        with self.lock:
            if op == "read":
                return "NONE" if self.val is None \
                    else f"VAL {self.val}"
            if op == "write":
                self.val = int(args[3])
                return "OK"
            if op == "cas":
                old, new = int(args[3]), int(args[4])
                if self.val == old:
                    self.val = new
                    return "CAS 1"
                return "CAS 0"
            raise AssertionError(f"unexpected {args}")


class FakeCliFactory:
    def __init__(self, state=None):
        self.state = state or FakeRethink()

    def __call__(self, test, node, timeout=10.0):
        factory = self

        class _C:
            def run(self, *args):
                return factory.state.run(*args)

            def close(self):
                pass

        return _C()


def run_register(opts, factory):
    w = rdb.register_workload(opts)
    w["client"].cli_factory = factory
    test = testing.noop_test()
    test.update(nodes=["n1", "n2"],
                concurrency=opts.get("concurrency", 4),
                client=w["client"], checker=w["checker"],
                generator=gen.clients(
                    gen.stagger(0.0004, w["generator"])))
    return core.run(test)


class TestEndToEnd:
    def test_register_valid(self):
        test = run_register({"ops": 150, "seed": 3},
                            FakeCliFactory())
        assert test["results"]["valid?"] is True
        # the one class this checker decides is explicitly tagged
        assert test["results"]["anomaly-classes"][
            "nonlinearizable"] == "clean"

    def test_register_detects_stale_read(self):
        class Stale(FakeRethink):
            def __init__(self):
                super().__init__()
                self.reads = 0

            def run(self, *args):
                if args[0] == "read":
                    self.reads += 1
                    if self.reads >= 20:
                        return "VAL 99"  # never written
                return super().run(*args)

        test = run_register({"ops": 150, "seed": 3},
                            FakeCliFactory(Stale()))
        assert test["results"]["valid?"] is False


class TestClientErrors:
    def _client(self, factory):
        return rdb.RethinkCasClient(factory).open({}, "n1")

    def test_cas_precondition_failure_is_definite_fail(self):
        c = self._client(FakeCliFactory())
        op = Op(index=0, time=0, type="invoke", process=0, f="cas",
                value=[1, 2])
        assert c.invoke({}, op).type == "fail"

    def test_opaque_cas_error_reply_is_indeterminate(self):
        """The query helper routes non-abort update errors as ERR (a
        cas whose acks failed MAY have applied) — the client must
        classify them info, never a definite CAS-0 fail."""
        class AckError:
            def __call__(self, test, node, timeout=10.0):
                class _C:
                    def run(self, *args):
                        return ("ERR Write acks not satisfied: "
                                "1 of 2 acks received")

                    def close(self):
                        pass

                return _C()

        c = self._client(AckError())
        op = Op(index=0, time=0, type="invoke", process=0, f="cas",
                value=[1, 2])
        assert c.invoke({}, op).type == "info"

    def test_query_script_cas_error_branches(self):
        """The uploaded helper's source keeps the abort/indeterminate
        split: only OUR precondition abort prints CAS 0."""
        assert '"abort" in err' in rdb.QUERY_SCRIPT
        assert 'print("ERR %s" % (err or "cas error"))' in \
            rdb.QUERY_SCRIPT

    def test_err_reply_lost_primary_is_definite_fail_for_write(self):
        class Lost:
            def __call__(self, test, node, timeout=10.0):
                class _C:
                    def run(self, *args):
                        return ("ERR Cannot perform write: lost "
                                "contact with primary replica")

                    def close(self):
                        pass

                return _C()

        c = self._client(Lost())
        op = Op(index=0, time=0, type="invoke", process=0, f="write",
                value=3)
        assert c.invoke({}, op).type == "fail"

    def test_opaque_transport_error_on_write_is_indeterminate(self):
        class Dying:
            def __call__(self, test, node, timeout=10.0):
                class _C:
                    def run(self, *args):
                        from jepsen_tpu.control.core import RemoteError

                        raise RemoteError("broken pipe", exit=1,
                                          out="", err="broken pipe",
                                          cmd="write", node=node)

                    def close(self):
                        pass

                return _C()

        c = self._client(Dying())
        op = Op(index=0, time=0, type="invoke", process=0, f="write",
                value=3)
        assert c.invoke({}, op).type == "info"

    def test_any_error_on_read_is_definite_fail(self):
        class Dying:
            def __call__(self, test, node, timeout=10.0):
                class _C:
                    def run(self, *args):
                        from jepsen_tpu.control.core import RemoteError

                        raise RemoteError("timeout", exit=1, out="",
                                          err="timed out", cmd="read",
                                          node=node)

                    def close(self):
                        pass

                return _C()

        c = self._client(Dying())
        op = Op(index=0, time=0, type="invoke", process=0, f="read",
                value=None)
        assert c.invoke({}, op).type == "fail"
