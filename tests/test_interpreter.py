"""Interpreter + clusterless end-to-end tests.

Mirrors jepsen/test/jepsen/generator/interpreter_test.clj (worker
semantics, crash -> new process) and core_test.clj (full lifecycle against
an in-memory DB with a dummy remote).
"""

import threading
import time

import pytest

from jepsen_tpu import checker as jchecker
from jepsen_tpu import client as jclient
from jepsen_tpu import core, interpreter
from jepsen_tpu import generator as gen
from jepsen_tpu import testing
from jepsen_tpu import util
from jepsen_tpu.checker import models
from jepsen_tpu.history import History


def base_test(**kw):
    t = testing.noop_test()
    t["concurrency"] = 4
    t.update(kw)
    return t


def run_interp(test):
    util.init_relative_time()
    return interpreter.run(dict(test))


def test_basic_run_produces_history():
    n = 50
    t = base_test(
        client=jclient.noop,
        generator=gen.clients(gen.limit(n, gen.repeat({"f": "write",
                                                       "value": 1}))))
    t = run_interp(t)
    hist = t["history"]
    assert len(hist) == 2 * n
    invokes = [o for o in hist if o.type == "invoke"]
    oks = [o for o in hist if o.type == "ok"]
    assert len(invokes) == n
    assert len(oks) == n
    # Dense indices in order.
    assert [o.index for o in hist] == list(range(2 * n))
    # Times are monotonic.
    times = [o.time for o in hist]
    assert times == sorted(times)
    # Every invocation pairs with a completion.
    pair = hist.pair_index()
    assert all(pair[o.index] >= 0 for o in invokes)


class CrashingClient(jclient.Client):
    def open(self, test, node):
        return self

    def invoke(self, test, op):
        raise RuntimeError("kaboom")


def test_crash_becomes_info_and_new_process():
    n = 6
    t = base_test(
        concurrency=1,
        client=CrashingClient(),
        generator=gen.on_threads({0}, gen.limit(
            n, gen.repeat({"f": "write", "value": 1}))))
    t = run_interp(t)
    hist = t["history"]
    infos = [o for o in hist if o.type == "info"]
    assert len(infos) == n
    # Each crash reincarnates the process: 0, 1, 2, ... (int thread count
    # 1 => process increments by 1 each time).
    procs = [o.process for o in hist if o.type == "invoke"]
    assert procs == sorted(set(procs))
    assert len(set(procs)) == n


def test_sleep_and_log_not_in_history():
    t = base_test(
        client=jclient.noop,
        generator=gen.clients([gen.log("hello"),
                               {"f": "write", "value": 1},
                               gen.once(gen.sleep(0.01))]))
    t = run_interp(t)
    hist = t["history"]
    assert all(o.type not in ("sleep", "log") for o in hist)
    assert len(hist) == 2


def test_nemesis_ops_routed():
    class Nem(testing.jnemesis.Nemesis):
        def __init__(self):
            self.seen = []

        def invoke(self, test, op):
            self.seen.append(op.f)
            return op.copy(type="info")

    nem = Nem()
    t = base_test(
        client=jclient.noop,
        nemesis=nem,
        generator=gen.nemesis(
            gen.limit(2, [{"f": "start"}, {"f": "stop"}])))
    t = run_interp(t)
    assert nem.seen == ["start", "stop"]
    nem_ops = [o for o in t["history"] if o.process == "nemesis"]
    assert len(nem_ops) == 4  # 2 invokes + 2 infos


def test_interpreter_throughput_floor():
    # Reference asserts >10k ops/s on the JVM (interpreter_test.clj:86-88);
    # measured here: ~22.8k ops/s with dummy clients on a fast box.
    # Floor at 2k, best of 3 attempts: the CI box throttles CPU by
    # shares and shows sustained windows around ~3.3k ops/s on
    # otherwise-idle runs, so the floor polices only order-of-
    # magnitude hot-loop regressions (accidental O(n^2), stray
    # sleeps), which slow EVERY attempt well below it.
    n = 2000
    run_interp(base_test(concurrency=10, client=jclient.noop,
                         generator=gen.clients(
                             gen.limit(50, gen.repeat({"f": "w"})))))
    rates = []
    for _attempt in range(3):
        t = base_test(
            concurrency=10,
            client=jclient.noop,
            generator=gen.clients(gen.limit(n, gen.repeat({"f": "w"}))))
        t0 = time.monotonic()
        t = run_interp(t)
        dt = time.monotonic() - t0
        assert len(t["history"]) == 2 * n
        rates.append(n / dt)
        if rates[-1] > 2000:
            break
    assert max(rates) > 2000, \
        f"interpreter rates {[f'{r:.0f}' for r in rates]} ops/s too slow"


def test_generator_only_rate_floor():
    # generator.clj:69-70: "realistic generator tests yield rates over
    # 20,000 operations/sec" single-threaded. Drive the pure-generator
    # pipeline (fill_in -> op -> update) without an interpreter and
    # assert the same order of magnitude. Floor at 5k, best of 3
    # (see the interpreter floor above for the CI-box rationale).
    from jepsen_tpu.generator import test_support

    n = 20_000
    ctx = test_support.n_plus_nemesis_context(10)
    rates = []
    for _attempt in range(3):
        g = gen.clients(gen.limit(
            n, gen.stagger(1e-9, gen.repeat({"f": "w", "value": 1}))))
        t0 = time.monotonic()
        hist = test_support.quick_ops(g, ctx=ctx)
        dt = time.monotonic() - t0
        assert len(hist) >= n
        rates.append(n / dt)
        if rates[-1] > 5000:
            break
    assert max(rates) > 5000, \
        f"generator rates {[f'{r:.0f}' for r in rates]} ops/s too slow"


def test_core_run_cas_register_e2e():
    state = testing.AtomState()
    meta_log: list = []
    import random

    # Narrow value range + plenty of attempts: cas success depends on
    # concurrent interleaving, so the stats checker's one-ok-per-f
    # requirement must be met with overwhelming probability
    # ((2/3)^~45 chance of all-cas-fail), not by luck.
    rng = random.Random(42)

    def rand_op():
        r = rng.random()
        if r < 0.4:
            return {"f": "read"}
        if r < 0.7:
            return {"f": "write", "value": rng.randint(0, 2)}
        return {"f": "cas", "value": [rng.randint(0, 2),
                                      rng.randint(0, 2)]}

    t = base_test(
        nodes=["n1", "n2", "n3"],
        concurrency=4,
        db=testing.AtomDB(state),
        client=testing.AtomClient(state, meta_log),
        checker=jchecker.compose({
            "stats": jchecker.stats(),
            "optimism": jchecker.unbridled_optimism()}),
        generator=gen.clients(gen.limit(150, lambda: rand_op())))
    t = core.run(t)
    res = t["results"]
    assert res["valid?"] is True, res
    assert res["stats"]["ok-count"] > 0
    hist = t["history"]
    assert len(hist) == 300
    # Client lifecycle was respected.
    assert "open" in meta_log and "setup" in meta_log
    assert "teardown" in meta_log and "close" in meta_log


def test_checker_stats_by_f():
    ops = []
    idx = 0
    for i in range(10):
        ops.append(dict(index=idx, time=i * 10, type="invoke", process=0,
                        f="read", value=None))
        idx += 1
        ops.append(dict(index=idx, time=i * 10 + 5,
                        type="ok" if i % 2 == 0 else "fail",
                        process=0, f="read", value=1))
        idx += 1
    res = jchecker.check(jchecker.stats(), {}, History(ops))
    assert res["valid?"] is True
    assert res["ok-count"] == 5
    assert res["fail-count"] == 5
    assert res["by-f"]["read"]["count"] == 10
