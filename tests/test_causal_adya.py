"""Causal, causal-reverse, and adya G2 workload tests: model
semantics on literal histories plus clusterless end-to-end runs with
correct and broken in-memory clients (mirror
jepsen/src/jepsen/tests/causal.clj, causal_reverse.clj, adya.clj)."""

from jepsen_tpu import core, independent, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.history import History, op
from jepsen_tpu.workloads import adya, causal, causal_reverse


def H(*events):
    """(type, f, value, position, link) tuples -> ok-only history."""
    return History([op(type=t, process=0, f=f, value=v, position=p,
                       link=lk)
                    for t, f, v, p, lk in events])


class TestCausalModel:
    def test_valid_causal_order(self):
        h = H(("ok", "read-init", 0, 1, "init"),
              ("ok", "write", 1, 2, 1),
              ("ok", "read", 1, 3, 2),
              ("ok", "write", 2, 4, 3),
              ("ok", "read", 2, 5, 4))
        res = causal.check().check({}, h, {})
        assert res["valid?"] is True, res

    def test_broken_link(self):
        h = H(("ok", "read-init", 0, 1, "init"),
              ("ok", "write", 1, 2, 99))  # links to unseen position
        res = causal.check().check({}, h, {})
        assert res["valid?"] is False
        assert "Cannot link" in res["error"]

    def test_write_skips_counter(self):
        h = H(("ok", "read-init", 0, 1, "init"),
              ("ok", "write", 2, 2, 1))  # expected 1
        res = causal.check().check({}, h, {})
        assert res["valid?"] is False
        assert "expected value 1" in res["error"]

    def test_stale_read(self):
        h = H(("ok", "read-init", 0, 1, "init"),
              ("ok", "write", 1, 2, 1),
              ("ok", "read", 0, 3, 2))  # reads old value
        res = causal.check().check({}, h, {})
        assert res["valid?"] is False

    def test_read_init_nonzero(self):
        h = H(("ok", "read-init", 7, 1, "init"),)
        res = causal.check().check({}, h, {})
        assert res["valid?"] is False
        assert "init value" in res["error"]


CausalClient = testing.CausalClient  # promoted to the library


class TestCausalEndToEnd:
    def _run(self, client):
        w = causal.workload({"keys": [0, 1, 2]})
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=2, client=client,
                    checker=w["checker"],
                    generator=gen.clients(w["generator"]))
        return core.run(test)

    def test_valid(self):
        t = self._run(CausalClient())
        assert t["results"]["valid?"] is True, t["results"]

    def test_lost_write_detected(self):
        t = self._run(CausalClient(lose_write=True))
        assert t["results"]["valid?"] is False


class TestCausalReverse:
    def W(self, *events):
        return History([op(type=t, process=p, f=f, value=v)
                        for t, p, f, v in events])

    def test_valid_order(self):
        h = self.W(("invoke", 0, "write", 1), ("ok", 0, "write", 1),
                   ("invoke", 1, "write", 2), ("ok", 1, "write", 2),
                   ("invoke", 2, "read", None),
                   ("ok", 2, "read", [1, 2]))
        res = causal_reverse.checker().check({}, h, {})
        assert res["valid?"] is True, res

    def test_t2_without_t1(self):
        # write 1 acked before write 2 invoked; a read sees 2 but not 1
        h = self.W(("invoke", 0, "write", 1), ("ok", 0, "write", 1),
                   ("invoke", 1, "write", 2), ("ok", 1, "write", 2),
                   ("invoke", 2, "read", None),
                   ("ok", 2, "read", [2]))
        res = causal_reverse.checker().check({}, h, {})
        assert res["valid?"] is False
        assert res["errors"][0]["missing"] == [1]

    def test_concurrent_writes_not_flagged(self):
        # both writes in flight together: no precedence either way
        h = self.W(("invoke", 0, "write", 1), ("invoke", 1, "write", 2),
                   ("ok", 0, "write", 1), ("ok", 1, "write", 2),
                   ("invoke", 2, "read", None),
                   ("ok", 2, "read", [2]))
        res = causal_reverse.checker().check({}, h, {})
        assert res["valid?"] is True, res


SetPerKeyClient = testing.PerKeySetClient  # promoted to the library


class TestCausalReverseEndToEnd:
    def _run(self, client):
        w = causal_reverse.workload({"keys": [0, 1],
                                     "per-key-limit": 40})
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=4, client=client,
                    checker=w["checker"],
                    generator=gen.clients(w["generator"]))
        return core.run(test)

    def test_valid(self):
        t = self._run(SetPerKeyClient())
        assert t["results"]["valid?"] is True, t["results"]

    def test_reordered_visibility_detected(self):
        t = self._run(SetPerKeyClient(hide_first=True))
        assert t["results"]["valid?"] is False


G2Client = testing.G2Client  # promoted to the library


class TestAdyaG2:
    def test_checker_literal(self):
        t = independent.ktuple
        h = History([
            op(type="invoke", process=0, f="insert", value=t(1, [None, 1])),
            op(type="ok", process=0, f="insert", value=t(1, [None, 1])),
            op(type="invoke", process=1, f="insert", value=t(1, [2, None])),
            op(type="ok", process=1, f="insert", value=t(1, [2, None]))])
        res = adya.g2_checker().check({}, h, {})
        assert res["valid?"] is False
        assert res["illegal"] == {1: 2}
        h2 = History([
            op(type="invoke", process=0, f="insert", value=t(1, [None, 1])),
            op(type="ok", process=0, f="insert", value=t(1, [None, 1])),
            op(type="invoke", process=1, f="insert", value=t(1, [2, None])),
            op(type="fail", process=1, f="insert", value=t(1, [2, None]))])
        res = adya.g2_checker().check({}, h2, {})
        assert res["valid?"] is True
        assert res["legal-count"] == 1

    def _run(self, client):
        w = adya.workload({"key-count": 6})
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=4, client=client,
                    checker=w["checker"],
                    generator=gen.clients(w["generator"]))
        return core.run(test)

    def test_serializable_client_valid(self):
        t = self._run(G2Client())
        assert t["results"]["valid?"] is True, t["results"]

    def test_g2_anomaly_detected(self):
        t = self._run(G2Client(broken=True))
        assert t["results"]["valid?"] is False
        assert t["results"]["illegal-count"] > 0
