"""graftlint: the static-analysis pass over the device kernels.

Covers: one synthetic mini-kernel per rule (R1-R6) asserting
detection, a clean kernel asserting zero findings, baseline-ratchet
semantics (new fails / baselined passes / fixed prunes), the
concurrency lint's positive and negative cases, the production-kernel
sweep (every registry entry traces without error; the committed
baseline gates tier-1 right here), and the profiler's
shape_buckets()/bucket_cardinality satellite.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from jepsen_tpu.analysis import concurrency, driver, registry
from jepsen_tpu.tpu import lint as L


def _jaxpr(fn, *args):
    import jax

    return jax.make_jaxpr(fn)(*args)


def _trace(fn, *args, name="syn", **kw) -> L.KernelTrace:
    return L.KernelTrace(name=name, bucket="t", jaxpr=_jaxpr(fn, *args),
                         **kw)


# ---------------------------------------------------------------------------
# R1 — host sync
# ---------------------------------------------------------------------------

class TestR1HostSync:
    def test_pure_callback_detected(self):
        import jax

        def k(x):
            return jax.pure_callback(
                lambda a: a, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        fs = L.rule_host_sync(_trace(k, np.ones(4, np.float32)))
        assert [f.rule for f in fs] == ["R1"]
        assert "pure_callback" in fs[0].site
        assert fs[0].file  # jaxpr source provenance

    def test_callback_inside_while_detected(self):
        import jax

        def k(x):
            def body(c):
                return jax.pure_callback(
                    lambda a: a,
                    jax.ShapeDtypeStruct(c.shape, c.dtype), c) + 1

            return jax.lax.while_loop(lambda c: c[0] < 3, body, x)

        fs = L.rule_host_sync(_trace(k, np.zeros(2, np.float32)))
        assert len(fs) == 1  # found through the while body sub-jaxpr


# ---------------------------------------------------------------------------
# R2 — dtype widening
# ---------------------------------------------------------------------------

class TestR2Widening:
    def test_int64_intermediate(self):
        import jax
        import jax.numpy as jnp

        with jax.experimental.enable_x64():
            def k(x):
                return jnp.sum(x.astype(jnp.int64))

            tr = _trace(k, np.arange(8, dtype=np.int32))
        fs = L.rule_dtype_widening(tr)
        assert any(f.rule == "R2" and "int64" in f.site for f in fs)

    def test_int32_kernel_clean(self):
        import jax.numpy as jnp

        def k(x):
            return jnp.sum(x * 2)

        assert L.rule_dtype_widening(
            _trace(k, np.arange(8, dtype=np.int32))) == []

    def test_host_feeder_ast_scan(self):
        src = ("import numpy as np\n"
               "def feeder(n):\n"
               "    ids = np.arange(n, dtype=np.int64)\n"
               "    return np.zeros(n, dtype='float64')\n"
               "def clean(n):\n"
               "    return np.zeros(n, dtype=np.int32)\n")
        fs = L.scan_source_dtypes(src, "x.py", "x")
        sites = {f.site for f in fs}
        assert sites == {"feeder:int64", "feeder:float64"}
        assert all(f.rule == "R2" and f.line for f in fs)

    def test_class_methods_qualified(self):
        src = ("import numpy as np\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self.x = np.int64(0)\n")
        fs = L.scan_source_dtypes(src, "x.py", "x")
        assert {f.site for f in fs} == {"C.__init__:int64"}


# ---------------------------------------------------------------------------
# R3 — donation
# ---------------------------------------------------------------------------

def _arg(name, nbytes, donated=False):
    return L.ArgSpec(name=name, shape=(nbytes // 4,), dtype="int32",
                     nbytes=nbytes, donated=donated)


class TestR3Donation:
    def test_large_nondonated_flagged(self):
        tr = L.KernelTrace(name="k", bucket="t",
                           args=[_arg("big", 1 << 20),
                                 _arg("tiny", 128)])
        fs = L.rule_donation(tr)
        assert [f.site for f in fs] == ["big"]
        assert fs[0].cost_bytes == 1 << 20

    def test_donated_and_small_pass(self):
        tr = L.KernelTrace(name="k", bucket="t",
                           args=[_arg("big", 1 << 20, donated=True),
                                 _arg("tiny", 128)])
        assert L.rule_donation(tr) == []


# ---------------------------------------------------------------------------
# R4 — sharding readiness
# ---------------------------------------------------------------------------

class TestR4Sharding:
    def test_replicated_large_operand(self):
        tr = L.KernelTrace(
            name="k", bucket="t", args=[_arg("tbl", 1 << 21)],
            partition={"axis": "b", "sharded": ["rows"],
                       "replicated": ["tbl"]})
        fs = L.rule_sharding(tr)
        assert [f.site for f in fs] == ["replicated:tbl"]

    def test_unsharded_batch_axis(self):
        tr = L.KernelTrace(name="k", bucket="t",
                           args=[_arg("rows", 4096)],
                           batch_axes=[("rows", 0, "independent")])
        fs = L.rule_sharding(tr)
        assert [f.site for f in fs] == ["unsharded-axis:rows.0"]

    def test_sharded_axis_passes(self):
        tr = L.KernelTrace(
            name="k", bucket="t", args=[_arg("rows", 4096)],
            partition={"axis": "b", "sharded": ["rows"],
                       "replicated": []},
            batch_axes=[("rows", 0, "independent")])
        assert L.rule_sharding(tr) == []

    def test_hlo_collective_scan(self):
        tr = L.KernelTrace(name="k", bucket="t",
                           hlo_text="... stablehlo.all-gather ...")
        fs = L.rule_sharding(tr)
        assert [f.site for f in fs] == ["collective:all-gather"]


# ---------------------------------------------------------------------------
# R5 — recompile risk
# ---------------------------------------------------------------------------

class TestR5Recompile:
    def test_captured_and_large_consts(self):
        import jax.numpy as jnp

        small = np.arange(4, dtype=np.float32)
        big = np.zeros((200, 200), np.float32)  # 160 KB

        def k(x):
            return x + jnp.sum(big) + small

        fs = L.rule_recompile(_trace(k, np.ones(4, np.float32)))
        sites = {f.site for f in fs}
        assert sites == {"captured-consts", "large-consts"}
        big_f = next(f for f in fs if f.site == "large-consts")
        assert big_f.cost_bytes == big.nbytes

    def test_linear_bucket_policy(self):
        tr = L.KernelTrace(name="k", bucket="t",
                           bucket_policy="linear")
        assert [f.site for f in L.rule_recompile(tr)] == \
            ["bucket-policy"]

    def test_runtime_bucket_cardinality(self):
        buckets = {"leaky": set(range(40)), "ok": {1, 2, 3}}
        fs = L.runtime_bucket_findings(buckets)
        assert [f.kernel for f in fs] == ["leaky"]
        assert fs[0].site == "bucket-cardinality"


# ---------------------------------------------------------------------------
# R6 — while-loop carry bloat
# ---------------------------------------------------------------------------

class TestR6Carry:
    def test_fat_carry_flagged(self):
        import jax

        def k(x):
            def body(c):
                i, a = c
                return i + 1, a * 2

            return jax.lax.while_loop(lambda c: c[0] < 8, body,
                                      (np.int32(0), x))

        # 64*1024 f32 = 256 KiB carry >= the 128 KiB budget
        fs = L.rule_carry(_trace(k, np.ones((64, 1024), np.float32)))
        assert [f.rule for f in fs] == ["R6"]
        assert fs[0].cost_bytes >= 256 * 1024

    def test_lean_carry_passes(self):
        import jax

        def k(x):
            def body(c):
                i, a = c
                return i + 1, a * 2

            return jax.lax.while_loop(lambda c: c[0] < 8, body,
                                      (np.int32(0), x))

        assert L.rule_carry(
            _trace(k, np.ones(16, np.float32))) == []


# ---------------------------------------------------------------------------
# Clean kernel: the whole suite finds nothing
# ---------------------------------------------------------------------------

def test_clean_kernel_zero_findings():
    import jax

    def k(x, y):
        def body(c):
            i, a = c
            return i + 1, a + y

        return jax.lax.while_loop(lambda c: c[0] < 4, body,
                                  (np.int32(0), x))

    args = (np.ones(16, np.float32), np.ones(16, np.float32))
    tr = _trace(k, *args,
                args=[_arg("x", 64, donated=True), _arg("y", 64)],
                bucket_policy="pow2")
    assert L.run_rules(tr) == []


# ---------------------------------------------------------------------------
# Baseline ratchet
# ---------------------------------------------------------------------------

def _finding(site, rule="R3", kernel="k"):
    return L.Finding(rule=rule, kernel=kernel, site=site,
                     message=f"m-{site}")


class TestRatchet:
    def test_new_baselined_stale(self):
        baseline = L.baseline_doc([_finding("a"), _finding("gone")])
        r = L.ratchet([_finding("a"), _finding("b")], baseline)
        assert [f.site for f in r["new"]] == [_finding("b").site]
        assert [f.site for f in r["baselined"]] == ["a"]
        assert r["stale"] == ["R3:k:gone"]

    def test_keys_ignore_line_numbers(self):
        f1 = _finding("a")
        f1.line = 10
        f2 = _finding("a")
        f2.line = 999  # the same finding after unrelated edits
        r = L.ratchet([f2], L.baseline_doc([f1]))
        assert not r["new"] and not r["stale"]

    def test_update_prunes_stale(self, tmp_path):
        p = tmp_path / "b.json"
        L.write_baseline(p, [_finding("a"), _finding("gone")])
        # the fix landed: rewriting pins only what's still found
        L.write_baseline(p, [_finding("a")])
        doc = L.load_baseline(p)
        assert [e["key"] for e in doc["findings"]] == ["R3:k:a"]

    def test_missing_baseline_is_empty(self, tmp_path):
        doc = L.load_baseline(tmp_path / "nope.json")
        assert doc["findings"] == []

    def test_malformed_baseline_raises(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text('{"findings": 3}')
        with pytest.raises(ValueError):
            L.load_baseline(p)

    def test_gate_exit_codes(self, tmp_path):
        rep = driver.LintReport(findings=[_finding("a")])
        p = tmp_path / "b.json"
        L.write_baseline(p, [_finding("a")])
        driver.gate(rep, p)
        assert not rep.ratchet["new"]
        rep2 = driver.LintReport(findings=[_finding("a"),
                                           _finding("b")])
        driver.gate(rep2, p)
        assert [f.site for f in rep2.ratchet["new"]] == ["b"]


# ---------------------------------------------------------------------------
# Concurrency lint
# ---------------------------------------------------------------------------

GOOD = '''
import threading
class Rec:
    _guarded_by_lock = {"_lock": ("_items", "_count")}
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._count = 0
    def add(self, x):
        with self._lock:
            self._items.append(x)
            self._count += 1
    def _drain_locked(self):
        out = list(self._items)
        self._items.clear()
        return out
    def drain(self):
        with self._lock:
            return self._drain_locked()
'''

BAD = '''
import threading
class Rec:
    _guarded_by_lock = ("_items",)
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
    def add(self, x):
        self._items.append(x)        # C1: mutator outside lock
    def reset(self):
        self._items = []             # C1: assignment outside lock
    def flush(self):
        self._flush_locked()         # C2: _locked call outside lock
    def _flush_locked(self):
        self._items = []             # ok: *_locked is lock-held
    def deferred(self):
        with self._lock:
            def cb():
                self._items.append(1)   # C1: closure runs later
            return cb
'''


class TestConcurrencyLint:
    def test_compliant_class_clean(self):
        assert concurrency.scan_source(GOOD, "g.py", "g") == []

    def test_violations_detected(self):
        fs = concurrency.scan_source(BAD, "b.py", "b")
        sites = {(f.rule, f.site) for f in fs}
        assert ("C1", "add:_items") in sites
        assert ("C1", "reset:_items") in sites
        assert ("C2", "flush:_flush_locked") in sites
        assert ("C1", "deferred.cb:_items") in sites
        # the *_locked body itself is NOT a finding
        assert not any(f.site.startswith("_flush_locked")
                       for f in fs)

    def test_unannotated_lock_advisory(self):
        src = ("import threading\n"
               "class X:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self.xs = []\n")
        fs = concurrency.scan_source(src, "x.py", "x")
        assert [(f.rule, f.site) for f in fs] == [("C3", "_lock")]
        assert fs[0].severity == "info"

    def test_lockless_class_skipped(self):
        src = "class P:\n    def f(self):\n        self.x = 1\n"
        assert concurrency.scan_source(src, "p.py", "p") == []

    def test_lambda_body_is_a_closure(self):
        src = ("import threading\n"
               "class R:\n"
               "    _guarded_by_lock = ('_xs',)\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._xs = []\n"
               "    def defer(self):\n"
               "        with self._lock:\n"
               "            return lambda: self._xs.append(1)\n")
        fs = concurrency.scan_source(src, "r.py", "r")
        assert [(f.rule, f.site) for f in fs] == \
            [("C1", "defer.<lambda>:_xs")]

    def test_match_statement_blocks(self):
        src = ("import threading\n"
               "class M:\n"
               "    _guarded_by_lock = ('_xs',)\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._xs = []\n"
               "    def ok(self, v):\n"
               "        match v:\n"
               "            case 1:\n"
               "                with self._lock:\n"
               "                    self._xs.append(v)\n"
               "    def bad(self, v):\n"
               "        match v:\n"
               "            case 1:\n"
               "                self._xs = [v]\n")
        fs = concurrency.scan_source(src, "m.py", "m")
        assert [(f.rule, f.site) for f in fs] == [("C1", "bad:_xs")]

    def test_production_modules_compliant(self):
        """telemetry/monitor/nodeprobe/profiler carry annotations and
        hold their locks; interpreter keeps worker stats thread-local.
        Any C1/C2 here is a real data race — fix it, don't baseline
        it."""
        fs = []
        for mod in driver._concurrency_modules():
            fs.extend(concurrency.scan_module(mod))
        assert [f for f in fs if f.rule in ("C1", "C2")] == []
        # ... and the convention is actually adopted (no unannotated
        # locks left in the scanned modules)
        assert [f for f in fs if f.rule == "C3"] == []


# ---------------------------------------------------------------------------
# Production sweep + the tier-1 baseline gate
# ---------------------------------------------------------------------------

def _repo_baseline():
    from pathlib import Path

    return Path(__file__).resolve().parent.parent / \
        "lint-baseline.json"


@pytest.fixture(scope="module")
def production_report():
    return driver.run_lint()


class TestProductionSweep:
    def test_every_entry_traces(self, production_report):
        assert production_report.errors == []
        traced = {t["kernel"] for t in production_report.traces}
        assert traced == {"wgl", "wgl-reach", "wgl-segmented",
                          "wgl-sharded", "wgl-single", "wgl-slices",
                          "scc", "scc-single"}

    def test_baseline_gate(self, production_report):
        """THE tier-1 ratchet: a change that introduces a finding not
        pinned in lint-baseline.json fails here. Fix the finding, or
        — for a deliberate, justified regression — re-pin with
        `python -m jepsen_tpu lint --baseline lint-baseline.json
        --update` and defend it in review."""
        rep = driver.gate(production_report, _repo_baseline())
        assert rep.ratchet["new"] == [], (
            "NEW lint findings vs lint-baseline.json:\n"
            + "\n".join(f"  {f.key}: {f.message}"
                        for f in rep.ratchet["new"]))
        assert rep.ratchet["stale"] == [], (
            "fixed findings still pinned — prune with --update: "
            + ", ".join(rep.ratchet["stale"]))

    def test_rule_breadth_and_provenance(self, production_report):
        """Post-SPMD (ISSUE-15): the sharding/donation rules report
        NOTHING — R3/R4 went to zero with the shard_map rebuild — and
        what remains (the pinned R2 fingerprint, scc's linear bucket
        policy, the R6 carry worklist) still carries file:line
        provenance."""
        rules = {f.rule for f in production_report.findings}
        assert "R3" not in rules and "R4" not in rules, rules
        assert rules, "the R2/R5/R6 worklist vanished? verify, then pin"
        assert all(f.file and f.line
                   for f in production_report.findings)

    def test_all_kernel_args_donated(self, production_report):
        """ISSUE-15 satellite, as the lint itself measures it: the wgl
        packed segment tensors AND the scc edge arrays are donated —
        zero R3 findings, and every kernel trace shows donated
        bytes."""
        assert [f for f in production_report.findings
                if f.rule == "R3"] == []
        for t in production_report.traces:
            assert t["donated_bytes"] > 0, t

    def test_int64_fixes_landed(self, production_report):
        """scc._scc_host and wgl.valid_cut_points now speak int32;
        the only remaining host-feeder int64 is the checkpoint
        fingerprint (pinned: changing it would invalidate every
        existing segment checkpoint)."""
        r2 = [f.site for f in production_report.findings
              if f.rule == "R2"]
        assert r2 == ["_SegmentCheckpoint.__init__:int64"]

    def test_aggregates_shape(self, production_report):
        """THE ISSUE-15 acceptance ledger block: the SPMD rebuild
        drove R3 non-donated bytes, R4 replicated bytes and R4
        unsharded batch axes all to zero — the per-round perf-ledger
        `lint` block (bench_lint_wall feeds these exact aggregates)
        records it from now on."""
        agg = production_report.aggregates()
        assert agg["non_donated_bytes"] == 0
        assert agg["replicated_bytes"] == 0
        assert agg["unsharded_axes"] == 0
        assert agg["findings"]

    def test_telemetry_counters(self):
        from jepsen_tpu import telemetry

        tel = telemetry.get()
        before = tel.counters().get("lint.runs", 0)
        driver.run_lint(trace_kernels=False)
        c = tel.counters()
        assert c.get("lint.runs", 0) == before + 1
        assert "lint.non-donated-bytes" in tel.gauges()

    def test_report_json_round_trip(self, production_report):
        doc = json.loads(json.dumps(production_report.to_dict()))
        assert doc["aggregates"]["unsharded_axes"] == 0
        assert doc["aggregates"]["replicated_bytes"] == 0
        assert len(doc["findings"]) == \
            len(production_report.findings)

    def test_cli_gate(self, capsys):
        rc = driver.main(["--baseline", str(_repo_baseline())])
        assert rc == 0
        out = capsys.readouterr().out
        assert "graftlint:" in out and "baseline:" in out

    def test_cli_rules_gate_not_destructive(self, capsys,
                                            tmp_path):
        """--rules narrows BOTH sides of the ratchet (other rules'
        pinned findings are not 'stale'), and --update refuses to
        combine with --rules (it would drop them from the file)."""
        rc = driver.main(["--rules", "R3",
                          "--baseline", str(_repo_baseline())])
        out = capsys.readouterr().out
        assert rc == 0
        assert "stale" not in out.replace("0 stale", "")
        bp = tmp_path / "b.json"
        bp.write_text((_repo_baseline()).read_text())
        rc = driver.main(["--rules", "R3", "--update",
                          "--baseline", str(bp)])
        assert rc == 254
        assert json.loads(bp.read_text()) == \
            json.loads(_repo_baseline().read_text())
        # ... and so do the non-deterministic modes: the committed
        # baseline's contract is the default mode only
        for flag in ("--runtime-buckets", "--full"):
            rc = driver.main([flag, "--update",
                              "--baseline", str(bp)])
            assert rc == 254, flag
        assert json.loads(bp.read_text()) == \
            json.loads(_repo_baseline().read_text())


# ---------------------------------------------------------------------------
# Satellites: profiler shape buckets + runtime cardinality, web, ledger
# ---------------------------------------------------------------------------

class TestShapeBuckets:
    def test_accessor_merges_wgl(self):
        from jepsen_tpu.checker import models
        from jepsen_tpu.tpu import profiler, wgl
        from jepsen_tpu.tpu.encode import encode
        from jepsen_tpu.tpu.synth import register_history

        hist = register_history(60, n_procs=3, seed=11)
        enc = encode(models.register(), hist)
        wgl.check_batch([enc])
        buckets = profiler.shape_buckets()
        assert buckets.get("wgl"), buckets
        # runtime bucket tuples translate back into traceable dicts
        rb = registry.runtime_wgl_buckets(buckets["wgl"])
        assert all(b["label"].startswith("rt-") for b in rb)

    def test_bucket_cardinality_gauge(self):
        from jepsen_tpu import telemetry
        from jepsen_tpu.tpu import profiler

        prof = profiler.Profiler()
        tel = telemetry.get()
        prof.bucket_fresh("lintcheck", ("a",))
        prof.bucket_fresh("lintcheck", ("b",))
        prof.bucket_fresh("lintcheck", ("a",))  # cache hit: no growth
        assert tel.gauges().get(
            "profiler.lintcheck.bucket_cardinality") == 2
        # a failed first launch unclaims and retries: the second miss
        # for the SAME bucket must not inflate the cardinality
        prof.bucket_unclaim("lintcheck", ("b",))
        prof.bucket_fresh("lintcheck", ("b",))
        assert tel.gauges().get(
            "profiler.lintcheck.bucket_cardinality") == 2


def test_web_lint_page_and_panel(monkeypatch):
    from jepsen_tpu import web

    # cold cache: the run-page panel must NOT lint inline — it shows
    # a warming placeholder and computes in the background
    web._lint_cache.clear()
    panel = web.lint_panel_html()
    assert "warming" in panel and "/lint" in panel
    # /lint itself is synchronous (the user asked for the report)
    html = web.lint_html()
    assert "graftlint" in html and "R4" in html
    panel = web.lint_panel_html()  # now served from the cache
    assert "/lint" in panel and "unsharded axes" in panel


def test_ledger_lint_field_validates():
    from jepsen_tpu import ledger

    entry = {"round": 1, "ts": 1.0, "kind": "bench",
             "headline": {"value": 1.0}, "kernels": {},
             "lint": {"non_donated_bytes": 100, "replicated_bytes": 0,
                      "unsharded_axes": 4, "findings": {"R3": 3}}}
    assert ledger.validate_entries([entry]) == 1
    bad = dict(entry, lint={"non_donated_bytes": "lots"})
    with pytest.raises(ValueError):
        ledger.validate_entries([bad])
