"""Mesh-sharded ensemble checking tests: the data-parallel and
segment-parallel (reach) paths of tpu/ensemble.py on the virtual
8-device CPU mesh set up by conftest.py.

Differential strategy mirrors test_wgl.py: the sharded kernel must agree
with the single-device kernel and the exhaustive host search on both
valid-by-construction and corrupted histories. This is the coverage the
driver's dryrun_multichip exercises (SURVEY §2.5: shard the batch dim
over a 1-D Mesh; independent.clj:271-377 is the host-side analog).
"""

import os

import numpy as np
import pytest

from jepsen_tpu.checker import models as model
from jepsen_tpu.history import History, op
from jepsen_tpu.tpu import ensemble, synth, wgl
from jepsen_tpu.tpu.encode import encode


@pytest.fixture(scope="module")
def mesh():
    import jax

    devs = jax.devices()
    if len(devs) < 8:  # real-device run (JEPSEN_TPU_TEST_REAL_DEVICE=1)
        pytest.skip(f"needs 8 devices, have {len(devs)}")
    return ensemble.default_mesh(8)


def corrupt(hist):
    """Flip one ok-read's value so the history becomes non-linearizable."""
    ops = list(hist)
    for i in range(len(ops) - 1, -1, -1):
        o = ops[i]
        if o.type == "ok" and o.f == "read" and o.value is not None:
            ops[i] = o.copy(value=o.value + 1000)
            return History(ops, assign_indices=False)
    raise AssertionError("no ok read to corrupt")


def test_default_mesh_shape(mesh):
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("b",)


def test_data_parallel_valid(mesh):
    m = model.cas_register()
    hists = [synth.register_history(32, n_procs=3, seed=i)
             for i in range(16)]
    encs = [encode(m, h) for h in hists]
    res = ensemble.check_batch_sharded(encs, mesh=mesh, W=16, F=16)
    assert res.shape == (16,)
    assert all(int(r) == wgl.VALID for r in res)


def test_data_parallel_mixed_validity(mesh):
    m = model.cas_register()
    hists = [synth.register_history(32, n_procs=3, seed=100 + i)
             for i in range(8)]
    bad_idx = {1, 4, 6}
    hists = [corrupt(h) if i in bad_idx else h
             for i, h in enumerate(hists)]
    encs = [encode(m, h) for h in hists]
    res = ensemble.check_batch_sharded(encs, mesh=mesh, W=16, F=32)
    for i, (e, r) in enumerate(zip(encs, res)):
        expect = wgl.search_host(e)["valid?"]
        if int(r) == wgl.UNKNOWN:
            continue  # sound: kernel may punt, never lie
        assert (int(r) == wgl.VALID) == expect, f"history {i}"
    # at least the corrupted ones must not come back VALID
    for i in bad_idx:
        assert int(res[i]) != wgl.VALID


def test_data_parallel_matches_unsharded(mesh):
    m = model.cas_register()
    hists = [synth.register_history(24, n_procs=3, seed=200 + i)
             for i in range(12)]
    hists[3] = corrupt(hists[3])
    encs = [encode(m, h) for h in hists]
    sharded = ensemble.check_batch_sharded(encs, mesh=mesh, W=16, F=16)
    plain = wgl.check_batch(encs, W=16, F=16)
    assert list(map(int, sharded)) == list(map(int, plain))


def test_ragged_batch_not_multiple_of_devices(mesh):
    """Row padding: 5 histories over 8 devices still answers 5 rows."""
    m = model.cas_register()
    hists = [synth.register_history(16, n_procs=2, seed=300 + i)
             for i in range(5)]
    encs = [encode(m, h) for h in hists]
    res = ensemble.check_batch_sharded(encs, mesh=mesh, W=16, F=16)
    assert res.shape == (5,)
    assert all(int(r) == wgl.VALID for r in res)


def test_reach_segments_compose(mesh):
    """Segment-parallel long history: sharded reach rows compose through
    boundary states to the same verdict as the host search."""
    m = model.cas_register()
    hist = synth.register_history(300, n_procs=4, seed=7)
    enc = encode(m, hist)
    cuts = wgl.segment_cuts(enc, target_len=32)
    K = len(cuts) - 1
    assert K >= 2
    segs = [enc.segment(cuts[k], cuts[k + 1]) for k in range(K)]
    S = enc.n_states
    rows = [(k, s) for k in range(K) for s in range(S)]
    out, unk = ensemble.check_batch_sharded(
        segs, mesh=mesh, W=16, F=16, reach=True, rows=rows)
    assert out.shape == (len(rows),)
    reach = 1 << enc.init_state
    for k in range(K):
        nreach = 0
        for s in range(S):
            if (reach >> s) & 1:
                i = k * S + s
                nreach |= (wgl.search_host_reach(segs[k].with_init(s))
                           if unk[i] else int(out[i]))
        assert nreach, f"segment {k} should stay reachable"
        reach = nreach


def test_reach_rows_match_host(mesh):
    """Every (segment, start-state) reach mask the kernel resolves must
    equal the exhaustive host reachability for that row."""
    m = model.cas_register()
    hist = synth.register_history(120, n_procs=3, seed=11)
    enc = encode(m, hist)
    cuts = wgl.segment_cuts(enc, target_len=24)
    K = len(cuts) - 1
    segs = [enc.segment(cuts[k], cuts[k + 1]) for k in range(K)]
    S = enc.n_states
    rows = [(k, s) for k in range(K) for s in range(S)]
    out, unk = ensemble.check_batch_sharded(
        segs, mesh=mesh, W=16, F=32, reach=True, rows=rows)
    for i, (k, s) in enumerate(rows):
        if unk[i]:
            continue
        host = wgl.search_host_reach(segs[k].with_init(s))
        assert int(out[i]) == host, f"row {(k, s)}"


def test_scaling_equivalence_across_mesh_sizes(mesh):
    """The sharded checker is a pure function of the histories: its
    answers must be bit-identical whether the mesh has 1, 2, or 8
    devices (VERDICT r3 #10 — turns 'wired' multi-chip into
    'verified'). Covers both the data-parallel ensemble path and the
    segment x start-state reach path."""
    m = model.cas_register()
    hists = [synth.register_history(28, n_procs=3, seed=500 + i)
             for i in range(10)]
    hists[2] = corrupt(hists[2])
    hists[7] = corrupt(hists[7])
    encs = [encode(m, h) for h in hists]

    long_hist = synth.register_history(220, n_procs=4, seed=77)
    enc = encode(m, long_hist)
    cuts = wgl.segment_cuts(enc, target_len=32)
    K = len(cuts) - 1
    assert K >= 2
    segs = [enc.segment(cuts[k], cuts[k + 1]) for k in range(K)]
    S = enc.n_states
    rows = [(k, s) for k in range(K) for s in range(S)]

    ens_by_n, reach_by_n = {}, {}
    for n in (1, 2, 8):
        sub = ensemble.default_mesh(n)
        assert sub.devices.size == n
        ens_by_n[n] = list(map(int, ensemble.check_batch_sharded(
            encs, mesh=sub, W=16, F=16)))
        out, unk = ensemble.check_batch_sharded(
            segs, mesh=sub, W=16, F=16, reach=True, rows=rows)
        reach_by_n[n] = (list(map(int, out)), list(map(bool, unk)))

    assert ens_by_n[1] == ens_by_n[2] == ens_by_n[8]
    assert reach_by_n[1] == reach_by_n[2] == reach_by_n[8]
    # and the 1-device answer equals the unsharded kernel's
    assert ens_by_n[1] == list(map(int, wgl.check_batch(
        encs, W=16, F=16)))


def test_analysis_batch_sharded(mesh):
    m = model.cas_register()
    hists = [synth.register_history(24, n_procs=3, seed=400 + i)
             for i in range(8)]
    hists[2] = corrupt(hists[2])
    res = ensemble.analysis_batch_sharded(m, hists, mesh=mesh, W=16, F=32)
    assert len(res) == 8
    for i, r in enumerate(res):
        assert r["valid?"] == (i != 2)
    assert res[2]["op"] is not None or res[2].get("configs")


@pytest.mark.skipif(
    os.environ.get("JEPSEN_TPU_TEST_REAL_DEVICE") == "1",
    reason="dryrun forces the virtual CPU platform mid-session")
def test_graft_entry_dryrun():
    """The driver's multichip dryrun must pass end-to-end in-process."""
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        import __graft_entry__ as ge
    finally:
        sys.path.pop(0)
    ge.dryrun_multichip(8)


class TestStreamedBatch:
    def test_matches_one_shot_batch(self):
        from jepsen_tpu.checker import models
        from jepsen_tpu.tpu import synth, wgl

        hists = [synth.register_history(60, n_procs=3, seed=100 + i,
                                        crash_p=0.1 if i % 3 else 0.0)
                 for i in range(40)]
        # corrupt one history so valid/invalid both flow through
        bad = hists[7]
        ops = list(bad)
        from jepsen_tpu.history import History, op as mkop
        ops.append(mkop(type="invoke", process=0, f="read", value=None))
        ops.append(mkop(type="ok", process=0, f="read", value=424242))
        hists[7] = History(ops)
        model = models.cas_register()
        one = wgl.analysis_batch(model, hists)
        streamed = wgl.analysis_batch_streamed(model, hists, chunk=16)
        assert [r["valid?"] for r in one] == \
            [r["valid?"] for r in streamed]
        assert streamed[7]["valid?"] is False
