"""Elasticsearch suite tests: DB command emission via the dummy
remote, HTTP driver semantics against an in-memory ES, and
clusterless end-to-end dirty-read and set runs (mirrors
elasticsearch/src/jepsen/elasticsearch/{core,dirty_read,sets}.clj)."""

import threading

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.suites import elasticsearch as es


def responder(node, action):
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "elasticsearch-7.17.23"
    return None


def make_test(nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return core.prepare_test(t)


class TestDB:
    def test_setup_commands(self):
        test = make_test()
        db = es.ElasticsearchDB("7.17.23")
        with control.with_session(test, "n2"):
            db.setup(test, "n2")
        acts = [a for a in test["sessions"]["n2"].log
                if isinstance(a, Action)]
        got = " ; ".join(a.cmd for a in acts)
        assert "elasticsearch-7.17.23-linux-x86_64.tar.gz" in got
        assert "adduser" in got and "elasticsearch" in got
        assert "chown -R elasticsearch:elasticsearch" in got
        # config carries unicast discovery of the whole cluster
        yml = next(a.stdin for a in acts
                   if a.stdin and "elasticsearch.yml" in a.cmd)
        assert 'discovery.seed_hosts: ["n1", "n2", "n3"]' in yml
        assert "node.name: n2" in yml
        # the daemon starts as the dedicated user, never root
        start = next(a for a in acts
                     if "bin/elasticsearch" in a.cmd
                     and "start" in a.cmd.lower() or
                     "daemon" in a.cmd.lower())
        assert start.sudo == "elasticsearch"


class FakeEs:
    """In-memory ES with per-'node' visibility semantics: indexed docs
    are immediately visible to get-by-id, but _search only sees docs
    present at the last _refresh — exactly the near-real-time behavior
    the dirty-read test exercises."""

    def __init__(self):
        self.lock = threading.Lock()
        self.docs: set = set()      # committed (acked) ids
        self.searchable: set = set()

    def request(self, method, path, body=None):
        with self.lock:
            if method == "PUT" and path.count("/") == 1:
                return 200, {"acknowledged": True}
            if "/_doc/" in path and method == "PUT":
                doc_id = path.split("/_doc/")[1].split("?")[0]
                if doc_id in self.docs:
                    return 409, {"error": "version_conflict"}
                self.docs.add(doc_id)
                return 201, {"result": "created"}
            if "/_doc/" in path and method == "GET":
                doc_id = path.split("/_doc/")[1]
                if doc_id in self.docs:
                    return 200, {"found": True,
                                 "_source": {"id": doc_id}}
                return 404, {"found": False}
            if path.endswith("/_refresh"):
                self.searchable = set(self.docs)
                return 200, {"_shards": {"total": 3, "successful": 3,
                                         "failed": 0}}
            if path.endswith("/_search"):
                docs = sorted(self.searchable)
                after = (body or {}).get("search_after")
                if after is not None:
                    docs = [d for d in docs if d > after[0]]
                size = (body or {}).get("size", 10)
                page = docs[:size]
                return 200, {"hits": {"hits": [
                    {"_id": d, "sort": [d]} for d in page]}}
            raise AssertionError(f"unexpected {method} {path}")


class FakeHttpFactory:
    def __init__(self, state=None):
        self.state = state or FakeEs()

    def __call__(self, node, timeout=8.0):
        http = es.EsHttp(node, timeout=timeout)
        http.request = self.state.request
        return http


class TestDriver:
    def test_index_get_refresh_search(self):
        http = FakeHttpFactory()("n1")
        assert http.index_doc("dirty_read", "7") is True
        assert http.get_doc("dirty_read", "7") is True
        assert http.search_ids("dirty_read") == []  # not refreshed
        assert http.refresh("dirty_read") is True
        assert http.search_ids("dirty_read") == ["7"]

    def test_duplicate_create_is_ok(self):
        http = FakeHttpFactory()("n1")
        http.index_doc("dirty_read", "3")
        assert http.index_doc("dirty_read", "3") is True  # 409 -> ok


class TestEndToEnd:
    def _run(self, factory, ops=300, concurrency=6):
        w = es.dirty_read_workload({"ops": ops,
                                    "concurrency": concurrency,
                                    "seed": 11})
        w["client"].http_factory = factory
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"], concurrency=concurrency,
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(gen.phases(
                        gen.stagger(0.0004, w["generator"]),
                        w["final_generator"])))
        return core.run(test)

    def test_dirty_read_workload_valid(self):
        test = self._run(FakeHttpFactory())
        res = test["results"]
        assert res["valid?"] is True
        assert res["strong-read-count"] == 6
        assert res["read-count"] > 0

    def test_lost_write_detected(self):
        """Acked writes that vanish before the strong read must
        surface as lost."""

        class Lossy(FakeEs):
            def __init__(self):
                super().__init__()
                self.n = 0

            def request(self, method, path, body=None):
                if "/_doc/" in path and method == "PUT":
                    self.n += 1
                    if self.n % 5 == 0:
                        return 201, {"result": "created"}  # ack, drop
                return super().request(method, path, body)

        test = self._run(FakeHttpFactory(Lossy()))
        res = test["results"]
        assert res["valid?"] is False
        assert res["lost-count"] > 0

    def test_dirty_read_detected(self):
        """Reads observing never-committed docs must surface as
        dirty."""

        class Dirty(FakeEs):
            def __init__(self):
                super().__init__()
                self.n = 0
                self.phantom: set = set()

            def request(self, method, path, body=None):
                if "/_doc/" in path and method == "PUT":
                    self.n += 1
                    if self.n % 4 == 0:
                        doc_id = path.split("/_doc/")[1].split("?")[0]
                        with self.lock:
                            self.phantom.add(doc_id)
                        raise TimeoutError("ack lost")  # info write
                if "/_doc/" in path and method == "GET":
                    doc_id = path.split("/_doc/")[1]
                    if doc_id in self.phantom:
                        return 200, {"found": True,
                                     "_source": {"id": doc_id}}
                return super().request(method, path, body)

        test = self._run(FakeHttpFactory(Dirty()), ops=400)
        res = test["results"]
        assert res["valid?"] is False
        assert res["dirty-count"] > 0

    def test_set_workload(self):
        w = es.set_workload({"ops": 80})
        w["client"].http_factory = FakeHttpFactory()
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=4,
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(gen.phases(
                        gen.stagger(0.0004, w["generator"]),
                        w["final_generator"])))
        test = core.run(test)
        assert test["results"]["valid?"] is True


class TestCli:
    def test_map_shape(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                "ssh": {"dummy": True}, "time_limit": 5}
        test = es.elasticsearch_test(opts)
        assert test["name"] == "elasticsearch-dirty-read"
        assert isinstance(test["db"], es.ElasticsearchDB)


class TestPaging:
    def test_search_pages_past_10000(self):
        """search_ids must not truncate at one page (review r3)."""
        state = FakeEs()
        state.docs = {f"{i:06d}" for i in range(25)}
        state.searchable = set(state.docs)
        http = FakeHttpFactory(state)("n1")
        # tiny pages to force multiple rounds through search_after
        real = http.request

        def small_pages(method, path, body=None):
            if path.endswith("/_search") and body:
                body = dict(body, size=7)
            return real(method, path, body)

        http.request = small_pages
        ids = http.search_ids("sets")
        assert len(ids) == 25 and ids == sorted(ids)
