"""Stolon suite tests: DB daemon orchestration via the dummy remote, a
scripted ledger 'postgres', and clusterless e2e append + ledger runs
(mirrors stolon/src/jepsen/stolon/{db,ledger}.clj)."""

import re
import threading

import pytest

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, RemoteError
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import stolon


def make_test(responder=None, nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return t


def cmds(test, node):
    return [a for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_cluster_spec(self):
        t = {"nodes": ["n1", "n2", "n3", "n4", "n5"]}
        spec = stolon.cluster_spec(t)
        assert spec["synchronousReplication"] is True
        assert spec["maxStandbysPerSender"] == 4
        assert spec["minSynchronousStandbys"] == 1

    def test_pg_ids(self):
        t = {"nodes": ["n1", "n2", "n3"]}
        assert stolon.pg_id(t, "n1") == "pg1"
        assert stolon.pg_id(t, "n3") == "pg3"

    def test_daemons_start_with_store_flags(self):
        test = make_test()
        db = stolon.StolonDB()
        with control.with_session(test, "n2"):
            db._start_sentinel(test, "n2")
            db._start_keeper(test, "n2")
            db._start_proxy(test, "n2")
        got = " ; ".join(a.cmd for a in cmds(test, "n2"))
        assert "stolon-sentinel" in got
        assert "stolon-keeper" in got and "--uid pg2" in got
        assert "stolon-proxy" in got
        assert got.count("--store-backend etcdv3") >= 3
        assert "--initial-cluster-spec" in got
        assert f"--pg-port {stolon.KEEPER_PG_PORT}" in got

    def test_kill_stops_keeper_only(self):
        test = make_test()
        db = stolon.StolonDB()
        with control.with_session(test, "n1"):
            db.kill(test, "n1")
        got = " ; ".join(a.cmd for a in cmds(test, "n1"))
        assert "keeper" in got
        assert "proxy" not in got and "sentinel" not in got


# ---------------------------------------------------------------------------
# Scripted ledger postgres
# ---------------------------------------------------------------------------

class _PgError(Exception):
    pass


class FakeLedgerPg:
    """Executes the ledger client's SQL shapes; broken=True ignores
    the non-negativity guard on withdrawals (a double-spend-friendly
    'postgres', what G2 looks like from the outside)."""

    def __init__(self, broken=False):
        self.lock = threading.Lock()
        self.rows = {}  # id -> (account, amount)
        self.broken = broken

    def _sum(self, account, excl):
        return sum(a for rid, (acct, a) in self.rows.items()
                   if acct == account and rid != excl)

    def execute(self, sql: str) -> str:
        with self.lock:
            out = []
            for stmt in filter(None, (s.strip()
                                      for s in sql.split(";"))):
                if re.match(r"BEGIN|COMMIT", stmt):
                    continue
                m = re.match(r"SELECT 'a=' \|\|", stmt)
                if m:
                    totals = {}
                    for acct, amt in self.rows.values():
                        totals[acct] = totals.get(acct, 0) + amt
                    out.append("a=" + ",".join(
                        f"{a}:{t}" for a, t in sorted(totals.items())))
                    continue
                m = re.match(r"INSERT INTO ledger VALUES "
                             r"\((\d+), (\d+), (-?\d+)\)", stmt)
                if m:
                    rid, acct, amt = map(int, m.groups())
                    self.rows[rid] = (acct, amt)
                    continue
                m = re.match(r"SELECT 'bal=' \|\| COALESCE.*"
                             r"account = (\d+) AND id != (\d+)", stmt)
                if m:
                    acct, rid = map(int, m.groups())
                    out.append(f"bal={self._sum(acct, rid)}")
                    continue
                m = re.match(r"INSERT INTO ledger SELECT (\d+), "
                             r"(\d+), (-?\d+) WHERE", stmt)
                if m:
                    rid, acct, amt = map(int, m.groups())
                    if self.broken or self._sum(acct, rid) + amt >= 0:
                        self.rows[rid] = (acct, amt)
                    continue
                m = re.match(r"SELECT 'n=' \|\| COUNT\(\*\) FROM "
                             r"ledger WHERE id = (\d+)", stmt)
                if m:
                    out.append(
                        f"n={1 if int(m.group(1)) in self.rows else 0}")
                    continue
                raise AssertionError(
                    f"fake ledger pg can't parse: {stmt!r}")
            return "\n".join(out) + ("\n" if out else "")


class FakeProxyFactory:
    def __init__(self, state=None):
        self.state = state or FakeLedgerPg()

    def __call__(self, test, node, host=None, timeout=10.0,
                 port=stolon.PROXY_PORT):
        factory = self

        class _Fake:
            def run(self, sql):
                try:
                    return factory.state.execute(sql)
                except _PgError as e:
                    raise RemoteError("psql failed", exit=1, out="",
                                      err=f"ERROR: {e}", cmd="psql",
                                      node=node)

            def close(self):
                pass

        return _Fake()


class TestLedgerClient:
    def _client(self, state=None):
        f = FakeProxyFactory(state)
        c = stolon.LedgerClient(psql_factory=f).open(
            {"nodes": ["n1"]}, "n1")
        return c, f.state

    def _op(self, f, v, process=0):
        return Op(type="invoke", process=process, f=f, value=v)

    def test_deposit_then_read(self):
        c, _ = self._client()
        assert c.invoke({}, self._op("transfer", [0, 10])).type == "ok"
        r = c.invoke({}, self._op("read", None))
        assert r.value == {0: 10}

    def test_withdrawal_guard(self):
        c, _ = self._client()
        c.invoke({}, self._op("transfer", [0, 10]))
        assert c.invoke({}, self._op("transfer", [0, -9])).type == "ok"
        # second -9 would go negative: definite fail
        r = c.invoke({}, self._op("transfer", [0, -9]))
        assert r.type == "fail"
        assert c.invoke({}, self._op("read", None)).value == {0: 1}

    def test_row_ids_disjoint_by_process(self):
        c, state = self._client()
        c.invoke({}, self._op("transfer", [0, 5], process=1))
        c.invoke({}, self._op("transfer", [0, 5], process=2))
        assert len(state.rows) == 2


class TestLedgerChecker:
    def test_charitable_interpretation(self):
        hist = [
            Op(type="ok", process=0, f="transfer", value=[0, 10]),
            Op(type="info", process=1, f="transfer", value=[0, -9]),
            Op(type="ok", process=2, f="transfer", value=[0, -9]),
        ]
        # info withdrawal doesn't count: 10 - 9 = 1 >= 0
        assert stolon.check_ledger(hist)["valid?"] is True
        hist.append(Op(type="ok", process=3, f="transfer",
                       value=[0, -9]))
        # two OK withdrawals against one deposit: double-spend
        res = stolon.check_ledger(hist)
        assert res["valid?"] is False
        assert res["errors"][0]["account"] == 0

    def test_info_deposit_counts(self):
        hist = [
            Op(type="info", process=0, f="transfer", value=[0, 10]),
            Op(type="ok", process=1, f="transfer", value=[0, -9]),
        ]
        assert stolon.check_ledger(hist)["valid?"] is True


class TestEndToEnd:
    def _run(self, state, ops=200, concurrency=4):
        w = stolon.ledger_workload({"ops": ops, "seed": 7})
        w["client"].psql_factory = FakeProxyFactory(state)
        test = testing.noop_test()
        test.update(nodes=["n1", "n2"], concurrency=concurrency,
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0003, w["generator"])))
        return core.run(test)

    def test_ledger_valid_on_honest_pg(self):
        t = self._run(FakeLedgerPg())
        assert t["results"]["valid?"] is True

    def test_double_spend_detected_on_broken_pg(self):
        t = self._run(FakeLedgerPg(broken=True), ops=300,
                      concurrency=6)
        assert t["results"]["valid?"] is False


class TestCli:
    def test_test_map_shape(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 3,
                "ssh": {"dummy": True}, "time_limit": 5}
        test = stolon.stolon_test(opts)
        assert test["name"] == "stolon-append"
        assert isinstance(test["db"], stolon.StolonDB)
        assert test["db"].supports_kill

    def test_ledger_workload_selectable(self):
        opts = {"nodes": ["n1"], "concurrency": 2,
                "ssh": {"dummy": True}, "workload": "ledger"}
        test = stolon.stolon_test(opts)
        assert test["name"] == "stolon-ledger"
        assert isinstance(test["client"], stolon.LedgerClient)
