"""Membership nemesis tests: the join/remove state machine against a
fake etcd member API (mirrors nemesis/membership.clj:109-247 +
membership/state.clj), including view polling, pending-op resolution,
and generator legality."""

import threading
import time

from jepsen_tpu import generator as gen
from jepsen_tpu import testing
from jepsen_tpu.generator.context import Context
from jepsen_tpu.nemesis import membership
from jepsen_tpu.suites import etcd


class FakeCluster:
    """Shared in-memory member list keyed by name."""

    def __init__(self, nodes):
        self.lock = threading.Lock()
        self.members = {str(n): {"name": str(n), "ID": 1000 + i}
                        for i, n in enumerate(nodes)}
        self.next_id = 2000

    def factory(self, node):
        return FakeMemberHttp(self, str(node))


class FakeMemberHttp:
    def __init__(self, cluster: FakeCluster, node: str):
        self.cluster = cluster
        self.node = node

    def members(self):
        with self.cluster.lock:
            if self.node not in self.cluster.members:
                raise ConnectionRefusedError(f"{self.node} not serving")
            return [dict(m) for m in self.cluster.members.values()]

    def member_add(self, peer: str):
        name = peer.split("//")[1].split(":")[0]
        with self.cluster.lock:
            self.cluster.members[name] = {"name": name,
                                          "ID": self.cluster.next_id}
            self.cluster.next_id += 1
        return {"member": dict(self.cluster.members[name])}

    def member_remove(self, member_id):
        with self.cluster.lock:
            for name, m in list(self.cluster.members.items()):
                if m["ID"] == member_id:
                    del self.cluster.members[name]
                    return {}
        raise RuntimeError(f"no member {member_id}")


NODES = ["n1", "n2", "n3", "n4", "n5"]


def make_test():
    t = testing.noop_test()
    t.update(nodes=list(NODES))
    return t


def make_nemesis(cluster):
    state = etcd.EtcdMembership(http_factory=cluster.factory)
    return membership.MembershipNemesis(state, interval=0.02), state


def await_(pred, timeout=3.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


class TestStateMachine:
    def test_view_converges_from_polling(self):
        cluster = FakeCluster(NODES)
        nem, state = make_nemesis(cluster)
        test = make_test()
        nem.setup(test)
        try:
            assert await_(lambda: state.view == frozenset(NODES))
        finally:
            nem.teardown(test)

    def test_remove_then_add_cycle(self):
        cluster = FakeCluster(NODES)
        nem, state = make_nemesis(cluster)
        test = make_test()
        nem.setup(test)
        try:
            assert await_(lambda: state.view is not None)
            g = membership.MembershipGenerator(nem)
            ctx = Context.for_test({"concurrency": 2})

            def next_op():
                res = g.op(test, ctx)
                while res[0] is gen.PENDING:
                    time.sleep(0.02)
                    res = g.op(test, ctx)
                return res[0]

            # policy: shrink to the quorum floor, then grow back
            op1 = next_op()
            assert op1.f == "remove-member"
            done = nem.invoke(test, op1)
            assert done.value[1] == "removed"
            assert done.value[0] not in cluster.members
            # pending until the pollers see the new view
            assert await_(lambda: not state.pending)
            op2 = next_op()
            assert op2.f == "remove-member"
            nem.invoke(test, op2)
            assert await_(lambda: not state.pending)
            assert len(cluster.members) == 3
            # at the floor: the only legal op is adding one back
            op3 = next_op()
            assert op3.f == "add-member"
            done3 = nem.invoke(test, op3)
            assert done3.value[1] == "added"
            assert await_(lambda: not state.pending)
            assert len(cluster.members) == 4
        finally:
            nem.teardown(test)

    def test_never_removes_below_quorum(self):
        cluster = FakeCluster(NODES)
        nem, state = make_nemesis(cluster)
        test = make_test()
        nem.setup(test)
        try:
            assert await_(lambda: state.view is not None)
            removed = 0
            for _ in range(6):
                o = None

                def ready():
                    nonlocal o
                    with nem.lock:
                        o = state.op(test)
                    return o is not gen.PENDING
                if not await_(ready, timeout=1.0):
                    break
                if o["f"] != "remove-member":
                    break
                nem.invoke(test, gen.fill_in_op(
                    dict(o), Context.for_test({"concurrency": 2})))
                removed += 1
                await_(lambda: not state.pending)
            # 5 nodes: majority quorum floor is 3 -> at most 2 removals
            assert removed == 2, removed
            assert len(cluster.members) == 3
        finally:
            nem.teardown(test)

    def test_down_node_view_ignored(self):
        cluster = FakeCluster(NODES)
        state = etcd.EtcdMembership(http_factory=cluster.factory)
        test = make_test()
        # n9 isn't a member: its view poll raises and must be ignored
        assert state.node_view(test, "n9") is None

    def test_fs(self):
        cluster = FakeCluster(NODES)
        _nem, state = make_nemesis(cluster)
        assert state.fs() == {"add-member", "remove-member"}


class TestPackage:
    def test_package_gated_on_fault(self):
        assert membership.package({"faults": set()}) is None
        cluster = FakeCluster(NODES)
        pkg = etcd.membership_package({
            "faults": {"membership"},
            "membership": {"http_factory": cluster.factory,
                           "view-interval": 0.02}})
        assert pkg is not None
        assert isinstance(pkg["nemesis"], membership.MembershipNemesis)
        assert pkg["generator"] is not None

    def test_combined_packages_include_membership(self):
        from jepsen_tpu.nemesis import combined

        cluster = FakeCluster(NODES)
        state = etcd.EtcdMembership(http_factory=cluster.factory)
        pkgs = combined.nemesis_packages({
            "db": None, "faults": {"membership"},
            "membership": {"state": state}})
        assert any(isinstance(p.get("nemesis"),
                              membership.MembershipNemesis)
                   for p in pkgs if p)


class TestReviewRegressions:
    def test_missing_state_raises_helpful_error(self):
        import pytest
        with pytest.raises(ValueError, match="MembershipState"):
            membership.package({"faults": {"membership"}})

    def test_add_member_wipes_stale_data_dir(self):
        """Rejoining with a stale data dir restarts the old removed
        identity; the add path must clean it (round-3 review)."""
        from jepsen_tpu.control.core import Action
        from jepsen_tpu.control.dummy import DummyRemote

        cluster = FakeCluster(NODES)
        db = etcd.EtcdDB()
        state = etcd.EtcdMembership(http_factory=cluster.factory, db=db)
        state.view = frozenset(NODES[:4])
        state.member_ids = {n: 1000 + i for i, n in enumerate(NODES)}
        remote = DummyRemote()
        test = make_test()
        test["remote"] = remote
        test["sessions"] = {n: remote.connect({"host": n})
                            for n in NODES}
        from jepsen_tpu.history import op as mkop
        done = state.invoke(test, mkop(type="info", f="add-member",
                                       value="n5"))
        assert done.value == ["n5", "added"]
        got = [a.cmd for a in test["sessions"]["n5"].log
               if isinstance(a, Action)]
        joined = " ; ".join(got)
        assert "rm -rf /opt/etcd/n5.etcd" in joined, got
        assert "--initial-cluster-state existing" in joined, got
        assert "n5=http://n5:2380" in joined, got

    def test_package_uses_test_db_by_default(self):
        cluster = FakeCluster(NODES)
        db = etcd.EtcdDB()
        pkg = etcd.membership_package({
            "faults": {"membership"}, "db": db,
            "membership": {"http_factory": cluster.factory}})
        assert pkg["state"].db is db
