"""Resumable analysis: a crashed run is recovered by `analyze`
(doc/robustness.md). In-process crash simulations run in tier-1; the
full SIGKILL-a-subprocess e2e is marked slow."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from jepsen_tpu import checker, core, resume, store, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.store import format as fmt

SPEC = {"workload": "register",
        "opts": {"workload": "register", "nodes": ["n1", "n2"],
                 "concurrency": 2, "ssh": {"dummy": True},
                 "time_limit": 1, "ops": 40, "rate": 1000}}


def full_run(tmp_path, name="resume-full"):
    state = testing.AtomState()
    test = testing.noop_test()
    test.update(
        name=name, store_base=str(tmp_path), nodes=["n1", "n2"],
        concurrency=2, db=testing.AtomDB(state),
        client=testing.AtomClient(state, latency_s=0.0002),
        checker=checker.compose({"stats": checker.stats()}),
        spec=SPEC,
        generator=gen.clients(gen.limit(40, lambda: {"f": "read"})))
    return core.run(test)


class TestOfflineAnalyze:
    def test_reanalysis_matches_original_verdict(self, tmp_path):
        t = full_run(tmp_path)
        d = store.path(t)
        want = t["results"]["valid?"]
        t2 = resume.analyze_run(d)
        assert t2["results"]["valid?"] == want
        assert t2["results"]["analysis"]["offline?"] is True
        assert t2["results"]["analysis"]["resumed?"] is False

    def test_crashed_run_recovers_valid_prefix(self, tmp_path):
        """Simulated kill -9 mid-run: results.json never written, the
        op log has a torn tail. analyze drops the torn record and
        produces the uninterrupted verdict."""
        t = full_run(tmp_path)
        d = store.path(t)
        want = t["results"]["valid?"]
        n_ops = len(t["history"])
        # erase every post-crash artifact and tear the log tail
        (d / "results.json").unlink()
        log = d / "history.jlog"
        with open(log, "r+b") as f:
            f.truncate(log.stat().st_size - 5)
        t2 = resume.analyze_run(d, resume=True)
        assert t2["results"]["valid?"] == want
        assert t2["results"]["analysis"]["recovered-ops"] == n_ops - 1
        assert (d / "results.json").exists()

    def test_resume_reuses_partial_results_verbatim(self, tmp_path):
        """Checkers that completed before the crash are not re-run:
        their partial-log entries come back byte-for-byte."""
        t = full_run(tmp_path)
        d = store.path(t)
        (d / "results.json").unlink()
        w = fmt.PartialResultsWriter(d / "results.partial.jlog")
        w.put("stats", {"valid?": True, "marker": 42})
        w.close()
        t2 = resume.analyze_run(d, resume=True)
        res = t2["results"]
        assert res["stats"]["marker"] == 42  # reused, not re-run
        assert res["analysis"]["resumed-checkers"] == ["stats"]

    def test_resume_reruns_unknown_checkers(self, tmp_path):
        """A checker that degraded to 'unknown' (timed out, hung,
        crashed) before the crash is re-run on resume — a larger
        --checker-timeout must be able to improve the verdict."""
        t = full_run(tmp_path)
        d = store.path(t)
        (d / "results.json").unlink()
        w = fmt.PartialResultsWriter(d / "results.partial.jlog")
        w.put("stats", {"valid?": "unknown",
                        "error": "checker timed out after 60s"})
        w.close()
        t2 = resume.analyze_run(d, resume=True)
        res = t2["results"]
        assert res["stats"]["valid?"] is True  # re-run, not reused
        assert res["analysis"]["resumed-checkers"] == []

    def test_resume_preserves_orphaned_checker_results(self, tmp_path):
        """A completed checker the rebuilt (fallback) stack doesn't
        carry is merged into the results, verdict and all — it's the
        very thing --resume exists to preserve."""
        t = full_run(tmp_path)
        d = store.path(t)
        (d / "results.json").unlink()
        (d / "spec.json").unlink()  # forces the generic fallback stack
        w = fmt.PartialResultsWriter(d / "results.partial.jlog")
        w.put("workload", {"valid?": False, "marker": 7})
        w.close()
        t2 = resume.analyze_run(d, resume=True)
        res = t2["results"]
        assert res["workload"]["marker"] == 7  # kept, not dropped
        assert res["valid?"] is False  # orphan verdict merged
        assert "workload" in res["analysis"]["resumed-checkers"]

    def test_no_resume_ignores_partials(self, tmp_path):
        t = full_run(tmp_path)
        d = store.path(t)
        w = fmt.PartialResultsWriter(d / "results.partial.jlog")
        w.put("stats", {"valid?": True, "marker": 42})
        w.close()
        t2 = resume.analyze_run(d, resume=False)
        assert "marker" not in t2["results"]["stats"]

    def test_run_without_spec_falls_back(self, tmp_path):
        t = full_run(tmp_path)
        d = store.path(t)
        (d / "spec.json").unlink()
        t2 = resume.analyze_run(d)
        assert t2["results"]["valid?"] in (True, False, "unknown")
        assert t2["results"]["stats"]["valid?"] is True

    def test_unbuildable_spec_falls_back(self, tmp_path):
        """make_test sys.exits on an unknown workload; analyzing a run
        whose spec names one (suite-only workload, schema drift) must
        degrade to the generic checkers, not kill the CLI."""
        t = full_run(tmp_path)
        d = store.path(t)
        spec = json.loads((d / "spec.json").read_text())
        spec["workload"] = "no-such-workload"
        spec["opts"]["workload"] = "no-such-workload"
        (d / "spec.json").write_text(json.dumps(spec))
        t2 = resume.analyze_run(d)
        assert t2["rebuilt-from"] == "fallback"
        assert t2["results"]["stats"]["valid?"] is True

    def test_offline_analysis_preserves_degraded_marker(self, tmp_path):
        """A :degraded run re-analyzed offline keeps its quarantine
        record — no live health registry exists to recompute it."""
        t = full_run(tmp_path)
        d = store.path(t)
        prev = json.loads((d / "results.json").read_text())
        prev["degraded"] = {"quarantined-nodes": ["n2"],
                            "still-quarantined": []}
        (d / "results.json").write_text(json.dumps(prev))
        t2 = resume.analyze_run(d, resume=True)
        assert (t2["results"]["degraded"]["quarantined-nodes"]
                == ["n2"])
        on_disk = json.loads((d / "results.json").read_text())
        assert on_disk["degraded"]["quarantined-nodes"] == ["n2"]

    def test_offline_analyze_leaves_live_run_artifacts_alone(
            self, tmp_path):
        """analyze over an OLD run must not retire the store-wide
        `current` symlink (it belongs to whichever run is live) or
        rewrite the analyzed run's original test.json."""
        t = full_run(tmp_path)
        d = store.path(t)
        before = (d / "test.json").read_text()
        base = d.parent.parent
        live = base / "live-run"
        live.mkdir()
        cur = base / "current"
        if cur.is_symlink() or cur.exists():
            cur.unlink()
        cur.symlink_to(live.resolve())
        resume.analyze_run(d, resume=True)
        assert cur.is_symlink()
        assert cur.resolve() == live.resolve()
        assert (d / "test.json").read_text() == before

    def test_analyze_cli_exit_codes(self, tmp_path, monkeypatch):
        from jepsen_tpu import cli

        t = full_run(tmp_path)
        d = store.path(t)

        def rebuild(opts):
            return {"checker": checker.compose(
                {"stats": checker.stats()}), "name": "x"}

        cmds = cli.analyze_cmd(rebuild)
        with pytest.raises(SystemExit) as e:
            cli.run_cli(cmds, ["analyze", str(d), "--resume"])
        assert e.value.code == 0

    def test_analyze_cli_missing_dir(self, tmp_path):
        from jepsen_tpu import cli

        with pytest.raises(SystemExit) as e:
            cli.run_cli(cli.analyze_cmd(None),
                        ["analyze", str(tmp_path / "nope")])
        assert e.value.code == 254


@pytest.mark.slow
class TestSigkillE2E:
    """The acceptance e2e: a run SIGKILLed mid-execution is recovered
    by `analyze --resume` with the same verdict as an uninterrupted
    run."""

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _env(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = self.REPO + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        return env

    def _run_cli(self, cwd, args, **kw):
        return subprocess.run(
            [sys.executable, "-m", "jepsen_tpu", *args],
            cwd=str(cwd), env=self._env(), capture_output=True,
            text=True, **kw)

    def test_sigkill_then_analyze_resume(self, tmp_path):
        env = self._env()
        args = ["test", "--workload", "register", "--no-ssh",
                "--nodes", "n1,n2", "--concurrency", "2",
                "--time-limit", "30", "--rate", "50"]
        # uninterrupted control run (short)
        ctl = self._run_cli(
            tmp_path, [*args[:-4], "--time-limit", "3", "--rate", "50"])
        assert ctl.returncode == 0, ctl.stderr[-2000:]
        ctl_results = json.loads(
            (tmp_path / "store" / "latest" / "results.json")
            .resolve().read_text())
        want = ctl_results["valid?"]

        # the victim: SIGKILL mid-execution
        runs_dir = tmp_path / "store" / "register-demo"
        before = {p.name for p in runs_dir.glob("2*")}
        proc = subprocess.Popen(
            [sys.executable, "-m", "jepsen_tpu", *args],
            cwd=str(tmp_path), env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        victim = None
        deadline = time.time() + 60
        while time.time() < deadline:
            dirs = [p for p in runs_dir.glob("2*")
                    if p.name not in before
                    and (p / "history.jlog").exists()
                    and (p / "history.jlog").stat().st_size > 4096]
            if dirs:
                victim = sorted(dirs)[-1]
                break
            if proc.poll() is not None:
                break
            time.sleep(0.25)
        assert victim is not None, "victim run never produced history"
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        assert not (victim / "results.json").exists()

        out = self._run_cli(tmp_path,
                            ["analyze", str(victim), "--resume"])
        assert out.returncode == 0, (out.stdout[-2000:],
                                     out.stderr[-2000:])
        got = json.loads((victim / "results.json").read_text())
        assert got["valid?"] == want
        assert got["analysis"]["resumed?"] is True
        assert got["analysis"]["recovered-ops"] > 0
