"""ZooKeeper suite tests: DB command emission via the dummy remote and
a clusterless end-to-end run against a scripted zkCli (mirrors
zookeeper/src/jepsen/zookeeper.clj)."""

import re
import threading

from jepsen_tpu import checker as chk
from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.checker import models
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.suites import zookeeper as zk


def make_test(responder=None, nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return t


def cmds(test, node):
    return [a.cmd for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_setup_commands(self):
        test = make_test()
        db = zk.ZkDB("3.4.13-2")
        with control.with_session(test, "n2"):
            db.setup(test, "n2")
        acts = [a for a in test["sessions"]["n2"].log
                if isinstance(a, Action)]
        got = " ; ".join(a.cmd for a in acts)
        assert "zookeeper=3.4.13-2" in got
        assert "echo 1 > /etc/zookeeper/conf/myid" in got  # n2 -> id 1
        cfg = next(a.stdin for a in acts
                   if a.stdin and "zoo.cfg" in a.cmd)
        assert "server.0=n1:2888:3888" in cfg
        assert "server.2=n3:2888:3888" in cfg
        assert "clientPort=2181" in cfg
        assert "service zookeeper start" in got

    def test_teardown_wipes_state(self):
        test = make_test()
        db = zk.ZkDB()
        with control.with_session(test, "n1"):
            db.teardown(test, "n1")
        got = " ; ".join(cmds(test, "n1"))
        assert "service zookeeper stop" in got
        assert "/var/lib/zookeeper/version-*" in got


class FakeZk:
    """In-memory zk node with dataVersion, scripted through the dummy
    remote's responder (commands arrive as one zkCli argv string)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.value = None
        self.version = -1

    def responder(self, node, action):
        cmd = action.cmd
        if "zkCli.sh" not in cmd:
            return None
        m = re.search(r"zkCli\.sh -server \S+ (.+)$", cmd)
        args = m.group(1).replace("'", "").split()
        with self.lock:
            if args[0] == "get":
                if self.value is None:
                    return Result(exit=1, out="",
                                  err="NoNode for /jepsen", cmd=cmd)
                return Result(
                    exit=0, err="",
                    out=f"{self.value}\ndataVersion = {self.version}\n",
                    cmd=cmd)
            if args[0] == "create":
                if self.value is None:
                    self.value = int(args[2])
                    self.version = 0
                    return Result(exit=0, out="Created", err="", cmd=cmd)
                return Result(exit=1, out="", err="NodeExists", cmd=cmd)
            if args[0] == "set":
                if self.value is None:
                    return Result(exit=1, out="", err="NoNode", cmd=cmd)
                if len(args) >= 4:  # set path data version (3.4 cas)
                    if int(args[3]) != self.version:
                        return Result(
                            exit=1, out="",
                            err="KeeperErrorCode = BadVersion for "
                                "/jepsen", cmd=cmd)
                self.value = int(args[2])
                self.version += 1
                return Result(exit=0, out="", err="", cmd=cmd)
        return Result(exit=1, out="", err=f"unknown {args}", cmd=cmd)


class TestClient:
    def test_ops_against_fake(self):
        from jepsen_tpu.history import op

        fake = FakeZk()
        test = make_test(fake.responder, nodes=("n1",))
        c = zk.ZkCasClient().open(test, "n1")
        done = c.invoke(test, op(type="invoke", f="read", value=None))
        assert done.type == "ok" and done.value == 0  # auto-created
        done = c.invoke(test, op(type="invoke", f="write", value=3))
        assert done.type == "ok"
        done = c.invoke(test, op(type="invoke", f="cas", value=[3, 4]))
        assert done.type == "ok"
        done = c.invoke(test, op(type="invoke", f="cas", value=[9, 1]))
        assert done.type == "fail"
        done = c.invoke(test, op(type="invoke", f="read", value=None))
        assert done.value == 4

    def test_end_to_end_linearizable(self):
        import random

        from jepsen_tpu.workloads import register as register_wl

        fake = FakeZk()
        test = make_test(fake.responder, nodes=("n1", "n2"))
        rng = random.Random(4)

        def one():
            return register_wl.cas_op_mix(rng, n_values=3)

        test.update(concurrency=4, client=zk.ZkCasClient(),
                    checker=chk.linearizable(
                        {"model": models.cas_register(0)}),
                    generator=gen.clients(gen.limit(120, one)))
        test = core.run(test)
        assert test["results"]["valid?"] is True, test["results"]


class TestBundle:
    def test_zk_test_shape(self):
        t = zk.zk_test({"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                        "time_limit": 1, "seed": 2})
        assert t["name"] == "zookeeper"
        assert isinstance(t["db"], zk.ZkDB)
        assert t["checker"] is not None
