"""CLI + web UI tests: option parsing, exit codes, store browsing."""

import argparse
import json
import socket
import urllib.error
import urllib.request

import pytest

from jepsen_tpu import cli, util


def parse(argv):
    p = argparse.ArgumentParser()
    cli.add_test_opts(p)
    return cli.test_opt_fn(p.parse_args(argv))


def test_default_options():
    o = parse([])
    assert o["nodes"] == cli.DEFAULT_NODES
    assert o["concurrency"] == 5  # 1n x 5 nodes
    assert o["time_limit"] == 60
    assert o["test_count"] == 1
    assert o["ssh"]["username"] == "root"
    assert o["ssh"]["dummy"] is False


def test_nodes_parsing():
    assert parse(["--nodes", "a,b, c"])["nodes"] == ["a", "b", "c"]
    assert parse(["-n", "x", "-n", "y"])["nodes"] == ["x", "y"]


def test_nodes_file(tmp_path):
    f = tmp_path / "nodes"
    f.write_text("h1\nh2\n\n")
    assert parse(["--nodes-file", str(f)])["nodes"] == ["h1", "h2"]


def test_concurrency_2n():
    o = parse(["--nodes", "a,b,c", "--concurrency", "2n"])
    assert o["concurrency"] == 6
    o = parse(["--concurrency", "7"])
    assert o["concurrency"] == 7
    assert util.coll_scaled("3n", 4) == 12


def test_ssh_options():
    o = parse(["--no-ssh", "--username", "admin",
               "--ssh-private-key", "/id"])
    assert o["ssh"] == {"username": "admin", "password": "root",
                       "strict_host_key_checking": False,
                       "private_key_path": "/id", "dummy": True}


def test_run_cli_unknown_command(capsys):
    with pytest.raises(SystemExit) as e:
        cli.run_cli({"test": {"run": lambda o: 0}}, ["bogus"])
    assert e.value.code == 254
    assert "Commands:" in capsys.readouterr().out


def test_run_cli_exit_codes():
    for ret, expect in [(0, 0), (1, 1), (2, 2), (None, 0)]:
        with pytest.raises(SystemExit) as e:
            cli.run_cli({"go": {"run": lambda o, r=ret: r}}, ["go"])
        assert e.value.code == expect
    with pytest.raises(SystemExit) as e:
        cli.run_cli({"go": {"run": lambda o: 1 / 0}}, ["go"])
    assert e.value.code == 255


def test_test_all_summary_and_exit(capsys):
    results = {True: ["a"], False: ["b"], "unknown": ["c"]}
    cli.test_all_print_summary(results)
    out = capsys.readouterr().out
    assert "# Successful tests" in out and "# Failed tests" in out
    assert "1 successes" in out
    assert cli.test_all_exit_code(results) == 2  # unknown beats invalid
    assert cli.test_all_exit_code({True: ["a"]}) == 0
    assert cli.test_all_exit_code({False: ["a"]}) == 1
    assert cli.test_all_exit_code({"crashed": ["a"]}) == 255


def test_single_test_cmd_runs_clusterless(tmp_path, monkeypatch):
    """`python -m jepsen_tpu test --workload register --no-ssh` works
    (VERDICT round 1 item 5)."""
    monkeypatch.chdir(tmp_path)
    from jepsen_tpu.__main__ import main

    with pytest.raises(SystemExit) as e:
        main(["test", "--workload", "register", "--no-ssh",
              "--time-limit", "3", "--ops", "120",
              "--nodes", "n1,n2,n3"])
    assert e.value.code == 0
    d = tmp_path / "store" / "register-demo" / "latest"
    assert (d / "results.json").exists()
    assert json.loads((d / "results.json").read_text())["valid?"] is True
    assert (d / "timeline.html").exists()
    assert (d / "rate.png").exists()


def test_web_ui(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    # a fake stored test
    d = tmp_path / "store" / "demo" / "20260729T000000.0000"
    d.mkdir(parents=True)
    (d / "results.json").write_text('{"valid?": true}')
    (d / "jepsen.log").write_text("hello log")

    from jepsen_tpu import web

    server = web.serve("127.0.0.1", 0, base=tmp_path / "store")
    port = server.server_address[1]
    try:
        base = f"http://127.0.0.1:{port}"
        home = urllib.request.urlopen(base + "/").read().decode()
        assert "demo" in home and "20260729T000000.0000" in home
        res = urllib.request.urlopen(
            base + "/files/demo/20260729T000000.0000/results.json")
        assert json.loads(res.read())["valid?"] is True
        listing = urllib.request.urlopen(
            base + "/files/demo/20260729T000000.0000/").read().decode()
        assert "jepsen.log" in listing
        zipb = urllib.request.urlopen(
            base + "/zip/demo/20260729T000000.0000").read()
        assert zipb[:2] == b"PK"
        # telemetry page: 404 without artifacts, rendered with them
        with pytest.raises(urllib.error.HTTPError) as he:
            urllib.request.urlopen(
                base + "/telemetry/demo/20260729T000000.0000")
        assert he.value.code == 404
        (d / "telemetry.jsonl").write_text(
            '{"id": 1, "parent": null, "name": "run", "t0": 0, '
            '"t1": 5000000}\n')
        (d / "metrics.json").write_text(
            '{"spans": {}, "counters": {"wgl.kernel.launches": 2}, '
            '"gauges": {}}')
        page = urllib.request.urlopen(
            base + "/telemetry/demo/20260729T000000.0000"
        ).read().decode()
        assert "run" in page and "wgl.kernel.launches" in page
        assert "5.0ms" in page
        home = urllib.request.urlopen(base + "/").read().decode()
        assert "/telemetry/demo/" in home
        # raw-socket path traversal (urllib would normalize ..)
        with socket.create_connection(("127.0.0.1", port)) as s:
            s.sendall(b"GET /files/../../../etc/passwd HTTP/1.0\r\n"
                      b"Host: x\r\n\r\n")
            reply = s.makefile("rb").read().decode()
        assert "404" in reply.splitlines()[0]
        assert "root:" not in reply
    finally:
        server.shutdown()


def test_runner_covers_every_workload():
    """Every REGISTRY workload has an in-memory client so the generic
    runner (and its test-all sweep) runs clusterless."""
    from jepsen_tpu import workloads
    from jepsen_tpu.__main__ import CLIENTS

    assert set(CLIENTS) == set(workloads.REGISTRY)


def test_runner_new_workloads_end_to_end():
    from jepsen_tpu import core
    from jepsen_tpu.__main__ import make_test

    for name in ("kafka", "causal", "causal-reverse", "adya-g2"):
        opts = {"workload": name, "nodes": ["n1"], "concurrency": 2,
                "ssh": {"dummy": True}, "ops": 40, "time_limit": 20,
                "rate": 5000}
        t = make_test(opts)
        t.pop("name")
        t = core.run(t)
        assert t["results"]["valid?"] in (True, "unknown"), (
            name, t["results"])


def test_runner_paired_workloads_tolerate_odd_concurrency():
    """Pair-based generators park the last thread instead of failing
    the divisibility assert (round-3 review finding)."""
    from jepsen_tpu import core
    from jepsen_tpu.__main__ import make_test

    for name in ("adya-g2", "causal-reverse"):
        opts = {"workload": name, "nodes": ["n1"], "concurrency": 5,
                "ssh": {"dummy": True}, "ops": 30, "time_limit": 20,
                "rate": 5000}
        t = make_test(opts)
        t.pop("name")
        t = core.run(t)
        assert t["results"]["valid?"] in (True, "unknown"), name
