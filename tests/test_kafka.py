"""Kafka queue workload tests: one hand-written history per anomaly
class the reference's analysis detects
(jepsen/src/jepsen/tests/kafka.clj:1881-2087), plus the allowed-error
policy and an end-to-end run against an in-memory log."""

from jepsen_tpu.history import History, op
from jepsen_tpu.workloads import kafka


def K(*events):
    """history from (type, process, f, value) tuples."""
    return History([op(type=t, process=p, f=f, value=v)
                    for t, p, f, v in events])


def send_ok(p, k, off, val):
    return (("invoke", p, "send", [["send", k, val]]),
            ("ok", p, "send", [["send", k, [off, val]]]))


def poll_ok(p, reads):
    """reads: {k: [[off, val], ...]}"""
    return (("invoke", p, "poll", [["poll"]]),
            ("ok", p, "poll", [["poll", reads]]))


def flat(*pairs):
    evs = []
    for pr in pairs:
        evs.extend(pr)
    return K(*evs)


class TestValid:
    def test_clean_send_poll(self):
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 poll_ok(1, {0: [[0, 1], [1, 2]]}))
        res = kafka.check(h)
        assert res["valid?"] is True, res

    def test_offset_gaps_are_fine(self):
        # txn metadata takes offset slots; contiguity is rank-based
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 5, 2),
                 poll_ok(1, {0: [[0, 1], [5, 2]]}))
        res = kafka.check(h)
        assert res["valid?"] is True, res


class TestAnomalies:
    def test_inconsistent_offsets(self):
        # two observations disagree about the value at offset 0
        h = flat(send_ok(0, 0, 0, 1), send_ok(1, 0, 0, 2),
                 poll_ok(2, {0: [[0, 1]]}))
        res = kafka.check(h)
        assert res["valid?"] is False
        assert "inconsistent-offsets" in res["bad-error-types"], res

    def test_g1a_aborted_read(self):
        h = K(("invoke", 0, "send", [["send", 0, 9]]),
              ("fail", 0, "send", [["send", 0, 9]]),
              *poll_ok(1, {0: [[0, 9]]}))
        res = kafka.check(h)
        assert res["valid?"] is False
        assert "G1a" in res["bad-error-types"], res

    def test_lost_write(self):
        # v=1 acked at offset 0, never polled; poll sees offset 1
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 poll_ok(1, {0: [[1, 2]]}))
        res = kafka.check(h)
        assert res["valid?"] is False
        assert "lost-write" in res["bad-error-types"], res

    def test_unseen_is_an_error(self):
        # acked above the highest polled offset: not lost, but if
        # nobody EVER polls it, the history ends with an unseen error
        # (kafka.clj last-unseen -> :errors)
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 poll_ok(1, {0: [[0, 1]]}))
        res = kafka.check(h)
        assert res["valid?"] is False, res
        assert "unseen" in res["bad-error-types"]
        assert res["unseen"] == {0: 1}
        assert res["errors"]["unseen"][0] == {
            "key": 0, "count": 1, "messages": [2]}

    def test_drained_history_has_no_unseen(self):
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 poll_ok(1, {0: [[0, 1], [1, 2]]}))
        res = kafka.check(h)
        assert res["valid?"] is True, res
        assert res["unseen"] == {}

    def test_wr_links_all_reads_not_just_highest(self):
        # T2 polls k0 and sees BOTH T1's value (rank 0) and T3's
        # (rank 1): the wr edge T1->T2 must exist even though T1's
        # value is not T2's highest read — the cycle with T2->T1 via
        # k1 closes only through that older read (wr-graph,
        # kafka.clj:1840-1852).
        h = flat(
            send_ok(3, 0, 1, 30),  # k0 rank 1 writer (the highest)
            (("invoke", 1, "txn", [["send", 0, 10], ["poll"]]),
             ("invoke", 2, "txn", [["send", 1, 20], ["poll"]]),
             # TA: writes k0=10 (rank 0), polls k1 and sees 20
             ("ok", 1, "txn", [["send", 0, [0, 10]],
                               ["poll", {1: [[0, 20]]}]]),
             # TB: writes k1=20, polls k0 seeing BOTH ranks —
             # TA's value is NOT its highest read
             ("ok", 2, "txn", [["send", 1, [0, 20]],
                               ["poll", {0: [[0, 10], [1, 30]]}]])),
        )
        res = kafka.check(h, {"ww-deps": False})
        assert any(t.startswith("G1c") for t in res["error-types"]), \
            res
        assert res["valid?"] is False

    def test_duplicate_offsets(self):
        # same value observed at two offsets
        h = flat(send_ok(0, 0, 0, 7),
                 poll_ok(1, {0: [[0, 7], [3, 7]]}))
        res = kafka.check(h)
        assert res["valid?"] is False
        assert "duplicate-offsets" in res["bad-error-types"], res

    def test_duplicate_writes(self):
        h = flat(send_ok(0, 0, 0, 7), send_ok(1, 0, 3, 7))
        res = kafka.check(h)
        assert res["valid?"] is False
        assert "duplicate" in res["bad-error-types"], res

    def test_int_poll_skip(self):
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 send_ok(0, 0, 2, 3),
                 (("invoke", 1, "txn", [["poll"], ["poll"]]),
                  ("ok", 1, "txn", [["poll", {0: [[0, 1]]}],
                                    ["poll", {0: [[2, 3]]}]])))
        res = kafka.check(h)
        assert res["valid?"] is False
        assert "int-poll-skip" in res["bad-error-types"], res

    def test_int_nonmonotonic_poll(self):
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 (("invoke", 1, "txn", [["poll"], ["poll"]]),
                  ("ok", 1, "txn", [["poll", {0: [[1, 2]]}],
                                    ["poll", {0: [[0, 1]]}]])))
        res = kafka.check(h)
        assert res["valid?"] is False
        assert "int-nonmonotonic-poll" in res["bad-error-types"], res

    def test_external_nonmonotonic_poll_assign_mode(self):
        # without subscribe in sub-via, external poll regressions count
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 poll_ok(1, {0: [[1, 2]]}),
                 poll_ok(1, {0: [[0, 1]]}))
        res = kafka.check(h, {"sub-via": ("assign",)})
        assert res["valid?"] is False
        assert "nonmonotonic-poll" in res["bad-error-types"], res
        # with subscribe, rebalances make this expected
        res = kafka.check(h, {"sub-via": ("subscribe",)})
        assert res["valid?"] is True, res

    def test_poll_skip_reset_by_subscribe(self):
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 send_ok(0, 0, 2, 3),
                 poll_ok(1, {0: [[0, 1]]}),
                 ((("invoke", 1, "subscribe", [0]),
                   ("ok", 1, "subscribe", [0]))),
                 poll_ok(1, {0: [[2, 3]]}))
        res = kafka.check(h, {"sub-via": ("assign",)})
        # subscribe resets the consumer's expected position
        assert "poll-skip" not in res["error-types"], res

    def test_nonmonotonic_send(self):
        h = flat(send_ok(0, 0, 5, 1), send_ok(0, 0, 2, 2),
                 poll_ok(1, {0: [[2, 2], [5, 1]]}))
        res = kafka.check(h)
        assert res["valid?"] is False
        assert "nonmonotonic-send" in res["bad-error-types"], res

    def test_int_send_skip_allowed(self):
        # txn writes interleave in kafka's model: always allowed
        h = flat((("invoke", 0, "send",
                   [["send", 0, 1], ["send", 0, 2]]),
                  ("ok", 0, "send",
                   [["send", 0, [0, 1]], ["send", 0, [4, 2]]])),
                 send_ok(1, 0, 2, 9),
                 poll_ok(2, {0: [[0, 1], [2, 9], [4, 2]]}))
        res = kafka.check(h)
        assert "int-send-skip" in res["error-types"]
        assert "int-send-skip" not in res["bad-error-types"]

    def test_wr_cycle_without_ww_deps(self):
        # T1 reads T2's write and vice versa: G1c, bad when ww-deps off
        h = K(("invoke", 0, "txn", [["send", 0, 1], ["poll"]]),
              ("invoke", 1, "txn", [["send", 1, 2], ["poll"]]),
              ("ok", 0, "txn", [["send", 0, [0, 1]],
                                ["poll", {1: [[0, 2]]}]]),
              ("ok", 1, "txn", [["send", 1, [0, 2]],
                                ["poll", {0: [[0, 1]]}]]))
        res = kafka.check(h, {"ww-deps": False})
        assert res["valid?"] is False
        assert any(t.startswith("G1c") for t in res["bad-error-types"]), res
        # with ww-deps, G1c is expected (no write isolation)
        res = kafka.check(h, {"ww-deps": True})
        assert res["valid?"] is True, res


class TestEndToEnd:
    def test_generated_run_against_memory_log(self):
        """Drive the generator against an in-memory kafka-like log and
        check the result is clean."""
        import random

        rng = random.Random(3)
        gen_fn = kafka.generator(n_keys=3, seed=3)
        logs: dict = {}
        positions: dict = {}  # (proc, k) -> next index
        events = []
        for i in range(400):
            p = i % 4
            o = gen_fn()
            f, v = o["f"], o["value"]
            events.append(("invoke", p, f, v))
            if f in ("subscribe", "assign"):
                for k in v:
                    positions[(p, k)] = 0
                events.append(("ok", p, f, v))
                continue
            done = []
            for m in v:
                if m[0] == "send":
                    _, k, val = m
                    logs.setdefault(k, []).append(val)
                    done.append(["send", k, [len(logs[k]) - 1, val]])
                else:
                    reads: dict = {}
                    for k in list(logs):
                        pos = positions.get((p, k), 0)
                        log = logs.get(k, [])
                        if pos < len(log):
                            n = rng.randint(1, len(log) - pos)
                            reads[k] = [[pos + j, log[pos + j]]
                                        for j in range(n)]
                            positions[(p, k)] = pos + n
                    done.append(["poll", reads])
            events.append(("ok", p, f, done))
        h = K(*events)
        res = kafka.check(h, {"sub-via": ("assign",)})
        assert res["valid?"] is True, (res["bad-error-types"],
                                       res["errors"])

    def test_workload_bundle(self):
        w = kafka.workload({"ops": 10, "seed": 1})
        assert "generator" in w and "checker" in w


def assign_ok(p, keys):
    return (("invoke", p, "assign", keys), ("ok", p, "assign", keys))


class TestAssignMode:
    """ISSUE-4 satellite (VERDICT weak #5): the sub-via consumer
    policy (kafka.clj:2019-2046 — poll-skip/nonmonotonic-poll are
    legal under subscribe, errors under assign) and the assignment
    reset branch in Analysis._contiguity, exercised through an
    explicit assign-mode history."""

    def _skip_history(self, mid=None):
        """Consumer 1 polls offset-rank 0 then rank 2 (an external
        poll skip); consumer 2 drains everything so no lost/unseen
        noise muddies the verdict. `mid` rides between consumer 1's
        polls."""
        pairs = [send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 send_ok(0, 0, 2, 3),
                 assign_ok(1, [0]),
                 poll_ok(1, {0: [[0, 1]]})]
        if mid is not None:
            pairs.append(mid)
        pairs += [poll_ok(1, {0: [[2, 3]]}),
                  assign_ok(2, [0]),
                  poll_ok(2, {0: [[0, 1], [1, 2], [2, 3]]})]
        return flat(*pairs)

    def test_poll_skip_allowed_under_subscribe(self):
        res = kafka.check(self._skip_history())
        assert "poll-skip" in res["error-types"], res
        assert res["valid?"] is True, res

    def test_poll_skip_flagged_in_assign_mode(self):
        res = kafka.check(self._skip_history(),
                          {"sub-via": ("assign",)})
        assert res["valid?"] is False
        assert "poll-skip" in res["bad-error-types"], res

    def test_reassign_resets_external_poll_tracking(self):
        # an ok re-assign between the polls legitimately moves the
        # consumer (kafka.py _contiguity's reset branch): no skip,
        # even in assign mode
        res = kafka.check(self._skip_history(mid=assign_ok(1, [0])),
                          {"sub-via": ("assign",)})
        assert "poll-skip" not in res["error-types"], res
        assert res["valid?"] is True, res

    def test_checker_reads_sub_via_from_test_map(self):
        c = kafka.checker()
        res = c.check({"sub-via": ("assign",)}, self._skip_history(),
                      {})
        assert "poll-skip" in res["bad-error-types"], res


class TestReviewRegressions:
    def test_info_send_offsets_count(self):
        """An indeterminate send that still reports its offset must
        feed the version order (round-3 review finding)."""
        h = K(("invoke", 0, "send", [["send", 0, 5]]),
              ("info", 0, "send", [["send", 0, [0, 5]]]),
              *send_ok(1, 0, 0, 9),
              *poll_ok(2, {0: [[0, 9]]}))
        res = kafka.check(h)
        assert res["valid?"] is False
        assert "inconsistent-offsets" in res["bad-error-types"], res

    def test_failed_subscribe_does_not_reset_tracking(self):
        h = flat(send_ok(0, 0, 0, 1), send_ok(0, 0, 1, 2),
                 send_ok(0, 0, 2, 3),
                 poll_ok(1, {0: [[0, 1]]}),
                 (("invoke", 1, "subscribe", [0]),
                  ("fail", 1, "subscribe", [0])),
                 poll_ok(1, {0: [[2, 3]]}))
        res = kafka.check(h, {"sub-via": ("assign",)})
        assert "poll-skip" in res["bad-error-types"], res

    def test_registry_has_kafka(self):
        from jepsen_tpu import workloads
        assert workloads.REGISTRY["kafka"] is kafka.workload
