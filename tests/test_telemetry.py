"""Telemetry subsystem tests: span nesting (within and across
threads), counter/gauge aggregation, JSONL + metrics round-trips from
the store, the span-tree renderers, and the instrumented pipeline —
a clusterless run() must leave phase spans, interpreter counters, and
device-kernel profile values behind."""

import json
import random
import threading

from jepsen_tpu import checker, core, store, telemetry, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.checker import models
from jepsen_tpu.reports import telemetry as rtel


class TestRecorder:
    def test_span_nesting_same_thread(self):
        t = telemetry.Telemetry()
        with t.span("outer"):
            with t.span("inner"):
                pass
        inner, outer = t.events()  # completion order
        assert (inner["name"], outer["name"]) == ("inner", "outer")
        assert inner["parent"] == outer["id"]
        assert outer["parent"] is None
        assert outer["t0"] <= inner["t0"] <= inner["t1"] <= outer["t1"]

    def test_span_nesting_across_threads(self):
        """Each thread keeps its own span stack: spans opened on
        worker threads are roots (never children of another thread's
        open span), and their own children nest under them."""
        t = telemetry.Telemetry()
        ready = threading.Barrier(3)

        def worker(i):
            with t.span(f"w{i}"):
                with t.span(f"w{i}-child"):
                    ready.wait(timeout=5)

        with t.span("main"):
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(2)]
            for th in threads:
                th.start()
            ready.wait(timeout=5)  # all three spans open concurrently
            for th in threads:
                th.join()
        by_name = {e["name"]: e for e in t.events()}
        assert by_name["main"]["parent"] is None
        for i in range(2):
            assert by_name[f"w{i}"]["parent"] is None
            assert (by_name[f"w{i}-child"]["parent"]
                    == by_name[f"w{i}"]["id"])

    def test_counter_aggregation_across_threads(self):
        t = telemetry.Telemetry()

        def bump():
            for _ in range(1000):
                t.count("hits")
                t.count("bytes", 3)

        threads = [threading.Thread(target=bump) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert t.counters() == {"hits": 4000, "bytes": 12000}
        t.gauge("occupancy", 0.5)
        t.gauge("occupancy", 0.75)  # last write wins
        assert t.gauges() == {"occupancy": 0.75}
        t.gauge_max("largest", 50)
        t.gauge_max("largest", 2)   # max survives later smaller sets
        assert t.gauges()["largest"] == 50

    def test_metrics_aggregates_spans(self):
        t = telemetry.Telemetry()
        for _ in range(3):
            with t.span("x"):
                pass
        m = t.metrics()
        assert m["spans"]["x"]["count"] == 3
        assert m["spans"]["x"]["total_ns"] >= m["spans"]["x"]["max_ns"]

    def test_disabled_recorder_records_nothing(self):
        t = telemetry.Telemetry(enabled=False)
        with t.span("x"):
            t.count("c")
            t.gauge("g", 1)
        assert t.events() == []
        assert t.counters() == {} and t.gauges() == {}

    def test_reset_bumps_epoch(self):
        """Deferred flushers (interpreter workers) use the epoch to
        detect an intervening reset and drop stale tallies."""
        t = telemetry.Telemetry()
        e0 = t.epoch
        t.count("n")
        t.reset()
        assert t.epoch == e0 + 1
        assert t.counters() == {}
        # a span completing after an intervening reset is dropped too:
        # its id and clock origin belong to the previous run
        with t.span("stale"):
            t.reset()
        assert t.events() == []

    def test_timed_decorator(self):
        t = telemetry.Telemetry()

        @t.timed("f")
        def f(x):
            return x + 1

        assert f(1) == 2
        assert t.metrics()["spans"]["f"]["count"] == 1


class TestRoundTrip:
    def test_jsonl_and_metrics_roundtrip(self, tmp_path):
        t = telemetry.Telemetry()
        with t.span("a", phase="case"):
            with t.span("b"):
                pass
        t.count("n", 2)
        t.gauge("g", 1.5)
        trace, metrics = t.save(tmp_path)
        back = list(telemetry.read_events(trace))
        assert [e["name"] for e in back] == ["b", "a"]
        assert back[1]["attrs"] == {"phase": "case"}
        assert back[0]["parent"] == back[1]["id"]
        m = telemetry.read_metrics(metrics)
        assert m["counters"] == {"n": 2}
        assert m["gauges"] == {"g": 1.5}
        assert m["spans"]["a"]["count"] == 1

    def test_torn_trailing_line_dropped(self, tmp_path):
        t = telemetry.Telemetry()
        with t.span("a"):
            pass
        trace, _metrics = t.save(tmp_path)
        with open(trace, "a") as f:
            f.write('{"name": "torn')  # writer died mid-line
        assert [e["name"] for e in telemetry.read_events(trace)] == ["a"]

    def test_missing_artifacts(self, tmp_path):
        assert list(telemetry.read_events(tmp_path / "nope.jsonl")) == []
        assert telemetry.read_metrics(tmp_path / "nope.json") is None
        events, metrics = store.load_telemetry(tmp_path)
        assert events == [] and metrics is None


class TestRendering:
    def test_span_tree_lines(self):
        t = telemetry.Telemetry()
        with t.span("run"):
            with t.span("case"):
                pass
            with t.span("analyze"):
                pass
        lines = rtel.span_tree_lines(t.events())
        assert lines[0].startswith("run")
        assert lines[1].startswith("  case")
        assert lines[2].startswith("  analyze")

    def test_text_and_html_render(self):
        t = telemetry.Telemetry()
        with t.span("run"):
            t.count("wgl.kernel.compile_ns", 12_000_000)
            t.gauge("wgl.batch.occupancy", 0.5)
        text = rtel.telemetry_text(t.events(), t.metrics())
        assert "run" in text and "wgl.kernel.compile_ns" in text
        assert "12.0ms" in text  # _ns counters render as durations
        html = rtel.telemetry_html("demo", t.events(), t.metrics())
        assert "wgl.batch.occupancy" in html and "<table" in html


class TestPipeline:
    def test_clusterless_run_records_phases_and_artifacts(self, tmp_path):
        from jepsen_tpu.workloads import register as register_wl

        state = testing.AtomState()
        rng = random.Random(7)
        test = testing.noop_test()
        test.update(
            name="telemetry-e2e", store_base=str(tmp_path),
            nodes=["n1", "n2"], concurrency=4,
            client=testing.AtomClient(state),
            checker=checker.compose({
                "linear": checker.linearizable(
                    {"model": models.cas_register()}),
                "stats": checker.stats()}),
            generator=gen.clients(gen.limit(
                60, lambda: register_wl.cas_op_mix(rng, n_values=3))))
        test = core.run(test)
        assert test["results"]["valid?"] is True, test["results"]

        # the :telemetry summary rides in the results
        summ = test["results"]["telemetry"]
        for phase in ("run", "os-setup", "db-cycle", "case",
                      "snarf-logs", "teardown-db", "teardown-os",
                      "analyze"):
            assert phase in summ["phases"], (phase, summ["phases"])
        assert summ["phases"]["run"] >= summ["phases"]["case"] > 0
        # per-checker timings
        assert set(summ["checkers"]) >= {"linear", "stats"}
        c = summ["counters"]
        assert c["interpreter.dispatched"] == 60
        assert c.get("interpreter.ops.ok", 0) > 0
        assert c["interpreter.invoke_ns"] > 0
        # the linearizable checker went through the device kernel
        assert c.get("wgl.batch.histories", 0) >= 1
        assert c.get("wgl.kernel.launches", 0) >= 1
        assert c.get("wgl.kernel.iterations", 0) >= 1

        # artifacts land in the store directory and read back
        d = store.path(test)
        assert (d / "telemetry.jsonl").exists()
        assert (d / "metrics.json").exists()
        events, metrics = store.load_telemetry(d)
        names = {e["name"] for e in events}
        assert {"run", "case", "analyze", "checker:linear"} <= names
        assert (metrics["counters"]["interpreter.dispatched"]
                == c["interpreter.dispatched"])
        # results.json carries the summary too
        with open(d / "results.json") as f:
            saved = json.load(f)
        assert "telemetry" in saved

    def test_cli_telemetry_subcommand(self, tmp_path, capsys):
        import pytest

        from jepsen_tpu import cli

        state = testing.AtomState()
        test = testing.noop_test()
        test.update(
            name="telemetry-cli", store_base=str(tmp_path),
            nodes=["n1"], concurrency=2,
            client=testing.AtomClient(state),
            checker=checker.stats(),
            generator=gen.clients(gen.limit(10, lambda: {"f": "read"})))
        test = core.run(test)
        d = store.path(test)
        with pytest.raises(SystemExit) as e:
            cli.run_cli(cli.telemetry_cmd(), ["telemetry", str(d)])
        assert e.value.code == 0
        out = capsys.readouterr().out
        assert "# Spans" in out and "run" in out
        assert "interpreter.dispatched" in out

    def test_crashed_invokes_still_count_client_time(self):
        """A client that waits then raises must still contribute its
        wait to interpreter.invoke_ns — timeout-heavy runs would
        otherwise show near-zero client time next to a pile of
        worker-crashes."""
        import time as _t

        from jepsen_tpu import client as jclient
        from jepsen_tpu import interpreter, util

        class SlowCrash(jclient.Client):
            def open(self, test, node):
                return self

            def invoke(self, test, op):
                _t.sleep(0.02)
                raise RuntimeError("timeout")

        telemetry.reset()
        util.init_relative_time()
        t = testing.noop_test()
        t.update(concurrency=1, client=SlowCrash(),
                 generator=gen.on_threads({0}, gen.limit(
                     3, gen.repeat({"f": "w"}))))
        t = interpreter.run(dict(t))
        c = telemetry.get().counters()
        assert c["interpreter.worker-crashes"] == 3
        assert c["interpreter.invoke_ns"] >= 3 * 15_000_000

    def test_nemesis_spans_recorded(self):
        from jepsen_tpu import nemesis as jnemesis
        from jepsen_tpu.history import op

        telemetry.reset()
        nem = jnemesis.validate(jnemesis.noop).setup({})
        nem.invoke({}, op(type="info", process="nemesis", f="start"))
        names = [e["name"] for e in telemetry.get().events()]
        assert "nemesis:setup" in names and "nemesis:start" in names


class TestKernelMetrics:
    def test_batched_check_reports_kernel_profile(self):
        """A batched wgl check must leave nonzero compile-time,
        while-loop iteration, and batch-occupancy values behind."""
        from jepsen_tpu.checker import models as m2
        from jepsen_tpu.tpu import synth, wgl
        from jepsen_tpu.tpu.encode import encode

        telemetry.reset()
        model = m2.cas_register()
        encs = [encode(model, synth.register_history(
            120, n_procs=3, seed=50 + i)) for i in range(4)]
        # nonstandard W/F pin a fresh compile bucket even when earlier
        # tests in this process warmed the default 32/64 kernel
        res = wgl.check_batch(encs, W=20, F=24)
        assert (res == wgl.VALID).all()
        c = telemetry.get().counters()
        assert c["wgl.kernel.compiles"] >= 1
        assert c["wgl.kernel.compile_ns"] > 0
        assert c["wgl.kernel.launches"] >= 1
        assert c["wgl.kernel.iterations"] >= 1
        assert c["wgl.batch.histories"] == 4
        assert 0 < c["wgl.batch.entries"] <= c["wgl.batch.slots"]
        g = telemetry.get().gauges()
        assert 0 < g["wgl.batch.occupancy"] <= 1

    def test_scc_and_elle_counters(self):
        from jepsen_tpu.tpu import elle_device, synth

        telemetry.reset()
        hist = synth.list_append_history(300, seed=5)
        res = elle_device.check_list_append_device(hist, device=False)
        assert res["valid?"] is True
        c = telemetry.get().counters()
        assert c["elle.txns"] == res["txn-count"]
        assert c["elle.edges"] == res["edge-count"]
        assert c.get("scc.path.host", 0) >= 1  # small graph: host path
