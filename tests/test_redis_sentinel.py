"""Redis+Sentinel suite tests: DB config emission via the dummy
remote, sentinel master discovery + READONLY re-resolution, CAS
atomicity through a fake redis, and clusterless end-to-end register
runs (mirrors aphyr/jepsen redis/src/jepsen/redis.clj)."""

import threading

from jepsen_tpu import control, core, suites, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import redis_sentinel as rs


class TestRegistry:
    def test_registered(self):
        assert "redis-sentinel" in suites.SUITES
        assert suites.load("redis-sentinel") is rs


class TestDB:
    def test_setup_commands(self):
        remote = DummyRemote()
        nodes = ["n1", "n2", "n3"]
        test = testing.noop_test()
        test.update(nodes=nodes, remote=remote,
                    sessions={n: remote.connect({"host": n})
                              for n in nodes})
        db = rs.RedisSentinelDB()
        with control.with_session(test, "n2"):
            db.setup(test, "n2")
        # config content travels as the write_file action's stdin
        got = " ; ".join(f"{a.cmd} << {a.stdin or ''}"
                         for a in test["sessions"]["n2"].log
                         if isinstance(a, Action))
        # a non-primary node replicates the first node
        assert "replicaof n1 6379" in got
        # the sentinel monitors the primary with a majority quorum
        assert "sentinel monitor jepsen n1 6379 2" in got
        assert "--sentinel" in got

    def test_primary_gets_no_replicaof(self):
        remote = DummyRemote()
        nodes = ["n1", "n2", "n3"]
        test = testing.noop_test()
        test.update(nodes=nodes, remote=remote,
                    sessions={n: remote.connect({"host": n})
                              for n in nodes})
        with control.with_session(test, "n1"):
            rs.RedisSentinelDB().setup(test, "n1")
        got = " ; ".join(f"{a.cmd} << {a.stdin or ''}"
                         for a in test["sessions"]["n1"].log
                         if isinstance(a, Action))
        assert "replicaof" not in got


class FakeRedis:
    """One in-memory register speaking redis-cli reply strings, with
    a scripted master address and optional READONLY bounces."""

    def __init__(self):
        self.lock = threading.Lock()
        self.value = None
        self.master = ("n1", 6379)
        self.readonly_bounces = 0  # bounce the next N writes

    def cli(self, host, port, *args):
        cmd = args[0].upper()
        with self.lock:
            if cmd == "SENTINEL":
                return f"{self.master[0]}\n{self.master[1]}"
            if cmd == "GET":
                return "" if self.value is None else str(self.value)
            if cmd in ("SET", "EVAL") and self.readonly_bounces > 0:
                self.readonly_bounces -= 1
                return ("READONLY You can't write against a read "
                        "only replica.")
            if cmd == "SET":
                self.value = int(args[2])
                return "OK"
            if cmd == "EVAL":
                frm, to = int(args[-2]), int(args[-1])
                if self.value is not None and self.value == frm:
                    self.value = to
                    return "1"
                return "0"
            raise AssertionError(f"unexpected {args}")


class FakeCliFactory:
    def __init__(self, state=None):
        self.state = state or FakeRedis()

    def __call__(self, test, node, timeout=5.0):
        state = self.state

        class _C:
            def __init__(self):
                self.master = None

            def resolve_master(self):
                out = state.cli(node, 26379, "SENTINEL",
                                "get-master-addr-by-name", "jepsen")
                h, p = out.splitlines()
                self.master = (h, int(p))
                return self.master

            def run(self, *args):
                if self.master is None:
                    self.resolve_master()
                return state.cli(self.master[0], self.master[1],
                                 *args)

            def forget_master(self):
                self.master = None

            def close(self):
                pass

        return _C()


def run_register(opts, factory):
    w = rs.register_workload(opts)
    w["client"].cli_factory = factory
    test = testing.noop_test()
    test.update(nodes=["n1", "n2"],
                concurrency=opts.get("concurrency", 4),
                client=w["client"], checker=w["checker"],
                generator=gen.clients(
                    gen.stagger(0.0004, w["generator"])))
    return core.run(test)


class TestEndToEnd:
    def test_register_linearizable(self):
        test = run_register({"ops": 150, "seed": 5},
                            FakeCliFactory())
        assert test["results"]["valid?"] is True
        assert test["results"]["anomaly-classes"][
            "nonlinearizable"] == "clean"

    def test_failover_lost_write_detected(self):
        class SplitBrain(FakeRedis):
            """After the failover point every read returns 99 — a
            value outside the write domain (0..4), i.e. state from a
            diverged master no linearization can explain (the
            synth.corrupt_register_history shape)."""

            def __init__(self):
                super().__init__()
                self.calls = 0

            def cli(self, host, port, *args):
                with self.lock:
                    self.calls += 1
                    diverged = self.calls > 120
                if diverged and args[0].upper() == "GET":
                    return "99"
                return super().cli(host, port, *args)

        test = run_register({"ops": 200, "seed": 7},
                            FakeCliFactory(SplitBrain()))
        assert test["results"]["valid?"] is False
        assert test["results"]["anomaly-classes"][
            "nonlinearizable"] == "witnessed"


class TestClient:
    def test_readonly_bounce_reresolves_once(self):
        state = FakeRedis()
        state.readonly_bounces = 1
        c = rs.SentinelRegisterClient(FakeCliFactory(state)).open(
            {}, "n1")
        op = Op(index=0, time=0, type="invoke", process=0, f="write",
                value=4)
        done = c.invoke({}, op)
        # one bounce: re-resolve + retry succeeds, still ONE op
        assert done.type == "ok"
        assert state.value == 4

    def test_persistent_readonly_is_definite_fail(self):
        state = FakeRedis()
        state.readonly_bounces = 99
        c = rs.SentinelRegisterClient(FakeCliFactory(state)).open(
            {}, "n1")
        op = Op(index=0, time=0, type="invoke", process=0, f="write",
                value=4)
        done = c.invoke({}, op)
        # a REFUSED write definitely did not apply
        assert done.type == "fail"
        assert state.value is None

    def test_cas_precondition_fail_is_definite(self):
        state = FakeRedis()
        state.value = 2
        c = rs.SentinelRegisterClient(FakeCliFactory(state)).open(
            {}, "n1")
        op = Op(index=0, time=0, type="invoke", process=0, f="cas",
                value=[3, 4])
        assert c.invoke({}, op).type == "fail"
        op2 = Op(index=0, time=0, type="invoke", process=0, f="cas",
                 value=[2, 4])
        assert c.invoke({}, op2).type == "ok"
        assert state.value == 4

    def test_transport_error_on_write_is_indeterminate(self):
        class Dying:
            def __call__(self, test, node, timeout=5.0):
                class _C:
                    def run(self, *args):
                        from jepsen_tpu.control.core import \
                            RemoteError

                        raise RemoteError("broken pipe", exit=1,
                                          out="", err="broken pipe",
                                          cmd="SET", node=node)

                    def forget_master(self):
                        pass

                    def close(self):
                        pass

                return _C()

        c = rs.SentinelRegisterClient(Dying()).open({}, "n1")
        op = Op(index=0, time=0, type="invoke", process=0, f="write",
                value=1)
        assert c.invoke({}, op).type == "info"
