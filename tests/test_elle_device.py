"""Differential tests: the device-path list-append engine
(tpu/elle_device) must agree with the host reference engine
(tpu/elle) on every fixture and on randomized valid/corrupted
histories, mirroring how the reference treats elle as ground truth
(jepsen/src/jepsen/tests/cycle/append.clj)."""

import random

import numpy as np
import pytest

from jepsen_tpu.history import History, op
from jepsen_tpu.tpu import elle, elle_device, scc as scc_mod


def T(*events):
    return History([op(type=t, process=p, f="txn", value=m)
                    for t, p, m in events])


FIXTURES = {
    "valid_seq": T(
        ("invoke", 0, [["append", "x", 1]]), ("ok", 0, [["append", "x", 1]]),
        ("invoke", 1, [["r", "x", None]]), ("ok", 1, [["r", "x", [1]]]),
        ("invoke", 0, [["append", "x", 2]]), ("ok", 0, [["append", "x", 2]]),
        ("invoke", 1, [["r", "x", None]]), ("ok", 1, [["r", "x", [1, 2]]])),
    "g0": T(("invoke", 0, [["append", "x", 1], ["append", "y", 1]]),
            ("invoke", 1, [["append", "x", 2], ["append", "y", 2]]),
            ("ok", 0, [["append", "x", 1], ["append", "y", 1]]),
            ("ok", 1, [["append", "x", 2], ["append", "y", 2]]),
            ("invoke", 2, [["r", "x", None], ["r", "y", None]]),
            ("ok", 2, [["r", "x", [1, 2]], ["r", "y", [2, 1]]])),
    "g1a": T(("invoke", 0, [["append", "x", 9]]),
             ("fail", 0, [["append", "x", 9]]),
             ("invoke", 1, [["r", "x", None]]),
             ("ok", 1, [["r", "x", [9]]])),
    "g1b": T(("invoke", 0, [["append", "x", 1], ["append", "x", 2]]),
             ("ok", 0, [["append", "x", 1], ["append", "x", 2]]),
             ("invoke", 1, [["r", "x", None]]),
             ("ok", 1, [["r", "x", [1]]])),
    "g1c": T(("invoke", 0, [["append", "x", 1], ["r", "y", None]]),
             ("invoke", 1, [["append", "y", 1], ["r", "x", None]]),
             ("ok", 0, [["append", "x", 1], ["r", "y", [1]]]),
             ("ok", 1, [["append", "y", 1], ["r", "x", [1]]])),
    "g_single": T(("invoke", 0, [["r", "x", None], ["r", "y", None]]),
                  ("invoke", 1, [["append", "y", 1], ["append", "x", 1]]),
                  ("ok", 1, [["append", "y", 1], ["append", "x", 1]]),
                  ("ok", 0, [["r", "x", []], ["r", "y", [1]]]),
                  ("invoke", 2, [["r", "x", None]]),
                  ("ok", 2, [["r", "x", [1]]])),
    "g2": T(("invoke", 0, [["r", "x", None], ["append", "y", 1]]),
            ("invoke", 1, [["r", "y", None], ["append", "x", 1]]),
            ("ok", 0, [["r", "x", []], ["append", "y", 1]]),
            ("ok", 1, [["r", "y", []], ["append", "x", 1]]),
            ("invoke", 2, [["r", "x", None], ["r", "y", None]]),
            ("ok", 2, [["r", "x", [1]], ["r", "y", [1]]])),
    "incompat": T(("invoke", 0, [["r", "x", None]]),
                  ("ok", 0, [["r", "x", [1, 2]]]),
                  ("invoke", 1, [["r", "x", None]]),
                  ("ok", 1, [["r", "x", [2, 1, 3]]])),
    "internal": T(("invoke", 0, [["append", "x", 5], ["r", "x", None]]),
                  ("ok", 0, [["append", "x", 5], ["r", "x", [1]]])),
    "dup": T(("invoke", 0, [["append", "x", 1]]),
             ("ok", 0, [["append", "x", 1]]),
             ("invoke", 1, [["append", "x", 1]]),
             ("ok", 1, [["append", "x", 1]])),
    "retry_after_fail": T(
        ("invoke", 0, [["append", "x", 1]]), ("fail", 0, [["append", "x", 1]]),
        ("invoke", 0, [["append", "x", 1]]), ("ok", 0, [["append", "x", 1]]),
        ("invoke", 1, [["r", "x", None]]), ("ok", 1, [["r", "x", [1]]])),
    "info_observed": T(
        ("invoke", 0, [["append", "x", 1]]), ("info", 0, [["append", "x", 1]]),
        ("invoke", 1, [["r", "x", None]]), ("ok", 1, [["r", "x", [1]]])),
    "empty_read_info": T(
        ("invoke", 0, [["append", "k", 1]]), ("info", 0, [["append", "k", 1]]),
        ("invoke", 1, [["r", "k", None]]), ("ok", 1, [["r", "k", [1]]]),
        ("invoke", 2, [["r", "k", None]]), ("ok", 2, [["r", "k", []]])),
    "rt_beyond": T(
        ("invoke", 1, [["append", "z", 1]]), ("invoke", 0, [["append", "y", 1]]),
        ("ok", 0, [["append", "y", 1]]), ("ok", 1, [["append", "z", 1]]),
        ("invoke", 2, [["r", "y", None]]), ("ok", 2, [["r", "y", []]])),
    "empty": T(),
    "no_appends": T(("invoke", 0, [["r", "x", None]]),
                    ("ok", 0, [["r", "x", []]])),
}


@pytest.mark.parametrize("name", sorted(FIXTURES))
def test_fixture_matches_host(name):
    hist = FIXTURES[name]
    rh = elle.check_list_append(hist, {"engine": "host"})
    rd = elle_device.check_list_append_device(hist)
    assert rh["valid?"] == rd["valid?"], (rh, rd)
    assert rh["anomaly-types"] == rd["anomaly-types"], (rh, rd)


def gen_history(rng, n_txns, n_keys=8, max_len=4, rotate=24):
    """Concurrent valid-by-construction list-append history with
    ok/fail/info completions and key rotation."""
    store = {}
    epoch = [0]
    events = []
    open_t = {}
    procs = list(range(5))
    t_count = 0
    nv = [1]
    while t_count < n_txns or open_t:
        idle = [p for p in procs if p not in open_t]
        if t_count < n_txns and idle and (rng.random() < 0.6
                                          or not open_t):
            p = rng.choice(idle)
            txn = []
            for _ in range(rng.randint(1, max_len)):
                k = f"k{rng.randrange(n_keys)}e{epoch[0]}"
                if rng.random() < 0.5:
                    txn.append(["append", k, nv[0]])
                    nv[0] += 1
                else:
                    txn.append(["r", k, None])
            events.append(("invoke", p, txn))
            open_t[p] = txn
            t_count += 1
            if t_count % rotate == 0:
                epoch[0] += 1
        else:
            p = rng.choice(list(open_t))
            txn = open_t.pop(p)
            r = rng.random()
            if r < 0.85:
                res = []
                for f, k, v in txn:
                    if f == "append":
                        store.setdefault(k, []).append(v)
                        res.append(["append", k, v])
                    else:
                        res.append(["r", k, list(store.get(k, []))])
                events.append(("ok", p, res))
            elif r < 0.95:
                events.append(("fail", p, txn))
            else:
                if rng.random() < 0.5:
                    for f, k, v in txn:
                        if f == "append":
                            store.setdefault(k, []).append(v)
                events.append(("info", p, txn))
    return [op(type=t, process=p, f="txn", value=m)
            for t, p, m in events]


def corrupt(rng, ops):
    """Damage one committed read to seed an anomaly."""
    ops = [op(**o.to_dict()) for o in ops]
    mode = rng.choice(["drop_elem", "swap", "phantom", "truncate"])
    oks = [i for i, o in enumerate(ops)
           if o.type == "ok" and any(m[0] == "r" and m[2]
                                     for m in (o.value or []))]
    if not oks:
        return ops
    i = rng.choice(oks)
    v = [list(m) for m in ops[i].value]
    for m in v:
        if m[0] == "r" and m[2]:
            lst = list(m[2])
            if mode == "drop_elem" and len(lst) > 1:
                del lst[rng.randrange(len(lst) - 1)]
            elif mode == "swap" and len(lst) > 1:
                a, b = rng.sample(range(len(lst)), 2)
                lst[a], lst[b] = lst[b], lst[a]
            elif mode == "phantom":
                lst.append(999999999)
            elif mode == "truncate" and len(lst) > 1:
                lst = lst[:-1]
            m[2] = lst
            break
    ops[i] = op(**{**ops[i].to_dict(), "value": v})
    return ops


def test_random_differential():
    rng = random.Random(11)
    for trial in range(25):
        ops = gen_history(rng, rng.choice([20, 60, 150]))
        if trial % 2 == 1:
            ops = corrupt(rng, ops)
        h = History(ops)
        rh = elle.check_list_append(h, {"engine": "host"})
        rd = elle_device.check_list_append_device(h)
        assert rh["valid?"] == rd["valid?"], (trial, rh, rd)
        assert rh["anomaly-types"] == rd["anomaly-types"], (trial, rh, rd)


def test_auto_engine_dispatch():
    """auto uses device for big histories, host for small; both agree;
    non-internable values fall back to host silently."""
    rng = random.Random(2)
    ops = gen_history(rng, 40)
    small = elle.check_list_append(History(ops))
    assert small["valid?"] is True
    weird = T(("invoke", 0, [["append", "x", "not-an-int"]]),
              ("ok", 0, [["append", "x", "not-an-int"]]),
              ("invoke", 1, [["r", "x", None]]),
              ("ok", 1, [["r", "x", ["not-an-int"]]]))
    res = elle.check_list_append(weird, {"engine": "auto"})
    assert res["valid?"] is True
    with pytest.raises(elle_device.Unvectorizable):
        elle.check_list_append(weird, {"engine": "device"})


def test_scc_kernel_matches_host_random():
    rng = np.random.default_rng(5)
    prev = scc_mod.DEVICE_MIN_EDGES
    scc_mod.DEVICE_MIN_EDGES = 1  # force the device path at test sizes
    try:
        for _ in range(15):
            n = 150
            e = rng.integers(0, n, size=(300, 2))
            d = scc_mod.scc(n, e[:, 0], e[:, 1], device=True)
            h = scc_mod._scc_host(n, e[:, 0], e[:, 1])
            assert (d == h).all()
    finally:
        scc_mod.DEVICE_MIN_EDGES = prev


def test_scc_edge_mask_subsets():
    src = np.array([0, 1, 2, 3])
    dst = np.array([1, 0, 3, 2])
    mask1 = np.array([True, True, False, False])
    labels = scc_mod.scc(4, src, dst, emask=mask1, device=False)
    groups = scc_mod.nontrivial_from_labels(labels)
    assert [sorted(g.tolist()) for g in groups] == [[0, 1]]


def test_scc_adversarial_chain_falls_back():
    """A decreasing chain exceeds the sweep cap on device; the host
    fallback must still give exact singleton labels."""
    n = 3000
    src = np.arange(n - 1, 0, -1)
    dst = np.arange(n - 2, -1, -1)
    labels = scc_mod.scc(n, src, dst, device=True)
    assert (labels == np.arange(n)).all()


def test_unobservable_last_element_still_gets_rw():
    """The anti-dependency is keyed by raw value (host nxt dict), so it
    must fire even when the read's last element has no writer
    (round-3 review finding: the pid-based lookup dropped the edge)."""
    hist = T(
        ("invoke", 0, [["append", "x", 1]]), ("ok", 0, [["append", "x", 1]]),
        ("invoke", 1, [["append", "x", 2]]), ("ok", 1, [["append", "x", 2]]),
        ("invoke", 2, [["r", "x", None]]),
        ("ok", 2, [["r", "x", [1, 999, 2]]]),   # 999 never appended
        ("invoke", 3, [["r", "x", None]]),
        ("ok", 3, [["r", "x", [1, 999]]]))
    rh = elle.check_list_append(hist, {"engine": "host"})
    rd = elle_device.check_list_append_device(hist)
    assert rh["valid?"] == rd["valid?"]
    assert rh["anomaly-types"] == rd["anomaly-types"], (rh, rd)


class TestRwRegisterDeviceDispatch:
    """check_rw_register's device-SCC dispatch must agree with the
    host cycle search (BASELINE config 3 covers rw-register too)."""

    def test_engines_agree_on_fixtures(self):
        cases = [
            # valid
            T(("invoke", 0, [["w", "x", 1]]), ("ok", 0, [["w", "x", 1]]),
              ("invoke", 1, [["r", "x", None]]), ("ok", 1, [["r", "x", 1]])),
            # wr cycle (G1c)
            T(("invoke", 0, [["w", "x", 1], ["r", "y", None]]),
              ("invoke", 1, [["w", "y", 2], ["r", "x", None]]),
              ("ok", 0, [["w", "x", 1], ["r", "y", 2]]),
              ("ok", 1, [["w", "y", 2], ["r", "x", 1]])),
        ]
        for hist in cases:
            rd = elle.check_rw_register(hist, {"engine": "device"})
            rh = elle.check_rw_register(hist, {"engine": "host"})
            assert rd["valid?"] == rh["valid?"]
            assert rd["anomaly-types"] == rh["anomaly-types"]

    def test_engines_agree_on_generated(self):
        from jepsen_tpu.tpu import synth

        hist = synth.rw_register_history(2000, seed=9)
        rd = elle.check_rw_register(hist, {"engine": "device"})
        rh = elle.check_rw_register(hist, {"engine": "host"})
        assert rd["valid?"] is rh["valid?"] is True


def test_rw_none_first_read_not_promoted_to_external():
    """A None first read is the key's external read (txn.clj ext-reads
    semantics): a later valued read of the same key must not emit the
    rw edge the host engine never produces (review r3)."""
    hist = T(
        ("invoke", 0, [["w", "x", 1]]), ("ok", 0, [["w", "x", 1]]),
        ("invoke", 1, [["w", "x", 2]]), ("ok", 1, [["w", "x", 2]]),
        # reads None first, then 1, in one txn; succ[(x,1)]=2 exists
        # via t2's write-follows-read
        ("invoke", 2, [["r", "x", None], ["r", "x", None]]),
        ("ok", 2, [["r", "x", None], ["r", "x", 1]]),
        ("invoke", 3, [["r", "x", None], ["w", "x", 3]]),
        ("ok", 3, [["r", "x", 1], ["w", "x", 3]]))
    rh = elle.check_rw_register(hist, {"engine": "host"})
    rd = elle.check_rw_register(hist, {"engine": "device"})
    assert rd["valid?"] == rh["valid?"]
    assert rd["anomaly-types"] == rh["anomaly-types"]
    assert rd["edge-count"] == rh["edge-count"]


def test_rw_unvectorizable_values_still_check():
    """String register values can't intern; engine=device must fall
    back to host inference + device SCC and agree with host."""
    hist = T(
        ("invoke", 0, [["w", "x", "a"]]),
        ("ok", 0, [["w", "x", "a"]]),
        ("invoke", 1, [["r", "x", None]]),
        ("ok", 1, [["r", "x", "a"]]))
    rd = elle.check_rw_register(hist, {"engine": "device"})
    rh = elle.check_rw_register(hist, {"engine": "host"})
    assert rd["valid?"] is rh["valid?"] is True
