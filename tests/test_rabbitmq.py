"""RabbitMQ suite tests: DB clustering command emission via the dummy
remote, and clusterless end-to-end queue-conservation runs against an
in-memory broker (mirrors rabbitmq/src/jepsen/rabbitmq.clj)."""

import collections
import threading

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, RemoteError, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.suites import rabbitmq as rmq


def responder(node, action):
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    return None


def make_test(nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return core.prepare_test(t)  # real barrier for synchronize()


def cmds(test, node):
    return [a.cmd for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def _setup_all(self, test):
        """Parallel setup like core.run does — the synchronize barrier
        requires all nodes in flight together."""
        db = rmq.RabbitDB("3.5.6")
        control.on_nodes(test, lambda t, n: db.setup(t, n))

    def test_cluster_join_flow(self):
        test = make_test()
        self._setup_all(test)
        got1 = " ; ".join(cmds(test, "n1"))
        got2 = " ; ".join(cmds(test, "n2"))
        # cookie set everywhere before clustering
        for got in (got1, got2):
            assert "jepsen-rabbitmq > /var/lib/rabbitmq/.erlang.cookie" \
                in got
            assert "rabbitmq_management" in got
        # primary never joins; secondaries stop_app -> join -> start_app
        assert "join_cluster" not in got1
        assert "rabbitmqctl stop_app" in got2
        assert "rabbitmqctl join_cluster rabbit@n1" in got2
        assert got2.index("stop_app") < got2.index("join_cluster")
        assert "rabbitmqctl start_app" in got2
        # mirroring policy on every node after the join barrier
        assert "set_policy ha-maj" in got1 and "ha-mode" in got1

    def test_teardown_nukes_mnesia(self):
        test = make_test()
        db = rmq.RabbitDB()
        with control.with_session(test, "n1"):
            db.teardown(test, "n1")
        got = " ; ".join(cmds(test, "n1"))
        assert "killall -9 beam.smp epmd" in got
        assert "/var/lib/rabbitmq/mnesia/" in got


class FakeBroker:
    """In-memory durable queue with rabbitmqadmin raw_json shapes."""

    def __init__(self):
        self.lock = threading.Lock()
        self.q = collections.deque()

    def publish(self, payload):
        with self.lock:
            self.q.append(payload)
        return "Message published"

    def get(self):
        with self.lock:
            if not self.q:
                return "[]"
            v = self.q.popleft()
        return f'[{{"payload": "{v}", "routing_key": "jepsen.queue"}}]'


class FakeAdminFactory:
    def __init__(self, broker=None):
        self.broker = broker or FakeBroker()
        self.declared: list = []

    def __call__(self, test, node, timeout=8.0):
        factory = self

        class _Admin:
            def run(self, *args):
                if args[0] == "declare":
                    factory.declared.append(args)
                    return "queue declared"
                if args[0] == "publish":
                    payload = next(a for a in args
                                   if a.startswith("payload="))
                    return factory.broker.publish(
                        payload.split("=", 1)[1])
                if args[0] == "get":
                    return factory.broker.get()
                raise AssertionError(f"unexpected {args}")

            def close(self):
                pass

        return _Admin()


def run_queue_test(factory, ops=200, concurrency=4):
    w = rmq.queue_workload({"ops": ops})
    w["client"].admin_factory = factory
    test = testing.noop_test()
    test.update(
        nodes=["n1", "n2"], concurrency=concurrency,
        client=w["client"], checker=w["checker"],
        generator=gen.phases(
            gen.clients(gen.stagger(0.0003, w["mix"])),
            gen.clients(w["drain"])))
    return core.run(test)


class TestEndToEnd:
    def test_conservation_holds(self):
        test = run_queue_test(FakeAdminFactory())
        assert test["results"]["valid?"] is True
        res = test["results"]["total-queue"]
        assert not res["lost"] and not res["unexpected"]
        assert res["ok-count"] > 0

    def test_aborted_drain_degrades_loss_to_unknown(self):
        """Undrained messages behind an :info drain are indeterminate,
        not lost: the queue may still hold them."""
        from jepsen_tpu import checker as chk
        from jepsen_tpu.history import History, Op

        hist = History([
            Op(index=0, type="invoke", process=0, f="enqueue", value=1),
            Op(index=1, type="ok", process=0, f="enqueue", value=1),
            Op(index=2, type="invoke", process=0, f="enqueue", value=2),
            Op(index=3, type="ok", process=0, f="enqueue", value=2),
            Op(index=4, type="invoke", process=1, f="drain", value=None),
            Op(index=5, type="info", process=1, f="drain", value=[1]),
        ])
        res = chk.total_queue().check({}, hist, {})
        assert res["valid?"] == "unknown"
        assert res["lost"] == {2: 1}
        assert res["aborted-drain-count"] == 1
        # same history with a completed drain: definitely lost
        done = History(list(hist[:5]) + [
            Op(index=5, type="ok", process=1, f="drain", value=[1])])
        res2 = chk.total_queue().check({}, done, {})
        assert res2["valid?"] is False

    def test_queue_declared_at_setup(self):
        factory = FakeAdminFactory()
        run_queue_test(factory)
        assert any("name=jepsen.queue" in a for d in factory.declared
                   for a in d)

    def test_lost_message_detected(self):
        """A broker that drops every 10th confirmed publish loses
        messages the drain never recovers -> invalid."""

        class Lossy(FakeBroker):
            def __init__(self):
                super().__init__()
                self.n = 0

            def publish(self, payload):
                self.n += 1
                if self.n % 10 == 0:
                    return "Message published"  # confirmed but gone
                return super().publish(payload)

        test = run_queue_test(FakeAdminFactory(Lossy()))
        assert test["results"]["valid?"] is False
        assert test["results"]["total-queue"]["lost"]

    def test_duplicate_delivery_detected(self):
        """A broker that re-delivers a message it already served must
        surface as unexpected/duplicate in total-queue."""

        class Dup(FakeBroker):
            def __init__(self):
                super().__init__()
                self.duped = False

            def get(self):
                with self.lock:
                    if not self.q:
                        return "[]"
                    v = self.q[0]
                    if self.duped or len(self.q) == 1:
                        self.q.popleft()  # normal delivery
                    else:
                        self.duped = True  # serve head once more later
                return (f'[{{"payload": "{v}", '
                        f'"routing_key": "jepsen.queue"}}]')

        test = run_queue_test(FakeAdminFactory(Dup()), ops=60,
                              concurrency=2)
        res = test["results"]["total-queue"]
        assert res["duplicated"] or res["unexpected"]


class TestClientErrors:
    def test_enqueue_crash_is_info_dequeue_fail(self):
        class Down:
            def __call__(self, test, node, timeout=8.0):
                class _Admin:
                    def run(self, *args):
                        raise RemoteError("broker down", exit=1,
                                          out="", err="conn refused",
                                          cmd="rabbitmqadmin",
                                          node=node)

                    def close(self):
                        pass

                return _Admin()

        client = rmq.RabbitQueueClient(admin_factory=Down()).open(
            {}, "n1")
        from jepsen_tpu.history import Op

        enq = client.invoke({}, Op(type="invoke", process=0,
                                   f="enqueue", value=7))
        deq = client.invoke({}, Op(type="invoke", process=0,
                                   f="dequeue", value=None))
        assert enq.type == "info"  # may have landed
        assert deq.type == "info"  # get-with-ack may have consumed

    def test_unrouted_publish_is_definite_fail(self):
        class Unrouted:
            def __call__(self, test, node, timeout=8.0):
                class _Admin:
                    def run(self, *args):
                        return "Message published but NOT routed"

                    def close(self):
                        pass

                return _Admin()

        client = rmq.RabbitQueueClient(
            admin_factory=Unrouted()).open({}, "n1")
        from jepsen_tpu.history import Op

        enq = client.invoke({}, Op(type="invoke", process=0,
                                   f="enqueue", value=7))
        assert enq.type == "fail"

    def _flaky_admin_factory(self, calls, fail_from, fail_count):
        """get #1..fail_from-1 return messages, then `fail_count`
        RemoteErrors, then empty replies."""

        class Flaky:
            def __call__(self, test, node, timeout=8.0):
                class _Admin:
                    def run(self, *args):
                        if args[0] == "get":
                            calls["n"] += 1
                            if calls["n"] < fail_from:
                                return ('[{"payload": "%d"}]'
                                        % calls["n"])
                            if calls["n"] < fail_from + fail_count:
                                raise RemoteError(
                                    "conn reset", exit=1, out="",
                                    err="reset", cmd="x", node=node)
                        return ""

                    def close(self):
                        pass

                return _Admin()

        return Flaky()

    def test_drain_retries_transient_error_to_completion(self,
                                                         monkeypatch):
        monkeypatch.setattr(rmq.time, "sleep", lambda s: None)
        calls = {"n": 0}
        client = rmq.RabbitQueueClient(
            admin_factory=self._flaky_admin_factory(
                calls, fail_from=3, fail_count=2)).open({}, "n1")
        from jepsen_tpu.history import Op

        r = client.invoke({}, Op(type="invoke", process=0, f="drain",
                                 value=None))
        # the two transient errors are retried through to the empty
        # reply, but either errored get may have consumed a message
        # whose reply was lost: the drain is :info, never an :ok
        # empty-queue claim
        assert r.type == "info" and r.value == [1, 2]

    def test_clean_drain_is_ok(self):
        calls = {"n": 0}
        client = rmq.RabbitQueueClient(
            admin_factory=self._flaky_admin_factory(
                calls, fail_from=3, fail_count=0)).open({}, "n1")
        from jepsen_tpu.history import Op

        r = client.invoke({}, Op(type="invoke", process=0, f="drain",
                                 value=None))
        assert r.type == "ok" and r.value == [1, 2]

    def test_drain_error_counter_resets_on_success(self, monkeypatch):
        """4 errors, a success, 4 more errors: never 5 consecutive, so
        the drain keeps going to completion (as :info)."""
        monkeypatch.setattr(rmq.time, "sleep", lambda s: None)
        calls = {"n": 0}
        pattern = (["msg"] * 2 + ["err"] * 4 + ["msg"] + ["err"] * 4
                   + ["msg"] + ["empty"])

        class Scripted:
            def __call__(self, test, node, timeout=8.0):
                class _Admin:
                    def run(self, *args):
                        if args[0] != "get":
                            return ""
                        step = pattern[min(calls["n"],
                                           len(pattern) - 1)]
                        calls["n"] += 1
                        if step == "err":
                            raise RemoteError(
                                "conn reset", exit=1, out="",
                                err="reset", cmd="x", node=node)
                        if step == "msg":
                            return ('[{"payload": "%d"}]'
                                    % calls["n"])
                        return ""

                    def close(self):
                        pass

                return _Admin()

        client = rmq.RabbitQueueClient(admin_factory=Scripted()).open(
            {}, "n1")
        from jepsen_tpu.history import Op

        r = client.invoke({}, Op(type="invoke", process=0, f="drain",
                                 value=None))
        assert r.type == "info" and len(r.value) == 4

    def test_drain_persistent_error_is_info(self, monkeypatch):
        monkeypatch.setattr(rmq.time, "sleep", lambda s: None)
        calls = {"n": 0}
        client = rmq.RabbitQueueClient(
            admin_factory=self._flaky_admin_factory(
                calls, fail_from=3, fail_count=99)).open({}, "n1")
        from jepsen_tpu.history import Op

        r = client.invoke({}, Op(type="invoke", process=0, f="drain",
                                 value=None))
        # broker never came back: the drain is indeterminate, NOT an
        # :ok empty-queue claim (messages left behind are not "lost")
        assert r.type == "info" and r.value == [1, 2]
        assert "reset" in r.error

    def test_cli_map(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 3,
                "ssh": {"dummy": True}, "time_limit": 5}
        test = rmq.rabbitmq_test(opts)
        assert test["name"] == "rabbitmq-queue"
        assert isinstance(test["db"], rmq.RabbitDB)
