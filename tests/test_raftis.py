"""Raftis suite tests: DB command emission via the dummy remote, a
scripted redis-cli, and clusterless end-to-end register/counter runs
(mirrors raftis/src/jepsen/raftis.clj)."""

import threading

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import raftis as rf


def responder(node, action):
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "raftis-v1.0"
    return None


class TestDB:
    def test_setup_commands(self):
        remote = DummyRemote(responder)
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"], remote=remote,
                    sessions={n: remote.connect({"host": n})
                              for n in ["n1", "n2", "n3"]})
        db = rf.RaftisDB("v1.0")
        with control.with_session(test, "n2"):
            db.setup(test, "n2")
        got = " ; ".join(a.cmd for a in test["sessions"]["n2"].log
                         if isinstance(a, Action))
        assert "raftis-v1.0.tar.gz" in got
        assert "--cluster n1:8901,n2:8901,n3:8901" in got
        assert "--local_ip n2" in got


class FakeRedis:
    """Single-register + counter store speaking redis-cli reply
    strings, atomically under a lock."""

    def __init__(self):
        self.lock = threading.Lock()
        self.kv: dict = {}

    def run(self, *args):
        with self.lock:
            cmd = args[0]
            if cmd == "GET":
                v = self.kv.get(args[1])
                return "" if v is None else str(v)
            if cmd == "SET":
                self.kv[args[1]] = int(args[2])
                return "OK"
            if cmd == "INCRBY":
                v = self.kv.get(args[1], 0) + int(args[2])
                self.kv[args[1]] = v
                return str(v)
            if cmd == "DECRBY":
                v = self.kv.get(args[1], 0) - int(args[2])
                self.kv[args[1]] = v
                return str(v)
            raise AssertionError(f"unexpected {args}")


class FakeCliFactory:
    def __init__(self, state=None):
        self.state = state or FakeRedis()

    def __call__(self, test, node, timeout=5.0):
        factory = self

        class _C:
            def run(self, *args):
                return factory.state.run(*args)

            def close(self):
                pass

        return _C()


def run_workload(workload_fn, opts, factory):
    w = workload_fn(opts)
    w["client"].cli_factory = factory
    test = testing.noop_test()
    test.update(nodes=["n1", "n2"],
                concurrency=opts.get("concurrency", 4),
                client=w["client"], checker=w["checker"],
                generator=gen.clients(
                    gen.stagger(0.0004, w["generator"])))
    return core.run(test)


class TestEndToEnd:
    def test_register_valid(self):
        test = run_workload(rf.register_workload,
                            {"ops": 150, "seed": 3},
                            FakeCliFactory())
        assert test["results"]["valid?"] is True

    def test_register_detects_stale_read(self):
        class Stale(FakeRedis):
            def __init__(self):
                super().__init__()
                self.reads = 0

            def run(self, *args):
                if args[0] == "GET":
                    self.reads += 1
                    if self.reads >= 20:
                        return "99"  # never written
                return super().run(*args)

        test = run_workload(rf.register_workload,
                            {"ops": 200, "seed": 5},
                            FakeCliFactory(Stale()))
        assert test["results"]["valid?"] is False

    def test_counter_valid(self):
        test = run_workload(rf.counter_workload,
                            {"ops": 200, "seed": 7},
                            FakeCliFactory())
        assert test["results"]["valid?"] is True

    def test_counter_detects_dropped_increment(self):
        class Dropping(FakeRedis):
            def __init__(self):
                super().__init__()
                self.n = 0

            def run(self, *args):
                if args[0] == "INCRBY":
                    self.n += 1
                    if self.n % 3 == 0:
                        # ack with a plausible value, apply nothing
                        with self.lock:
                            return str(self.kv.get(args[1], 0))
                return super().run(*args)

        test = run_workload(rf.counter_workload,
                            {"ops": 300, "seed": 9},
                            FakeCliFactory(Dropping()))
        assert test["results"]["valid?"] is False


class TestClientErrors:
    def test_no_leader_is_definite_fail(self):
        class NoLeader:
            def __call__(self, test, node, timeout=5.0):
                class _C:
                    def run(self, *args):
                        from jepsen_tpu.control.core import RemoteError

                        raise RemoteError(
                            "redis failed", exit=1, out="",
                            err="ERR write InComplete: no leader "
                                "node!", cmd="SET", node=node)

                    def close(self):
                        pass

                return _C()

        c = rf.RaftisRegisterClient(cli_factory=NoLeader()).open(
            {"nodes": ["n1"]}, "n1")
        op = Op(type="invoke", process=0, f="write", value=3)
        assert c.invoke({}, op).type == "fail"

    def test_inline_error_reply_classified(self):
        """An error reply means the server REJECTED the command — a
        definite fail, in both tty '(error) ...' and raw exec
        formatting."""
        for reply in ("(error) ERR not ready", "ERR not ready"):
            class ErrReply:
                def __call__(self, test, node, timeout=5.0,
                             _reply=reply):
                    class _C:
                        def run(self, *args):
                            return _reply

                        def close(self):
                            pass

                    return _C()

            c = rf.RaftisRegisterClient(cli_factory=ErrReply()).open(
                {"nodes": ["n1"]}, "n1")
            w = c.invoke({}, Op(type="invoke", process=0, f="write",
                                value=1))
            r = c.invoke({}, Op(type="invoke", process=0, f="read",
                                value=None))
            assert w.type == "fail", reply  # server rejected it
            assert r.type == "fail", reply

    def test_cli_map(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 3,
                "ssh": {"dummy": True}, "time_limit": 5}
        test = rf.raftis_test(opts)
        assert test["name"] == "raftis-register"
        assert isinstance(test["db"], rf.RaftisDB)
