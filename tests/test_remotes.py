"""Docker/k8s remotes, retry wrapper, and reconnect tests (mirror
jepsen/src/jepsen/control/docker.clj, k8s.clj, retry.clj:35-72,
reconnect.clj:17-94)."""

import pytest

from jepsen_tpu import reconnect
from jepsen_tpu.control import retry as retry_mod
from jepsen_tpu.control.core import (Action, RemoteError, Result,
                                     TransportError)
from jepsen_tpu.control.docker import DockerRemote, resolve_container_id
from jepsen_tpu.control.k8s import K8sRemote, list_pods


class ScriptedRunner:
    """Records argv calls; replies via a function."""

    def __init__(self, reply=None):
        self.calls: list = []
        self.reply = reply or (lambda argv, stdin: Result(0, "", "", ""))

    def __call__(self, argv, stdin=None, timeout=600.0):
        self.calls.append((list(argv), stdin))
        return self.reply(argv, stdin)


DOCKER_PS = """CONTAINER ID   IMAGE   COMMAND   CREATED   STATUS   PORTS                     NAMES
a1b2c3d4e5f6   etcd    "/etcd"   2d ago    Up 2d    0.0.0.0:30404->2379/tcp   jepsen-n1
ffffffffffff   etcd    "/etcd"   2d ago    Up 2d    0.0.0.0:30405->2379/tcp   jepsen-n2
"""


class TestDocker:
    def test_resolve_by_port(self):
        r = ScriptedRunner(lambda argv, stdin: Result(0, DOCKER_PS, "", ""))
        assert resolve_container_id("localhost:30404", r) == "a1b2c3d4e5f6"
        assert resolve_container_id("localhost:30405", r) == "ffffffffffff"

    def test_resolve_unknown_port_raises(self):
        r = ScriptedRunner(lambda argv, stdin: Result(0, DOCKER_PS, "", ""))
        with pytest.raises(RemoteError):
            resolve_container_id("localhost:9999", r)

    def test_bare_name_passes_through(self):
        assert resolve_container_id("jepsen-n1") == "jepsen-n1"

    def test_exec_and_cp(self):
        r = ScriptedRunner(lambda argv, stdin: Result(0, "out", "", ""))
        sess = DockerRemote(r).connect({"host": "n1"})
        res = sess.execute(Action(cmd="echo hi"))
        assert res.exit == 0 and res.out == "out"
        assert r.calls[-1][0] == ["docker", "exec", "n1", "sh", "-c",
                                  "echo hi"]
        sess.execute(Action(cmd="cat", stdin="data"))
        assert r.calls[-1][0][:3] == ["docker", "exec", "-i"]
        assert r.calls[-1][1] == "data"
        sess.upload("/tmp/f", "/opt/f")
        assert r.calls[-1][0] == ["docker", "cp", "/tmp/f", "n1:/opt/f"]
        sess.download("/var/log/x", "/tmp/out")
        assert r.calls[-1][0] == ["docker", "cp", "n1:/var/log/x",
                                  "/tmp/out"]

    def test_sudo_wrapping(self):
        r = ScriptedRunner(lambda argv, stdin: Result(0, "", "", ""))
        sess = DockerRemote(r).connect({"host": "n1"})
        sess.execute(Action(cmd="whoami", sudo="root"))
        assert "sudo -S -u root" in r.calls[-1][0][-1]

    def test_cp_failure_raises(self):
        r = ScriptedRunner(lambda argv, stdin: Result(1, "", "no", ""))
        sess = DockerRemote(r).connect({"host": "n1"})
        with pytest.raises(RemoteError):
            sess.upload("/tmp/f", "/opt/f")


class TestK8s:
    def test_exec_flags(self):
        r = ScriptedRunner(lambda argv, stdin: Result(0, "", "", ""))
        sess = K8sRemote(context="kind", namespace="jepsen",
                         runner=r).connect({"host": "pod-1"})
        sess.execute(Action(cmd="uptime"))
        assert r.calls[-1][0] == [
            "kubectl", "exec", "--context=kind", "--namespace=jepsen",
            "pod-1", "--", "sh", "-c", "uptime"]
        sess.upload("/tmp/f", "/opt/f")
        assert r.calls[-1][0][:2] == ["kubectl", "cp"]
        assert r.calls[-1][0][-1] == "pod-1:/opt/f"

    def test_list_pods(self):
        r = ScriptedRunner(lambda argv, stdin: Result(0, "p1 p2 p3", "", ""))
        assert list_pods(runner=r) == ["p1", "p2", "p3"]


class FlakySession:
    """Fails with TransportError n times, then succeeds."""

    def __init__(self, failures):
        self.failures = failures
        self.executed: list = []
        self.disconnected = 0

    def execute(self, action):
        if self.failures > 0:
            self.failures -= 1
            raise TransportError("flaky", node="n1", cmd=action.cmd)
        self.executed.append(action.cmd)
        return Result(0, "ok", "", action.cmd)

    def disconnect(self):
        self.disconnected += 1


class FlakyRemote:
    def __init__(self, failures):
        self.failures = failures
        self.sessions: list = []

    def connect(self, conn_spec):
        s = FlakySession(self.failures)
        self.failures = 0  # later sessions are healthy
        self.sessions.append(s)
        return s


class TestRetry:
    def test_transport_failures_retried(self, monkeypatch):
        monkeypatch.setattr(retry_mod, "BACKOFF_S", 0.001)
        remote = FlakyRemote(failures=3)
        sess = retry_mod.RetryingRemote(remote).connect({"host": "n1"})
        res = sess.execute(Action(cmd="echo hi"))
        assert res.out == "ok"
        # each failure cycles the connection
        assert len(remote.sessions) >= 2

    def test_gives_up_after_retries(self, monkeypatch):
        monkeypatch.setattr(retry_mod, "BACKOFF_S", 0.001)

        class AlwaysDown:
            def connect(self, conn_spec):
                return FlakySession(10**9)

        sess = retry_mod.RetryingRemote(AlwaysDown()).connect(
            {"host": "n1"})
        with pytest.raises(TransportError):
            sess.execute(Action(cmd="echo hi"))

    def test_nonzero_exit_not_retried(self):
        class FailingSession(FlakySession):
            def execute(self, action):
                self.executed.append(action.cmd)
                return Result(7, "", "boom", action.cmd)

        class R:
            def connect(self, conn_spec):
                return FailingSession(0)

        sess = retry_mod.RetryingRemote(R()).connect({"host": "n1"})
        res = sess.execute(Action(cmd="false"))
        assert res.exit == 7  # command's own failure passes through once


class TestReconnectWrapper:
    def test_open_close_reopen(self):
        opened: list = []
        closed: list = []
        w = reconnect.Wrapper(
            open=lambda: opened.append(1) or len(opened),
            close=lambda c: closed.append(c))
        w.open()
        w.open()  # idempotent
        assert w.conn() == 1 and len(opened) == 1
        w.reopen()
        assert closed == [1] and w.conn() == 2
        w.close()
        assert w.conn() is None and closed == [1, 2]

    def test_with_conn_cycles_on_error(self):
        opened: list = []
        w = reconnect.Wrapper(
            open=lambda: opened.append(1) or len(opened),
            close=lambda c: None)
        with pytest.raises(ValueError):
            with w.with_conn():
                raise ValueError("boom")
        assert w.conn() == 2  # replaced after the failure

    def test_open_returning_none_raises(self):
        w = reconnect.Wrapper(open=lambda: None, close=lambda c: None)
        with pytest.raises(RuntimeError):
            w.open()


class TestEtcdOverDocker:
    def test_db_setup_via_docker_remote(self):
        """The etcd suite's DB drives a faked docker CLI end-to-end
        (VERDICT r2 item 7)."""
        from jepsen_tpu import control
        from jepsen_tpu.suites import etcd

        def reply(argv, stdin):
            # commands arrive sudo/cd-wrapped: match on substrings
            cmd = argv[-1] if argv[0] == "docker" else ""
            if "stat /" in cmd:
                return Result(1, "", "absent", "")
            if "dirname /" in cmd:
                return Result(0, cmd.split()[-1].rstrip("'").rsplit(
                    "/", 1)[0], "", "")
            if "ls -A" in cmd:
                return Result(0, "etcd-v3.5.15-linux-amd64", "", "")
            return Result(0, "", "", "")

        r = ScriptedRunner(reply)
        remote = DockerRemote(r)
        test = {"nodes": ["n1"], "remote": remote, "ssh": {},
                "sessions": {"n1": remote.connect({"host": "n1"})}}
        db = etcd.EtcdDB("v3.5.15")
        with control.with_session(test, "n1"):
            try:
                db.setup(test, "n1")
            except Exception:
                pass  # await_tcp_port will fail against the fake; fine
        joined = [c[0][-1] for c in r.calls if c[0][0] == "docker"
                  and c[0][1] == "exec"]
        assert any("start-stop-daemon" in c for c in joined)
        assert any("--initial-cluster" in c for c in joined)


class TestRetryRegressions:
    def test_non_transport_error_keeps_session(self, monkeypatch):
        """A command's own failure (e.g. scp of a missing file) must
        not cycle the shared session (round-3 review finding)."""
        monkeypatch.setattr(retry_mod, "BACKOFF_S", 0.001)

        class Sess(FlakySession):
            def upload(self, local_paths, remote_path):
                raise RemoteError("no such file", exit=1)

        class R:
            def __init__(self):
                self.connects = 0

            def connect(self, conn_spec):
                self.connects += 1
                return Sess(0)

        r = R()
        sess = retry_mod.RetryingRemote(r).connect({"host": "n1"})
        with pytest.raises(RemoteError):
            sess.upload("/nope", "/tmp/x")
        assert r.connects == 1  # session survived

    def test_ssh_255_heuristic(self):
        from jepsen_tpu.control.ssh import _looks_like_ssh_failure
        assert _looks_like_ssh_failure(
            "ssh: connect to host n1 port 22: Connection refused")
        assert _looks_like_ssh_failure("kex_exchange_identification: "
                                       "Connection closed by remote host")
        assert not _looks_like_ssh_failure("myapp: fatal error 42")
        assert not _looks_like_ssh_failure("")


class TestDockerRegressions:
    def test_internal_port_not_matched(self):
        """localhost:2379 (the container-INTERNAL port) must not
        resolve to the first container (round-3 review finding)."""
        r = ScriptedRunner(lambda argv, stdin: Result(0, DOCKER_PS, "", ""))
        with pytest.raises(RemoteError):
            resolve_container_id("localhost:2379", r)


class TestScp:
    """Sudo-aware transfer wrapper (control/scp.clj:82-146)."""

    def _session(self, responder=None):
        from jepsen_tpu.control.dummy import DummyRemote
        from jepsen_tpu.control.scp import ScpRemote

        remote = ScpRemote(DummyRemote(responder))
        sess = remote.connect({"host": "n1", "username": "admin"})
        return sess, sess.base

    def test_plain_upload_delegates(self):
        sess, base = self._session()
        sess.upload("/local/f", "/remote/f")
        assert base.log == [("upload", "/local/f", "/remote/f")]

    def test_matching_sudo_delegates(self):
        from jepsen_tpu import control

        sess, base = self._session()
        with control.su("admin"):
            sess.upload("/local/f", "/remote/f")
        assert base.log == [("upload", "/local/f", "/remote/f")]

    def test_sudo_upload_does_tmpfile_dance(self):
        from jepsen_tpu import control
        from jepsen_tpu.control.core import Action
        from jepsen_tpu.control.scp import TMP_DIR

        sess, base = self._session()
        with control.su():
            sess.upload("/local/f", "/etc/secret")
        uploads = [e for e in base.log if isinstance(e, tuple)]
        assert len(uploads) == 1
        (_, src, tmp) = uploads[0]
        assert src == "/local/f" and tmp.startswith(TMP_DIR + "/")
        assert tmp.endswith("/f")  # basename preserved under tmp subdir
        cmds = [a.cmd for a in base.log if isinstance(a, Action)]
        assert f"install -d -m 0777 {TMP_DIR}" in cmds
        assert f"chown root {tmp}" in cmds
        assert f"mv {tmp} /etc/secret" in cmds
        # cleanup is best-effort
        assert any(c.startswith(f"rm -rf {TMP_DIR}/") for c in cmds)
        # privilege steps run as root
        chown = next(a for a in base.log if isinstance(a, Action)
                     and a.cmd.startswith("chown"))
        assert chown.sudo == "root"

    def test_sudo_download_readable_file_fetches_directly(self):
        from jepsen_tpu import control

        sess, base = self._session()  # head succeeds by default
        with control.su():
            sess.download("/var/log/syslog", "/tmp/out")
        assert ("download", "/var/log/syslog", "/tmp/out") in base.log

    def test_sudo_download_unreadable_file_copies_first(self):
        from jepsen_tpu import control
        from jepsen_tpu.control.core import Action, Result
        from jepsen_tpu.control.scp import TMP_DIR

        def responder(node, action):
            if action.cmd.startswith("head"):
                return Result(exit=1, out="", err="Permission denied",
                              cmd=action.cmd)
            return None

        sess, base = self._session(responder)
        with control.su():
            sess.download("/root/secret", "/tmp/out")
        cmds = [a.cmd for a in base.log if isinstance(a, Action)]
        assert any(c.startswith(f"cp /root/secret {TMP_DIR}/")
                   for c in cmds)
        # never ln -L: chowning a hardlink would chown the source inode
        assert not any(c.startswith("ln") for c in cmds)
        assert any(c.startswith(f"chown admin {TMP_DIR}/") for c in cmds)
        dl = next(e for e in base.log if isinstance(e, tuple)
                  and e[0] == "download")
        assert dl[1].startswith(TMP_DIR + "/") and dl[2] == "/tmp/out"

    def test_multi_file_sudo_upload_preserves_basenames(self):
        from jepsen_tpu import control
        from jepsen_tpu.control.core import Action

        sess, base = self._session()
        with control.su():
            sess.upload(["/l/a.conf", "/l/b.conf"], "/etc/app")
        mvs = [a.cmd for a in base.log if isinstance(a, Action)
               and a.cmd.startswith("mv")]
        assert len(mvs) == 2
        assert mvs[0].split()[1].endswith("/a.conf")
        assert mvs[1].split()[1].endswith("/b.conf")
        assert mvs[0].endswith(" /etc/app/a.conf")
        assert mvs[1].endswith(" /etc/app/b.conf")

    def test_default_stack_includes_scp_wrapper(self):
        from jepsen_tpu.control import _default_ssh
        from jepsen_tpu.control.retry import RetryingRemote
        from jepsen_tpu.control.scp import ScpRemote
        from jepsen_tpu.control.ssh import SshRemote

        stack = _default_ssh()
        assert isinstance(stack, RetryingRemote)
        assert isinstance(stack.remote, ScpRemote)
        assert isinstance(stack.remote.remote, SshRemote)

    def test_tmp_dir_created_once_per_session(self):
        from jepsen_tpu import control
        from jepsen_tpu.control.core import Action

        sess, base = self._session()
        with control.su():
            sess.upload("/a", "/x")
            sess.upload("/b", "/y")
        from jepsen_tpu.control.scp import TMP_DIR

        mkdirs = [a for a in base.log if isinstance(a, Action)
                  and a.cmd == f"install -d -m 0777 {TMP_DIR}"]
        assert len(mkdirs) == 1  # the shared dir; subdirs are per-file

    def test_hostile_basename_upload_restores_real_name_in_dir(self):
        from jepsen_tpu import control
        from jepsen_tpu.control.core import Action, Result

        def responder(node, action):
            if action.cmd.startswith("test -d"):
                return Result(exit=0, out="", err="", cmd=action.cmd)
            return None

        sess, base = self._session(responder)
        with control.su():
            sess.upload("/l/my config (prod).yaml", "/etc/app")
        up = next(e for e in base.log if isinstance(e, tuple))
        assert up[2].endswith("/file")  # sanitized tmp name for scp
        mv = next(a.cmd for a in base.log if isinstance(a, Action)
                  and a.cmd.startswith("mv"))
        assert mv.endswith(" '/etc/app/my config (prod).yaml'")

    def test_hostile_basename_download_renames_locally(self, tmp_path):
        from jepsen_tpu import control
        from jepsen_tpu.control.core import Result

        def responder(node, action):
            if action.cmd.startswith("head"):
                return Result(exit=1, out="", err="denied",
                              cmd=action.cmd)
            return None

        from jepsen_tpu.control.dummy import DummyRemote
        from jepsen_tpu.control.scp import ScpRemote

        class WritingDummy(DummyRemote):
            def connect(self, conn_spec):
                sess = super().connect(conn_spec)
                orig = sess.download

                def download(remote_paths, local_path):
                    orig(remote_paths, local_path)
                    import os
                    name = os.path.basename(str(remote_paths))
                    (tmp_path / name).write_text("data")
                sess.download = download
                return sess

        remote = ScpRemote(WritingDummy(responder))
        sess = remote.connect({"host": "n1", "username": "admin"})
        with control.su():
            sess.download("/var/log/app log.1", str(tmp_path))
        assert (tmp_path / "app log.1").read_text() == "data"
        assert not (tmp_path / "file").exists()
