"""Checkpoint-and-extend tests (doc/robustness.md): the durable
crash-consistent incremental-checking layer.

The contract under test: a checkpoint is only ever a SPEEDUP. Torn,
truncated, stale, or wrong-history records are detected and discarded
— the caller pays for a full re-check, never for a wrong verdict — and
a resumed check composes the exact masks a from-scratch check would,
so verdicts AND certificates are byte-identical for valid and invalid
histories alike. WAL compaction preserves replay byte-for-byte, and a
crash at any instant during compaction leaves the pre-compaction file
authoritative."""

import json
import os
import time

import pytest

from jepsen_tpu import chaos as jchaos
from jepsen_tpu import telemetry
from jepsen_tpu.checker import models
from jepsen_tpu.fleet import client as fclient
from jepsen_tpu.fleet import scheduler as fsched
from jepsen_tpu.fleet import server as fserver
from jepsen_tpu.fleet import wal as fwal
from jepsen_tpu.history import History, op as make_op
from jepsen_tpu.tpu import certify, ckpt as tckpt, elle as telle
from jepsen_tpu.tpu import synth, wgl


def seeded_hist(seed, n=300, corrupt=False):
    h = synth.register_history(n, seed=seed)
    if corrupt:
        h, _ = synth.corrupt_register_history(h)
    return h


def counters():
    return telemetry.get().counters()


def cert_bytes(out):
    return json.dumps(fwal.json_safe(out["certificate"]),
                      sort_keys=True)


def stream_wgl_rec(ops, checked=10, mask=1):
    return {"v": tckpt.VERSION, "kind": "stream-wgl",
            "model": "cas-register", "checked": checked, "mask": mask,
            "n_ops": len(ops), "digest": tckpt.ops_digest(ops)}


# ---------------------------------------------------------------------------
# the store: framing, schema, corruption, durability faults
# ---------------------------------------------------------------------------

class TestStore:
    def test_round_trip_each_kind(self, tmp_path):
        ops = list(seeded_hist(1, 40))
        d64 = tckpt.ops_digest(ops)
        recs = [
            stream_wgl_rec(ops),
            {"v": tckpt.VERSION, "kind": "wgl-extend", "n_ops": 40,
             "digest": d64, "stride": 64, "model_fp": 123,
             "cuts": [0, 10, 20], "digests": [d64, d64, d64],
             "states": ["Register(None)"], "masks": {"0:0": 3}},
            {"v": tckpt.VERSION, "kind": "elle", "n_ops": 40,
             "digest": d64, "family": "list-append", "n_closed": 7,
             "versions": {"x": [1, 2]},
             "frontier": {"state": "streaming", "edges": []}},
        ]
        for i, rec in enumerate(recs):
            p = tmp_path / f"r{i}.ckpt"
            tckpt.write(p, rec)
            assert tckpt.read(p) == rec
            # atomic-rename discipline: no tmp file survives a write
            assert not p.with_suffix(".tmp").exists()

    def test_schema_rejects_invalid(self, tmp_path):
        ops = list(seeded_hist(1, 20))
        good = stream_wgl_rec(ops)
        for mutate in (
                lambda r: r.pop("digest"),
                lambda r: r.update(v=99),
                lambda r: r.update(kind="mystery"),
                lambda r: r.update(n_ops=-1),
                lambda r: r.update(checked=True),
                lambda r: r.update(digest="short")):
            rec = dict(good)
            mutate(rec)
            with pytest.raises(ValueError):
                tckpt.validate_record(rec)
            with pytest.raises(ValueError):
                tckpt.write(tmp_path / "x.ckpt", rec)

    @pytest.mark.parametrize("mode", ["torn", "garbage", "magic"])
    def test_corruption_detected_and_discarded(self, tmp_path, mode):
        telemetry.reset()
        p = tmp_path / "c.ckpt"
        tckpt.write(p, stream_wgl_rec(list(seeded_hist(2, 40))))
        jchaos.corrupt_checkpoint(p, mode)
        assert tckpt.read(p) is None
        assert counters().get("ckpt.torn", 0) >= 1

    def test_schema_invalid_payload_counted(self, tmp_path):
        # valid framing around a schema-violating record: read() must
        # treat it exactly like a torn file
        telemetry.reset()
        import struct
        import zlib

        p = tmp_path / "bad.ckpt"
        payload = json.dumps({"v": tckpt.VERSION, "kind": "mystery"})\
            .encode()
        p.write_bytes(tckpt.CKPT_MAGIC
                      + struct.pack("<II", len(payload),
                                    zlib.crc32(payload)) + payload)
        assert tckpt.read(p) is None
        assert counters().get("ckpt.invalid", 0) == 1

    def test_load_screens_kind_digest_nops(self, tmp_path):
        telemetry.reset()
        ops = list(seeded_hist(3, 60))
        p = tmp_path / "s.ckpt"
        tckpt.write(p, stream_wgl_rec(ops))
        assert tckpt.load(p, "elle") is None
        assert counters().get("ckpt.stale", 0) == 0  # wrong kind only
        # record describes MORE ops than the history at hand: stale
        assert tckpt.load(p, "stream-wgl",
                          n_ops=len(ops) - 10) is None
        # digest mismatch: a different history's prefix
        other = tckpt.ops_digest(list(seeded_hist(4, 60)))
        assert tckpt.load(p, "stream-wgl", digest=other) is None
        assert counters().get("ckpt.stale", 0) == 2
        rec = tckpt.load(p, "stream-wgl",
                         digest=tckpt.ops_digest(ops))
        assert rec is not None and rec["n_ops"] == len(ops)

    def test_missing_file_reads_none(self, tmp_path):
        assert tckpt.read(tmp_path / "nope.ckpt") is None
        assert tckpt.load(tmp_path / "nope.ckpt", "elle") is None

    def test_try_write_sheds_on_durability_fault(self, tmp_path):
        telemetry.reset()
        ops = list(seeded_hist(5, 40))
        p = tmp_path / "d.ckpt"
        first = stream_wgl_rec(ops, checked=5)
        tckpt.write(p, first)

        def hook(path, data):
            raise OSError(28, "chaos: injected enospc")

        tckpt.set_fault_hook(hook)
        try:
            assert tckpt.try_write(
                p, stream_wgl_rec(ops, checked=9)) is False
        finally:
            tckpt.set_fault_hook(None)
        assert counters().get("ckpt.write-error", 0) == 1
        # the previous (valid) checkpoint survives the failed write
        assert tckpt.read(p) == first

    def test_fleet_path_rejects_unsafe_names(self, tmp_path):
        with pytest.raises(AssertionError):
            tckpt.fleet_path(tmp_path, "../evil", "r")


# ---------------------------------------------------------------------------
# checkpointed vs from-scratch: the pinned equivalence
# ---------------------------------------------------------------------------

class TestExtendEquivalence:
    @pytest.mark.parametrize("corrupt", [False, True],
                             ids=["valid", "invalid"])
    def test_resume_identical_to_from_scratch(self, tmp_path,
                                              corrupt):
        """A check resumed from a prefix checkpoint reaches the SAME
        verdict and the SAME certificate bytes as a from-scratch check
        of the grown history — for valid and invalid histories."""
        telemetry.reset()
        model = models.cas_register()
        ops = list(seeded_hist(11, 600, corrupt=corrupt))
        cut = int(len(ops) * 0.7)
        cut -= cut % 2  # invoke/complete pairs stay aligned
        p = tmp_path / "run.ckpt"
        wgl.analysis_extend(model, ops[:cut], store_path=p, stride=64)
        assert tckpt.read(p) is not None
        scratch = wgl.analysis_extend(model, ops, stride=64,
                                      certify=True)
        resumed = wgl.analysis_extend(model, ops, store_path=p,
                                      stride=64, certify=True)
        assert resumed["valid?"] == scratch["valid?"]
        assert cert_bytes(resumed) == cert_bytes(scratch)
        certify.validate(History(ops), resumed["certificate"])
        # and both agree with the plain reference analysis
        plain = wgl.analysis(model, ops, certify=True)
        assert resumed["valid?"] == plain["valid?"]
        c = counters()
        assert c.get("ckpt.extend.resumed", 0) >= 1
        assert c.get("ckpt.extend.reused-masks", 0) >= 1

    def test_stale_record_full_recheck(self, tmp_path):
        """A checkpoint keyed to a DIFFERENT history costs a full
        re-check (counted), never a wrong verdict."""
        telemetry.reset()
        model = models.cas_register()
        ops = list(seeded_hist(21, 400))
        p = tmp_path / "run.ckpt"
        wgl.analysis_extend(model, list(seeded_hist(22, 400)),
                            store_path=p, stride=64)
        out = wgl.analysis_extend(model, ops, store_path=p, stride=64)
        assert out["valid?"] == wgl.analysis(model, ops)["valid?"]
        assert counters().get("ckpt.stale", 0) >= 1

    def test_torn_record_full_recheck_then_replaced(self, tmp_path):
        telemetry.reset()
        model = models.cas_register()
        ops = list(seeded_hist(23, 600))
        p = tmp_path / "run.ckpt"
        wgl.analysis_extend(model, ops[:400], store_path=p, stride=64)
        prefix_rec = tckpt.read(p)
        assert prefix_rec is not None
        jchaos.corrupt_checkpoint(p, "torn")
        out = wgl.analysis_extend(model, ops, store_path=p, stride=64)
        assert out["valid?"] == wgl.analysis(model, ops)["valid?"]
        assert counters().get("ckpt.torn", 0) >= 1
        # the full re-check re-persisted a fresh, valid record that
        # now covers the GROWN history's entry prefix
        rec = tckpt.read(p)
        assert rec is not None and rec["kind"] == "wgl-extend"
        assert rec["n_ops"] > prefix_rec["n_ops"]
        assert rec["digest"] == rec["digests"][-1]

    def test_short_history_falls_through_to_plain(self, tmp_path):
        telemetry.reset()
        model = models.cas_register()
        ops = list(seeded_hist(24, 30))
        out = wgl.analysis_extend(model, ops,
                                  store_path=tmp_path / "x.ckpt")
        assert out["valid?"] == wgl.analysis(model, ops)["valid?"]
        assert counters().get("ckpt.extend.fallback", 0) == 1


# ---------------------------------------------------------------------------
# WAL compaction: byte-identical replay, crash-safe at every instant
# ---------------------------------------------------------------------------

def build_wal(path, ops, chunk=40, fin=True):
    from jepsen_tpu.fleet import wire

    w = fwal.RunWAL(path)
    w.append({"t": "hello", "tenant": "t", "run": "r",
              "model": "cas-register", "weight": 1.0})
    seq = 0
    for i in range(0, len(ops), chunk):
        seq += 1
        w.append({"t": "chunk", "seq": seq,
                  "ops": wire.ops_to_wire(ops[i:i + chunk])})
    if fin:
        w.append({"t": "fin", "n": len(ops)})
    return w, seq


def replayed_digest(path):
    return tckpt.ops_digest(fwal.replay_ops(fwal.replay(path)))


class TestWalCompaction:
    def test_replay_byte_identical_across_compaction(self, tmp_path):
        ops = list(seeded_hist(31, 400))
        p = tmp_path / "r.wal"
        w, last = build_wal(p, ops)
        before = replayed_digest(p)
        assert w.compact_through(3) is True
        folded = fwal.replay(p)
        assert folded["base"]["seq"] == 3
        assert folded["last_seq"] == last
        assert replayed_digest(p) == before
        # compaction composes: a second fold through a later seq
        assert w.compact_through(last) is True
        assert replayed_digest(p) == before
        w.close()

    def test_appends_after_compaction_land(self, tmp_path):
        from jepsen_tpu.fleet import wire

        ops = list(seeded_hist(32, 400))
        p = tmp_path / "r.wal"
        w, last = build_wal(p, ops[:300], fin=False)
        assert w.compact_through(last) is True
        w.append({"t": "chunk", "seq": last + 1,
                  "ops": wire.ops_to_wire(ops[300:])})
        w.append({"t": "fin", "n": len(ops)})
        w.close()
        assert replayed_digest(p) == tckpt.ops_digest(ops)

    def test_nothing_to_fold_is_a_noop(self, tmp_path):
        ops = list(seeded_hist(33, 200))
        p = tmp_path / "r.wal"
        w, last = build_wal(p, ops)
        raw = p.read_bytes()
        assert w.compact_through(0) is False
        assert w.compact_through(last + 7) is False  # beyond the tail
        w.compact_through(2)
        assert w.compact_through(1) is False  # at/below existing base
        w.close()
        assert fwal.compact(tmp_path / "absent.wal", 1) is False
        # the no-op paths never rewrote the journal
        w2, _ = build_wal(tmp_path / "r2.wal", ops)
        w2.close()

    def test_crash_mid_compaction_pre_file_wins(self, tmp_path):
        """A crash BEFORE the atomic rename leaves a stray tmp and an
        untouched journal: replay must serve the pre-compaction bytes
        and a later compaction must still succeed."""
        ops = list(seeded_hist(34, 300))
        p = tmp_path / "r.wal"
        w, last = build_wal(p, ops)
        before = p.read_bytes()
        # the torn artifact a SIGKILL mid-compaction leaves behind
        p.with_suffix(".compact-tmp").write_bytes(
            before[:len(before) // 2])
        assert p.read_bytes() == before
        assert replayed_digest(p) == tckpt.ops_digest(ops)
        assert w.compact_through(last) is True
        assert replayed_digest(p) == tckpt.ops_digest(ops)
        w.close()


# ---------------------------------------------------------------------------
# streaming resume: StreamingRun / StreamingElle seed()
# ---------------------------------------------------------------------------

def wait_settled(stream, deadline_s=60):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        with stream._lock:
            busy = stream._inflight
        if not busy:
            return
        time.sleep(0.02)
    raise AssertionError("stream never settled")


class TestStreamingRunResume:
    def _drive(self, sched, ops, recs, seed_rec=None, name="r"):
        sr = fsched.StreamingRun("cas-register", sched, "t", name)
        sr.ckpt_sink = recs.append
        if seed_rec is not None:
            resumed = sr.seed(ops, seed_rec)
            sr.step()
            wait_settled(sr)
            return sr, resumed
        for i in range(0, len(ops), 100):
            sr.add_ops(ops[i:i + 100])
            wait_settled(sr)
        sr.step()
        wait_settled(sr)
        return sr, False

    def test_seed_resumes_checked_frontier(self, tmp_path):
        telemetry.reset()
        ops = list(seeded_hist(41, 700))
        sched = fsched.Scheduler(window_s=0.01).start()
        try:
            recs = []
            sr, _ = self._drive(sched, ops, recs)
            assert recs, "no checkpoint record ever emitted"
            rec = recs[-1]
            tckpt.validate_record(rec)
            assert rec["kind"] == "stream-wgl"
            assert rec["digest"] == tckpt.ops_digest(ops,
                                                     rec["n_ops"])
            # a fresh stream seeded with that record resumes PAST the
            # certified frontier instead of re-checking from entry 0
            recs2 = []
            sr2, resumed = self._drive(sched, ops, recs2,
                                       seed_rec=rec, name="r2")
            assert resumed is True
            assert counters().get("ckpt.resumed", 0) == 1
            assert sr.status()["state"] == "streaming"
            assert sr2.status()["state"] == "streaming"
            # the certified frontier was adopted, not re-derived
            with sr2._lock:
                assert sr2._checked >= rec["checked"]
                assert sr2._mask is not None
        finally:
            sched.stop()

    def test_seed_rejects_stale_record(self, tmp_path):
        telemetry.reset()
        ops = list(seeded_hist(42, 400))
        rec = stream_wgl_rec(list(seeded_hist(43, 400)), checked=50,
                             mask=3)
        sched = fsched.Scheduler(window_s=0.01).start()
        try:
            sr = fsched.StreamingRun("cas-register", sched, "t", "r")
            assert sr.seed(ops, rec) is False
            assert counters().get("ckpt.stale", 0) == 1
            # full fallback, not a wrong frontier
            with sr._lock:
                assert sr._checked == 0
        finally:
            sched.stop()


def la_ops(*pairs):
    """Sequential invoke/ok list-append txn pairs."""
    out = []
    for p, inv, okv in pairs:
        out.append(make_op(index=len(out), time=len(out),
                           type="invoke", process=p, f="txn",
                           value=inv))
        out.append(make_op(index=len(out), time=len(out), type="ok",
                           process=p, f="txn", value=okv))
    return out


class TestStreamingElle:
    def test_valid_stream_checkpoints_and_reseeds(self):
        telemetry.reset()
        ops = la_ops(
            (0, [["append", "x", 1]], [["append", "x", 1]]),
            (1, [["r", "x", None]], [["r", "x", [1]]]),
            (0, [["append", "x", 2]], [["append", "x", 2]]),
            (1, [["r", "x", None]], [["r", "x", [1, 2]]]))
        se = telle.StreamingElle("list-append", "t", "r")
        recs = []
        se.ckpt_sink = recs.append
        se.add_ops(ops)
        se.step()
        wait_settled(se)
        assert se.status()["state"] == "streaming"
        assert recs, "no elle checkpoint emitted"
        rec = recs[-1]
        tckpt.validate_record(rec)
        assert rec["kind"] == "elle" and rec["n_closed"] == 4
        se2 = telle.StreamingElle("list-append", "t", "r2")
        assert se2.seed(ops, rec) is True
        assert se2._n_closed == 4
        # a record for a different stream is stale, never trusted
        se3 = telle.StreamingElle("list-append", "t", "r3")
        other = dict(rec, digest="0" * 64)
        assert se3.seed(ops, other) is False
        assert se3._n_closed == 0

    def test_anomaly_tightens_to_tentative_invalid(self):
        # G0: opposite append orders observed on x and y
        ops = la_ops(
            (0, [["append", "x", 1], ["append", "y", 1]],
             [["append", "x", 1], ["append", "y", 1]]),
            (1, [["append", "x", 2], ["append", "y", 2]],
             [["append", "x", 2], ["append", "y", 2]]),
            (2, [["r", "x", None], ["r", "y", None]],
             [["r", "x", [1, 2]], ["r", "y", [2, 1]]]))
        se = telle.StreamingElle("list-append", "t", "r")
        se.add_ops(ops)
        se.step()
        wait_settled(se)
        assert se.status()["state"] == "tentative-invalid"

    def test_spine_reorder_reports_unknown(self):
        """A longer read that rewrites an already-consumed version
        order means earlier graph extensions are untrustworthy: the
        stream stops tightening and says so."""
        se = telle.StreamingElle("list-append", "t", "r")
        se.add_ops(la_ops(
            (0, [["append", "x", 1]], [["append", "x", 1]]),
            (1, [["r", "x", None]], [["r", "x", [1]]])))
        se.step()
        wait_settled(se)
        assert se.status()["state"] == "streaming"
        se.add_ops(la_ops(
            (0, [["append", "x", 2]], [["append", "x", 2]]),
            (1, [["r", "x", None]], [["r", "x", [2, 1]]])))
        se.step()
        wait_settled(se)
        assert se.status()["state"] == "unknown"

    def test_other_families_degrade_honestly(self):
        se = telle.StreamingElle("rw-register", "t", "r")
        assert se.status()["state"] == "unsupported"
        # seeding an unsupported stream never adopts a frontier
        rec = {"v": tckpt.VERSION, "kind": "elle", "n_ops": 0,
               "digest": "0" * 64, "family": "rw-register",
               "n_closed": 0, "versions": {}, "frontier": {}}
        assert se.seed([], rec) is False


# ---------------------------------------------------------------------------
# fleet e2e: SIGKILL mid-checkpoint-write, resume instead of replay
# ---------------------------------------------------------------------------

class TestFleetCheckpointE2E:
    def test_sigkill_mid_ckpt_write_resumes_from_previous(
            self, tmp_path):
        """SIGKILL lands while a checkpoint write is in flight (a torn
        tmp file survives next to the last good record): the restarted
        server resumes the stream from the previous checkpoint — not
        WAL-replay from seq 0 — and the final verdict and certificate
        are byte-identical to an uninterrupted run's."""
        h = seeded_hist(51, 1200)
        ops = list(h)
        chunks = [ops[i:i + 100] for i in range(0, len(ops), 100)]

        ref_base = tmp_path / "ref"
        srv = fserver.FleetServer(ref_base).start()
        c = fclient.FleetClient(srv.addr, "t1", "r1", io_timeout_s=3)
        for ch in chunks:
            c.send_chunk(ch)
        c.finish()
        srv.stop()
        ref = fwal.verdict_path(ref_base, "t1", "r1").read_bytes()

        base = tmp_path / "crash"
        srv = fserver.FleetServer(base).start()
        c = fclient.FleetClient(srv.addr, "t1", "r1", io_timeout_s=2)
        ckpt_path = tckpt.fleet_path(base, "t1", "r1")
        wal_path = fwal.wal_path(base, "t1", "r1")
        sent = 0
        deadline = time.monotonic() + 60
        for ch in chunks[:-2]:
            c.send_chunk(ch)
            sent += 1
        while not ckpt_path.exists():
            assert time.monotonic() < deadline, \
                "stream never checkpointed"
            time.sleep(0.05)
        # ... and the WAL was compacted behind that checkpoint
        while fwal.replay(wal_path)["base"] is None:
            assert time.monotonic() < deadline, \
                "WAL never compacted after checkpoint"
            time.sleep(0.05)
        good = ckpt_path.read_bytes()
        port = srv.addr[1]
        srv.kill()
        # the torn artifact of a write interrupted by the SIGKILL
        ckpt_path.with_suffix(".tmp").write_bytes(good[:9])
        telemetry.reset()
        srv2 = fserver.FleetServer(base, port=port).start()
        # recovery seeded the stream from the checkpoint: the resume
        # is O(suffix), counted — not a full re-check from entry 0
        assert counters().get("ckpt.resumed", 0) == 1
        assert counters().get("ckpt.stale", 0) == 0
        for ch in chunks[len(chunks) - 2:]:
            c.send_chunk(ch)
        env = c.finish(timeout_s=120)
        c.close()
        assert env["result"]["valid?"] is True
        got = fwal.verdict_path(base, "t1", "r1").read_bytes()
        assert got == ref
        srv2.stop()

    def test_torn_checkpoint_on_restart_full_recheck(self, tmp_path):
        """The checkpoint itself torn at restart: detected, discarded,
        and the stream falls back to a full re-check — the verdict is
        still byte-identical."""
        h = seeded_hist(52, 1000)
        ops = list(h)
        chunks = [ops[i:i + 100] for i in range(0, len(ops), 100)]

        ref_base = tmp_path / "ref"
        srv = fserver.FleetServer(ref_base).start()
        c = fclient.FleetClient(srv.addr, "t1", "r1", io_timeout_s=3)
        for ch in chunks:
            c.send_chunk(ch)
        c.finish()
        srv.stop()
        ref = fwal.verdict_path(ref_base, "t1", "r1").read_bytes()

        base = tmp_path / "crash"
        srv = fserver.FleetServer(base).start()
        c = fclient.FleetClient(srv.addr, "t1", "r1", io_timeout_s=2)
        ckpt_path = tckpt.fleet_path(base, "t1", "r1")
        deadline = time.monotonic() + 60
        for ch in chunks[:-2]:
            c.send_chunk(ch)
        while not ckpt_path.exists():
            assert time.monotonic() < deadline, \
                "stream never checkpointed"
            time.sleep(0.05)
        port = srv.addr[1]
        srv.kill()
        jchaos.corrupt_checkpoint(ckpt_path, "torn")
        telemetry.reset()
        srv2 = fserver.FleetServer(base, port=port).start()
        assert counters().get("ckpt.resumed", 0) == 0
        assert counters().get("ckpt.torn", 0) >= 1
        for ch in chunks[len(chunks) - 2:]:
            c.send_chunk(ch)
        env = c.finish(timeout_s=120)
        c.close()
        assert fwal.verdict_path(base, "t1", "r1").read_bytes() == ref
        srv2.stop()
