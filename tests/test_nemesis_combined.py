"""Combined nemesis package tests: node specs, per-fault command lines
through dummy sessions, package composition, and a clusterless
package-driven lifecycle."""

import pytest

from jepsen_tpu import control, db as jdb, generator as gen, net
from jepsen_tpu.control.core import Action
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import op
from jepsen_tpu.nemesis import combined, core as n
from jepsen_tpu.nemesis import time as nt


def responder(node, action):
    cmd = action.cmd
    if cmd.startswith("getent ahostsv4"):
        host = cmd.split()[-1]
        return f"10.0.0.{host[1:]}   STREAM {host}"
    if cmd == "ip -o link show":
        return "1: lo: <LOOPBACK>\n2: eth0: <BROADCAST>"
    if cmd.startswith("date +%s.%N"):
        return "1000.5"
    if cmd.startswith("/opt/jepsen/bump-time"):
        return "1000.25"
    if cmd == "cat /run/db.pid":
        return "1234"
    return None


class FakeDB(jdb.DB):
    supports_kill = True
    supports_pause = True

    def kill(self, test, node):
        control.exec_("killall", "-9", "-w", "db")
        return "killed"

    def start(self, test, node):
        control.exec_("start-db")
        return "started"

    def pause(self, test, node):
        control.exec_("killall", "-s", "STOP", "db")
        return "paused"

    def resume(self, test, node):
        control.exec_("killall", "-s", "CONT", "db")
        return "resumed"


@pytest.fixture()
def test_map():
    net.clear_ip_cache()
    remote = DummyRemote(responder)
    nodes = ["n1", "n2", "n3", "n4", "n5"]
    t = {"nodes": nodes, "remote": remote, "net": net.iptables,
         "db": FakeDB(),
         "sessions": {x: remote.connect({"host": x}) for x in nodes}}
    return t


def cmds(test, node, sudo=None):
    return [a.cmd for a in test["sessions"][node].log
            if isinstance(a, Action)
            and (sudo is None or a.sudo == sudo)]


def info(f, value=None):
    return op(type="info", process="nemesis", f=f, value=value)


# ---------------------------------------------------------------------------
# node specs
# ---------------------------------------------------------------------------

def test_db_nodes_specs(test_map):
    nodes = test_map["nodes"]
    db = test_map["db"]
    assert combined.db_nodes(test_map, db, "all") == nodes
    assert len(combined.db_nodes(test_map, db, "one")) == 1
    assert len(combined.db_nodes(test_map, db, "minority")) == 2
    assert len(combined.db_nodes(test_map, db, "majority")) == 3
    assert len(combined.db_nodes(test_map, db, "minority-third")) == 1
    got = combined.db_nodes(test_map, db, None)
    assert 1 <= len(got) <= 5
    assert combined.db_nodes(test_map, db, ["n2"]) == ["n2"]


def test_node_specs_primaries_gated():
    class P(jdb.DB):
        supports_primaries = True

    assert "primaries" not in combined.node_specs(jdb.DB())
    assert "primaries" in combined.node_specs(P())


def test_grudge_specs(test_map):
    db = test_map["db"]
    g = combined.grudge(test_map, db, "one")
    isolated = [k for k, v in g.items() if len(v) == 4]
    assert len(isolated) == 1
    g = combined.grudge(test_map, db, "majority")
    sizes = sorted(len(v) for v in g.values())
    assert sizes == [2, 2, 2, 3, 3]
    g = combined.grudge(test_map, db, "majorities-ring")
    assert all(len(v) == 2 for v in g.values())
    g = combined.grudge(test_map, db, "minority-third")
    assert sorted(len(v) for v in g.values()) == [1, 1, 1, 1, 4]


# ---------------------------------------------------------------------------
# db (kill/pause) nemesis
# ---------------------------------------------------------------------------

def test_db_nemesis_kill_start(test_map):
    nem = combined.DbNemesis(test_map["db"])
    done = nem.invoke(test_map, info("kill", "all"))
    assert done.value == {x: "killed" for x in test_map["nodes"]}
    for x in test_map["nodes"]:
        assert "killall -9 -w db" in cmds(test_map, x)
    done = nem.invoke(test_map, info("start", "all"))
    assert done.value == {x: "started" for x in test_map["nodes"]}


def test_db_nemesis_pause_resume(test_map):
    nem = combined.DbNemesis(test_map["db"])
    done = nem.invoke(test_map, info("pause", ["n2"]))
    assert done.value == {"n2": "paused"}
    assert "killall -s STOP db" in cmds(test_map, "n2")
    assert "killall -s STOP db" not in cmds(test_map, "n1")
    nem.invoke(test_map, info("resume", "all"))
    assert "killall -s CONT db" in cmds(test_map, "n1")


def test_db_generators_flip_flop(test_map):
    pkg_opts = {"db": test_map["db"], "faults": {"kill"},
                "interval": 0}
    gens = combined.db_generators(pkg_opts)
    ctx = gen.context({"concurrency": 2, "nodes": test_map["nodes"]})
    o, g2 = gen.op(gens["generator"], test_map, ctx)
    assert o.f == "kill"
    o2, _ = gen.op(g2, test_map, ctx)
    assert o2.f == "start"
    assert o2.value == "all"
    assert gens["final_generator"] == [
        {"type": "info", "f": "start", "value": "all"}]


# ---------------------------------------------------------------------------
# partition + packet nemeses
# ---------------------------------------------------------------------------

def test_partition_nemesis(test_map):
    nem = combined.PartitionNemesis(test_map["db"]).setup(test_map)
    done = nem.invoke(test_map, info("start-partition", "majority"))
    assert done.f == "start-partition"
    assert done.value[0] == "isolated"
    dropped = [x for x in test_map["nodes"]
               if any("DROP" in c for c in cmds(test_map, x))]
    assert len(dropped) == 5
    done = nem.invoke(test_map, info("stop-partition"))
    assert done.f == "stop-partition"
    assert done.value == "network healed"


def test_packet_nemesis(test_map):
    nem = combined.PacketNemesis(test_map["db"]).setup(test_map)
    done = nem.invoke(
        test_map, info("start-packet", ["all", {"delay": {}}]))
    assert done.value[0] == "shaped"
    got = cmds(test_map, "n1", sudo="root")
    assert any("netem delay 50ms" in c for c in got)
    done = nem.invoke(test_map, info("stop-packet"))
    assert done.value[0] == "reliable"


# ---------------------------------------------------------------------------
# clock nemesis
# ---------------------------------------------------------------------------

def test_clock_nemesis_bump(test_map):
    nem = nt.clock_nemesis().setup(test_map)
    done = nem.invoke(test_map, info("bump", {"n1": 4000, "n3": -8000}))
    offs = done["clock-offsets"]
    assert set(offs) == {"n1", "n3"}
    assert "/opt/jepsen/bump-time 4000" in cmds(test_map, "n1", "root")
    assert "/opt/jepsen/bump-time -8000" in cmds(test_map, "n3", "root")
    done = nem.invoke(test_map, info("check-offsets"))
    assert set(done["clock-offsets"]) == set(test_map["nodes"])
    done = nem.invoke(
        test_map,
        info("strobe", {"n2": {"delta": 100, "period": 10,
                               "duration": 2}}))
    assert "/opt/jepsen/strobe-time 100 10 2" in cmds(test_map, "n2",
                                                      "root")
    done = nem.invoke(test_map, info("reset", ["n4"]))
    assert "ntpdate -b time.google.com" in cmds(test_map, "n4", "root")


# ---------------------------------------------------------------------------
# file corruption
# ---------------------------------------------------------------------------

def test_truncate_file_nemesis(test_map):
    nem = n.truncate_file()
    done = nem.invoke(test_map, info(
        "truncate", {"n1": {"file": "/data/wal", "drop": 64}}))
    assert done.value == {"n1": {"file": "/data/wal", "drop": 64}}
    assert "truncate -c -s -64 /data/wal" in cmds(test_map, "n1",
                                                  "root")


def test_bitflip_nemesis(test_map):
    nem = n.bitflip().setup(test_map)
    done = nem.invoke(test_map, info(
        "bitflip", {"n2": {"file": "/data/wal", "probability": 0.001}}))
    assert done.value["n2"]["probability"] == 0.001
    sprays = [c for c in cmds(test_map, "n2", "root")
              if c.startswith("/opt/jepsen/bitflip spray")]
    assert len(sprays) == 1
    assert sprays[0].endswith("/data/wal")
    assert "0.1" in sprays[0]  # 0.001 probability -> 0.1 percent


def test_file_corruption_nemesis_spec(test_map):
    nem = combined.FileCorruptionNemesis(test_map["db"]).setup(test_map)
    done = nem.invoke(test_map, info(
        "truncate", [["n1", "n2"], {"file": "/data/wal", "drop": 8}]))
    assert set(done.value) == {"n1", "n2"}


# ---------------------------------------------------------------------------
# hammer time
# ---------------------------------------------------------------------------

def test_hammer_time(test_map):
    nem = n.hammer_time("db")
    done = nem.invoke(test_map, info("start"))
    (node, val), = done.value.items()
    assert val == ["paused", "db"]
    assert "killall -s STOP db" in cmds(test_map, node, "root")
    # second start while held: refuses
    again = nem.invoke(test_map, info("start"))
    assert "already disrupting" in again.value
    done = nem.invoke(test_map, info("stop"))
    (node2, val2), = done.value.items()
    assert node2 == node and val2 == ["resumed", "db"]


# ---------------------------------------------------------------------------
# package composition + lifecycle
# ---------------------------------------------------------------------------

def test_nemesis_package_composes(test_map):
    pkg = combined.nemesis_package(
        {"db": test_map["db"], "interval": 0.001,
         "faults": ["partition", "kill", "pause"]})
    assert pkg["generator"] is not None
    fs = pkg["nemesis"].fs()
    assert {"start-partition", "stop-partition", "kill", "start",
            "pause", "resume"} <= fs
    assert pkg["final_generator"]
    perf_names = {p[0] for p in pkg["perf"]}
    assert {"partition", "kill", "pause"} <= perf_names


def test_package_lifecycle_end_to_end(test_map):
    """A package-driven nemesis schedule runs through the real
    interpreter clusterless: ops invoked, completions recorded."""
    from jepsen_tpu import checker, client, core, os_setup, testing

    pkg = combined.nemesis_package(
        {"db": test_map["db"], "interval": 0.001,
         "faults": ["partition"]})
    state = testing.AtomState()
    test = dict(test_map)
    test.update(
        name=None, os=os_setup.noop, ssh={},
        concurrency=2,
        client=testing.AtomClient(state),
        db=testing.AtomDB(state),
        checker=checker.stats(),
        nemesis=pkg["nemesis"],
        generator=gen.nemesis(
            gen.phases(gen.limit(4, pkg["generator"]),
                       pkg["final_generator"]),
            gen.time_limit(1.5, gen.stagger(
                0.01, lambda: {"f": "read"}))))
    test = core.run(test)
    nem_ops = [o for o in test["history"]
               if o.process == "nemesis" and o.type == "info"]
    fs = {o.f for o in nem_ops}
    assert "start-partition" in fs
    assert "stop-partition" in fs
    # the grudge really reached iptables on the dummy sessions
    all_cmds = [c for x in test_map["nodes"]
                for c in cmds(test, x)]
    assert any("-j DROP" in c for c in all_cmds)
    assert any(c == "iptables -F -w" for c in all_cmds)
    assert test["results"]["valid?"] is True
