"""OS-layer tests: CentOS (yum/rpm, start-stop-daemon source build)
and SmartOS (pkgin, ipfilter) command emission over the dummy remote
(mirror jepsen/src/jepsen/os/centos.clj, smartos.clj)."""

from jepsen_tpu import control, testing
from jepsen_tpu.control.core import Action
from jepsen_tpu.control.dummy import DummyRemote


def test_centos_os_commands():
    from jepsen_tpu.control.core import Result
    from jepsen_tpu.os_setup import centos

    def responder(node, action):
        if action.cmd.startswith("rpm -qa"):
            return Result(exit=0, out="wget\ncurl\n", err="",
                          cmd=action.cmd)
        if action.cmd.startswith("stat "):
            return Result(exit=1, out="", err="absent", cmd=action.cmd)
        return None

    remote = DummyRemote(responder)
    test = testing.noop_test()
    test.update(nodes=["n1"], remote=remote,
                sessions={"n1": remote.connect({"host": "n1"})})
    with control.with_session(test, "n1"):
        centos.os.setup(test, "n1")
    cmds = [a.cmd for a in test["sessions"]["n1"].log
            if isinstance(a, Action)]
    joined = " ; ".join(cmds)
    yum = next(c for c in cmds if "yum -y install" in c)
    assert "gcc" in yum
    # wget/curl report installed via rpm -qa: not re-installed
    assert " wget" not in yum and " curl " not in yum + " "
    assert "start-stop-daemon" in joined  # built from dpkg source


def test_smartos_os_commands():
    from jepsen_tpu.control.core import Result
    from jepsen_tpu.os_setup import smartos

    def responder(node, action):
        if action.cmd.startswith("pkgin -p list"):
            return Result(exit=0, out="curl-8.0\nwget-1.21\n", err="",
                          cmd=action.cmd)
        return None

    remote = DummyRemote(responder)
    test = testing.noop_test()
    test.update(nodes=["n1"], remote=remote,
                sessions={"n1": remote.connect({"host": "n1"})})
    with control.with_session(test, "n1"):
        smartos.os.setup(test, "n1")
    cmds = [a.cmd for a in test["sessions"]["n1"].log
            if isinstance(a, Action)]
    inst = next(c for c in cmds if "pkgin -y install" in c)
    assert "gcc10" in inst and "curl" not in inst.split("install")[1]
    assert any("svcadm enable -r ipfilter" in c for c in cmds)


class TestCentOSRegressions:
    def test_centos_daemon_build_runs_in_workdir(self):
        from jepsen_tpu.control.core import Result
        from jepsen_tpu.os_setup import centos

        remote = DummyRemote()
        test = testing.noop_test()
        test.update(nodes=["n1"], remote=remote,
                    sessions={"n1": remote.connect({"host": "n1"})})
        with control.with_session(test, "n1"):
            centos.install_start_stop_daemon()
        acts = [a for a in test["sessions"]["n1"].log
                if isinstance(a, Action)]
        cp = next(a for a in acts if a.cmd.startswith("cp "))
        assert cp.dir == "/tmp/jepsen/dpkg-build/dpkg-1.17.27"
        assert "utils/start-stop-daemon" in cp.cmd
