"""The fleet flight recorder (ISSUE 17): latency-block schema, record
schema (chaos-proof chunk-span uniqueness), streaming SLO histograms
with cross-process persistence, per-class occupancy + decision-log
accounting, Perfetto fleet-session export, and the Prometheus
scrape-parse gate.

The fleet-integration side (every verdict carries a schema-valid
block, decision counts sum to launches, byte-identical verdicts with
the recorder off) lives in tests/test_fleet.py with the rest of the
service suite.
"""

import json
import threading

import pytest

from jepsen_tpu import telemetry
from jepsen_tpu.fleet import flightrec as frec
from jepsen_tpu.monitor import LogHistogram
from jepsen_tpu.reports import trace as rtrace


class _Item:
    def __init__(self, tenant):
        self.tenant = tenant


# ---------------------------------------------------------------------------
# latency blocks
# ---------------------------------------------------------------------------

class TestLatencyBlock:
    def test_block_schema_and_total(self):
        b = frec.latency_block(ingest_wait_ms=5.0, wal_fsync_ms=1.0,
                               queue_wait_ms=2.0,
                               batching_delay_ms=0.5, encode_ms=3.0,
                               device_ms=10.0, certify_ms=1.5,
                               serialize_ms=0.25)
        frec.validate_latency(b)
        assert set(b) == set(frec.LATENCY_KEYS) | {"total_ms"}
        assert b["total_ms"] == pytest.approx(23.25)
        assert frec.dominant_slice(b) == ("device", 10.0)

    def test_negative_clock_tie_clamps_to_zero(self):
        b = frec.latency_block(encode_ms=-0.4, device_ms=1.0)
        frec.validate_latency(b)
        assert b["encode"] == 0.0

    def test_replay_block_is_schema_valid_and_annotated(self):
        b = frec.replay_block()
        frec.validate_latency(b)
        assert b["replay"] is True
        assert b["total_ms"] == 0.0

    @pytest.mark.parametrize("bad", [
        None,
        {},
        {k: 0.0 for k in frec.LATENCY_KEYS},            # no total
        dict(frec.latency_block(), extra=1.0),           # unknown key
        dict(frec.latency_block(), device=-1.0),         # negative
        dict(frec.latency_block(), device="1"),          # non-numeric
        dict(frec.latency_block(), replay=False),        # bad replay
    ])
    def test_validate_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            frec.validate_latency(bad)


# ---------------------------------------------------------------------------
# record schema
# ---------------------------------------------------------------------------

def _chunk_rec(tenant="t0", run="r", seq=1, t0=100, t1=200):
    return {"kind": "chunk", "tenant": tenant, "run": run, "seq": seq,
            "t0": t0, "t1": t1, "wal_ms": 0.5, "ack_ms": 1.0,
            "ops": 10}


def _launch_rec(cls="final", reason="timeout", rows=4, cap=64):
    return {"kind": "launch", "cls": cls, "reason": reason, "t0": 10,
            "t1": 20, "rows": rows, "capacity": cap,
            "occupancy": rows / cap, "tenants": ["t0"],
            "device_ms": 1.0, "certify_ms": 0.1}


def _verdict_rec(tenant="t0", run="r"):
    return {"kind": "verdict", "tenant": tenant, "run": run, "t0": 5,
            "t1": 50, "latency": frec.latency_block(device_ms=1.0)}


class TestRecordSchema:
    def test_valid_mixture_counts(self):
        recs = [_chunk_rec(seq=1), _chunk_rec(seq=2), _launch_rec(),
                _verdict_rec()]
        assert frec.validate_records(recs) == 4

    def test_duplicate_chunk_span_rejected(self):
        # the chaos-parity gate: a duplicated/reordered frame that
        # somehow journaled twice would show up as two spans for one
        # (tenant, run, seq) — the validator refuses it
        with pytest.raises(ValueError, match="duplicate chunk"):
            frec.validate_records([_chunk_rec(seq=3), _chunk_rec(seq=3)])

    def test_same_seq_different_runs_is_fine(self):
        frec.validate_records([_chunk_rec(run="a"), _chunk_rec(run="b")])

    @pytest.mark.parametrize("rec", [
        {"kind": "nope", "t0": 0, "t1": 1},
        {"kind": "chunk", "tenant": "t", "run": "r", "seq": 0,
         "t0": 0, "t1": 1, "wal_ms": 0, "ack_ms": 0},
        {"kind": "chunk", "tenant": "t", "run": "r", "seq": 1,
         "t0": 5, "t1": 4, "wal_ms": 0, "ack_ms": 0},  # t1 < t0
        dict(_launch_rec(), reason="because"),
        dict(_launch_rec(), cls="warmup"),
        dict(_launch_rec(), occupancy=1.5),
        dict(_launch_rec(), rows=-1),
        dict(_verdict_rec(), latency=None),
    ])
    def test_malformed_rejected(self, rec):
        with pytest.raises(ValueError):
            frec.validate_records([rec])


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_disabled_recorder_is_inert(self):
        fr = frec.FlightRecorder(enabled=False)
        fr.chunk("t", "r", 1, 0, 10, 5, 3)
        fr.launch("final", "timeout", 0, 10, 4, 64, [_Item("t")])
        fr.verdict("t", "r", 0, 10, frec.latency_block())
        assert fr.records() == []
        assert fr.snapshot() == {"enabled": False}

    def test_decision_counts_sum_to_launches(self):
        fr = frec.FlightRecorder()
        fr.launch("slice", "full", 0, 10, 64, 64, [_Item("a")])
        fr.launch("slice", "timeout", 20, 30, 8, 64, [_Item("a")])
        fr.launch("final", "drain", 40, 50, 2, 64,
                  [_Item("a"), _Item("b")])
        fr.launch("final", "breaker", 60, 70, 1, 64, [_Item("b")])
        s = fr.snapshot()
        assert sum(s["decisions"].values()) == s["launches"] == 4
        assert s["classes"]["slice"]["launches"] == 2
        assert s["classes"]["final"]["launches"] == 2
        # occupancy is per-class packed-rows/capacity, not blended
        assert s["classes"]["slice"]["occupancy"] == pytest.approx(
            (1.0 + 8 / 64) / 2)
        assert s["classes"]["final"]["occupancy"] == pytest.approx(
            (2 / 64 + 1 / 64) / 2, abs=1e-4)
        frec.validate_records(fr.records())

    def test_idle_gap_accounting(self):
        fr = frec.FlightRecorder()
        ms = 1_000_000  # ns
        fr.launch("final", "timeout", 0, 10 * ms, 1, 64, [_Item("a")])
        fr.launch("final", "timeout", 25 * ms, 30 * ms, 1, 64,
                  [_Item("a")])
        s = fr.snapshot()
        assert s["idle"]["gaps"] == 1
        assert s["idle"]["total_ms"] == pytest.approx(15.0)

    def test_fairness_counters_split_rows_by_item_share(self):
        fr = frec.FlightRecorder()
        items = [_Item("a"), _Item("a"), _Item("b")]
        fr.launch("final", "timeout", 0, 10, 9, 64, items)
        f = fr.snapshot()["fairness"]
        assert f["a"] == {"items": 2, "rows": 6, "launches": 1}
        assert f["b"] == {"items": 1, "rows": 3, "launches": 1}

    def test_chunk_span_extends_to_plausible_client_stamp(self):
        fr = frec.FlightRecorder()
        t0 = frec.now()
        fr.chunk("t", "r", 1, t0, t0 + 1_000_000, 500, 10,
                 client_t=t0 - 2_000_000)
        rec = fr.records()[0]
        assert rec["t0"] == t0 - 2_000_000
        assert rec["ack_ms"] == pytest.approx(3.0)

    def test_chunk_span_ignores_implausible_client_stamp(self):
        fr = frec.FlightRecorder()
        t0 = frec.now()
        # a different clock domain (way in the past) must not stretch
        # the span; so must a stamp from the "future"
        fr.chunk("t", "r", 1, t0, t0 + 1_000_000, 500, 10, client_t=1)
        fr.chunk("t", "r", 2, t0, t0 + 1_000_000, 500, 10,
                 client_t=t0 + 5_000_000)
        assert [r["t0"] for r in fr.records()] == [t0, t0]

    def test_tenant_histograms_and_quantiles(self):
        fr = frec.FlightRecorder()
        for i in range(20):
            fr.verdict("a", f"r{i}", 0, (i + 1) * 1_000_000,
                       frec.latency_block())
        s = fr.snapshot()
        assert s["verdicts"] == 20
        assert s["verdict_ms"]["n"] == 20
        assert s["tenants"]["a"]["verdict_ms"]["n"] == 20
        # log-bucketed estimate lands within one bucket (~9%)
        assert s["verdict_ms"]["p50"] == pytest.approx(11.0, rel=0.1)

    def test_record_ring_is_bounded(self):
        fr = frec.FlightRecorder(max_records=8)
        for i in range(1, 30):
            fr.chunk("t", "r", i, i, i + 1, 0, 1)
        assert len(fr.records()) == 8

    def test_save_load_fold_round_trip(self, tmp_path):
        fr = frec.FlightRecorder()
        fr.chunk("t", "r", 1, 100, 200, 50, 10)
        fr.launch("final", "full", 0, 10, 64, 64, [_Item("t")])
        fr.verdict("t", "r", 0, 7_000_000, frec.latency_block())
        p = tmp_path / frec.SNAPSHOT_FILE
        fr.save(p)
        fr2 = frec.FlightRecorder()
        assert fr2.load(p) is True
        s1, s2 = fr.snapshot(), fr2.snapshot()
        assert s1 == s2
        # folding the same snapshot again doubles the counters —
        # histogram merge + counter add, the cross-process observer
        fr2.load(p)
        s3 = fr2.snapshot()
        assert s3["verdicts"] == 2 * s1["verdicts"]
        assert s3["verdict_ms"]["n"] == 2
        assert s3["decisions"]["full"] == 2

    def test_load_tolerates_missing_and_torn(self, tmp_path):
        fr = frec.FlightRecorder()
        assert fr.load(tmp_path / "nope.json") is False
        torn = tmp_path / "torn.json"
        torn.write_text('{"verdicts": 3, "verdict_ms": {"co')
        assert fr.load(torn) is False
        assert fr.snapshot()["verdicts"] == 0

    def test_concurrent_saves_never_lose_the_file(self, tmp_path):
        fr = frec.FlightRecorder()
        fr.verdict("t", "r", 0, 1_000_000, frec.latency_block())
        p = tmp_path / frec.SNAPSHOT_FILE
        errs = []

        def saver():
            try:
                for _ in range(50):
                    fr.save(p)
            except OSError as e:  # the bug this guards: tmp renamed
                errs.append(e)   # out from under a racing writer

        ts = [threading.Thread(target=saver) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        assert json.loads(p.read_text())["verdicts"] == 1


# ---------------------------------------------------------------------------
# kernel-phase join
# ---------------------------------------------------------------------------

class TestKernelPhases:
    def test_joins_kernel_and_certify_spans_in_window(self):
        telemetry.reset()
        from jepsen_tpu import util

        r0 = util.relative_time_nanos()
        with telemetry.span("kernel:wgl-test"):
            pass
        with telemetry.span("certify.attach"):
            pass
        with telemetry.span("unrelated"):
            pass
        r1 = util.relative_time_nanos()
        device, cert = frec.kernel_phases(r0, r1)
        assert device > 0
        assert cert > 0
        # outside the window: nothing
        assert frec.kernel_phases(r1 + 10, r1 + 20) == (0.0, 0.0)
        telemetry.reset()


# ---------------------------------------------------------------------------
# exports: Perfetto + Prometheus
# ---------------------------------------------------------------------------

class TestExports:
    def test_fleet_chrome_trace_validates(self):
        fr = frec.FlightRecorder()
        ms = 1_000_000
        for i in range(1, 4):
            fr.chunk("alpha", "r", i, i * 10 * ms, i * 10 * ms + ms,
                     ms // 2, 16, trace="abc123")
        fr.chunk("beta", "r", 1, 5 * ms, 6 * ms, ms // 4, 8)
        fr.launch("slice", "full", 40 * ms, 50 * ms, 64, 64,
                  [_Item("alpha"), _Item("beta")], device_ms=5.0)
        fr.launch("final", "timeout", 60 * ms, 80 * ms, 2, 64,
                  [_Item("alpha")], device_ms=10.0, certify_ms=1.0)
        fr.verdict("alpha", "r", 60 * ms, 90 * ms,
                   frec.latency_block(device_ms=10.0))
        fr.verdict("beta", "r", 60 * ms, 95 * ms, frec.replay_block())
        doc = rtrace.fleet_chrome_trace(fr.records())
        n = rtrace.validate_chrome_trace(doc)
        assert n > 0
        evs = doc["traceEvents"]
        names = {e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        # one track per tenant + the service swimlanes
        assert {"alpha", "beta", "device launches", "wal",
                "scheduler"} <= names
        # decision instants mirror the launches
        assert sorted(e["name"] for e in evs if e["ph"] == "i") == \
            ["full", "timeout"]
        # occupancy counter per class
        cvals = [e["args"] for e in evs
                 if e["ph"] == "C" and e["name"] == "batch occupancy"]
        assert {"slice": 1.0} in cvals
        # timestamps rebased to the earliest record
        assert min(e["ts"] for e in evs if e["ph"] != "M") == 0.0

    def test_fleet_chrome_trace_empty_records(self):
        doc = rtrace.fleet_chrome_trace([])
        assert rtrace.validate_chrome_trace(doc) >= 0

    def test_prometheus_validator(self):
        good = ('# HELP x y\n'
                'jepsen_fleet_verdict_latency_ms{q="p99"} 12.5\n'
                'jepsen_fleet_tenant_ack_latency_ms'
                '{tenant="a",q="p50"} 0.25\n'
                'jepsen_fleet_decisions_total{reason="timeout"} 3\n'
                'jepsen_fleet_launches 4\n')
        assert frec.validate_prometheus(good) == 4
        with pytest.raises(ValueError):
            frec.validate_prometheus('jepsen bad line\n')
        with pytest.raises(ValueError):
            frec.validate_prometheus(
                'jepsen_fleet_x{tenant=unquoted} 1\n')


# ---------------------------------------------------------------------------
# CLI / web renderers (pure text-from-dict)
# ---------------------------------------------------------------------------

def _stats_fixture():
    fr = frec.FlightRecorder()
    fr.chunk("a", "r", 1, 0, 2_000_000, 1_000_000, 10)
    fr.launch("final", "timeout", 0, 5_000_000, 4, 64,
              [_Item("a")])
    fr.verdict("a", "r", 0, 9_000_000, frec.latency_block())
    return {"streams": 1, "chunks": 1, "verdicts": 1,
            "scheduler": {"launches": 1},
            "flightrec": fr.snapshot()}


class TestRenderers:
    def test_fleet_top_lines(self):
        from jepsen_tpu import cli

        lines = cli._fleet_top_lines(_stats_fixture())
        text = "\n".join(lines)
        assert "verdict ms" in text
        assert "a" in text
        assert "final" in text
        assert "timeout=1" in text
        # disabled recorder renders honestly
        lines = cli._fleet_top_lines({"flightrec": {"enabled": False}})
        assert any("disabled" in ln for ln in lines)

    def test_web_event_payload_and_section(self):
        from jepsen_tpu import web

        st = _stats_fixture()
        payload = web.fleet_event_payload(st)
        assert payload["enabled"] is True
        assert payload["launches"] == 1
        assert payload["occupancy"]["final"] == pytest.approx(
            4 / 64, abs=1e-4)
        assert json.loads(json.dumps(payload)) == payload
        assert web.fleet_event_payload({}) == {"enabled": False}
        html = web._flightrec_html(st["flightrec"])
        assert "flight recorder" in html
        assert "EventSource" in html
        assert "disabled" in web._flightrec_html({"enabled": False})
