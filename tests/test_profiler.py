"""Device-performance observability: the kernel profiler (per-launch
cost records, cache accounting, wall/device split), the cross-run perf
ledger + slow-bleed detector, the profile CLI/table, the Prometheus
endpoint, and the metrics/ledger schema validators (ISSUE 6)."""

from __future__ import annotations

import argparse
import json
import urllib.request

import numpy as np
import pytest

from jepsen_tpu import ledger, telemetry, util
from jepsen_tpu.checker import models
from jepsen_tpu.reports import profile as rprofile
from jepsen_tpu.reports import telemetry as rtel
from jepsen_tpu.tpu import profiler, scc, synth, wgl
from jepsen_tpu.tpu.encode import encode


@pytest.fixture
def fresh():
    """Fresh clocks + recorders; wgl's compiled-bucket set is cleared
    (and restored) so cache accounting is deterministic per test."""
    util.init_relative_time()
    telemetry.reset()
    profiler.reset()
    saved = set(wgl._compiled_buckets)
    wgl._compiled_buckets.clear()
    yield profiler.get()
    wgl._compiled_buckets.update(saved)


def _launch_small(seed=1):
    hist = synth.register_history(64, n_procs=3, seed=seed)
    enc = encode(models.cas_register(), hist)
    return wgl.check_batch([enc])


class TestLaunchRecords:
    def test_wgl_cost_fields_present_and_plausible(self, fresh):
        res = _launch_small()
        assert int(res[0]) == wgl.VALID
        recs = [r for r in fresh.records() if r["kernel"] == "wgl"]
        assert recs, "no wgl launch record"
        r = recs[0]
        # cost analysis: a 64-entry search still moves real work
        assert r["flops"] and r["flops"] > 1e3
        assert r["bytes_accessed"] and r["bytes_accessed"] > 1e3
        assert r["peak_memory_bytes"] and r["peak_memory_bytes"] > 1e3
        # wall/device split: the pipeline phases all recorded, and sum
        # within the record's wall time (monotonic vs linear clocks
        # differ, so allow slack via presence + positivity only)
        for ph in ("h2d_ns", "dispatch_ns", "compute_ns", "d2h_ns"):
            assert r.get(ph, 0) > 0, ph
        assert r["t1"] > r["t0"]
        assert r["iterations"] > 0
        assert r["compile_ns"] > 0  # first launch of the bucket

    def test_encode_and_pack_accounted(self, fresh):
        _launch_small()
        c = telemetry.get().counters()
        assert c["profiler.encode.launches"] >= 1
        assert c["profiler.encode.wall_ns"] > 0
        assert c["profiler.encode.entries"] > 0
        assert c["profiler.pack.launches"] >= 1

    def test_cache_hit_miss_across_repeated_buckets(self, fresh):
        _launch_small(seed=1)
        assert fresh.cache_stats["wgl"] == {"hits": 0, "misses": 1}
        _launch_small(seed=2)  # same shape bucket -> hit
        assert fresh.cache_stats["wgl"] == {"hits": 1, "misses": 1}
        # a different shape bucket compiles anew
        hist = synth.register_history(200, n_procs=3, seed=3)
        wgl.check_batch([encode(models.cas_register(), hist)])
        assert fresh.cache_stats["wgl"]["misses"] == 2
        c = telemetry.get().counters()
        assert c["profiler.wgl.compile.hit"] == 1
        assert c["profiler.wgl.compile.miss"] == 2
        # hit launches reuse the bucket's cached cost analysis
        hits = [r for r in fresh.records()
                if r["kernel"] == "wgl" and "compile_ns" not in r]
        assert hits and all(r.get("flops") for r in hits)

    def test_scc_launch_record(self, fresh):
        rng = np.random.default_rng(0)
        n, e = 2000, 25_000  # past DEVICE_MIN_EDGES
        labels = scc.scc(n, rng.integers(0, n, e),
                         rng.integers(0, n, e), device=True)
        assert labels is not None and len(labels) == n
        recs = [r for r in fresh.records() if r["kernel"] == "scc"]
        assert recs
        r = recs[0]
        assert r["nodes"] == n and r["edges"] == e
        assert r["flops"] and r["bytes_accessed"]
        assert r.get("compute_ns", 0) > 0

    def test_elle_launch_record(self, fresh):
        from jepsen_tpu.tpu import elle

        hist = synth.list_append_history(600, seed=3)
        res = elle.check_list_append(hist, {"engine": "device"})
        assert res["valid?"] is True
        recs = [r for r in fresh.records()
                if r["kernel"] == "elle-append"]
        assert recs
        r = recs[0]
        assert r["txns"] > 0 and r["edges"] > 0
        assert r["encode_ns"] > 0  # host flatten/edge inference
        assert r["compute_ns"] > 0  # cycle detection

    def test_sharded_launch_attribution(self, fresh):
        from jepsen_tpu.tpu import ensemble

        hists = [synth.register_history(24, n_procs=3, seed=i)
                 for i in range(4)]
        encs = [encode(models.cas_register(), h) for h in hists]
        mesh = ensemble.default_mesh(1)
        res = ensemble.check_batch_sharded(encs, mesh=mesh, W=16, F=16)
        assert all(int(r) == wgl.VALID for r in res)
        recs = [r for r in fresh.records()
                if r["kernel"] == "wgl-sharded"]
        assert recs
        r = recs[0]
        assert r["devices"] == 1
        assert len(r["device_entries"]) == 1
        assert r["device_entries"][0] > 0
        assert r["balance"] == 1.0  # one device is trivially balanced

    def test_launch_records_land_in_telemetry_and_trace(self, fresh):
        from jepsen_tpu.reports import trace as rtrace

        _launch_small()
        spans = telemetry.get().events()
        kernel_spans = [s for s in spans
                        if s["name"].startswith("kernel:")]
        assert kernel_spans and kernel_spans[0]["attrs"]["flops"]
        doc = rtrace.chrome_trace({}, [], spans)
        rtrace.validate_chrome_trace(doc)
        dev = [e for e in doc["traceEvents"]
               if e.get("pid") == rtrace._PID_DEVICE
               and e.get("ph") == "X"]
        assert dev, "no device-track launch slices"
        assert dev[0]["name"] == "wgl"
        # kernel spans moved off the harness flame onto the device track
        harness = [e for e in doc["traceEvents"]
                   if e.get("pid") == rtrace._PID_HARNESS
                   and str(e.get("name", "")).startswith("kernel:")]
        assert not harness

    def test_metrics_json_schema_validates(self, fresh, tmp_path):
        _launch_small()
        _trace, mpath = telemetry.save(tmp_path)
        with open(mpath) as f:
            metrics = json.load(f)
        assert telemetry.validate_metrics(metrics) > 0

    def test_validate_metrics_rejects_bad_docs(self):
        with pytest.raises(ValueError):
            telemetry.validate_metrics({"spans": {}, "counters": {}})
        with pytest.raises(ValueError):
            telemetry.validate_metrics(
                {"spans": {"x": {"count": 1, "total_ns": -5,
                                 "max_ns": 0}},
                 "counters": {}, "gauges": {}})
        with pytest.raises(ValueError):
            telemetry.validate_metrics(
                {"spans": {}, "counters": {"c": "nope"}, "gauges": {}})
        assert telemetry.validate_metrics(
            {"spans": {"x": {"count": 2, "total_ns": 10,
                             "max_ns": 7}},
             "counters": {"c": 3}, "gauges": {"g": 1.5}}) == 3


class TestRecorderGuards:
    def test_straggler_record_dropped_after_reset(self, fresh):
        """A record opened before telemetry.reset() (the next run
        starting) is dropped at finish: its clock origin is stale."""
        rec = fresh.begin("wgl", bucket=("b",))
        telemetry.reset()
        fresh.finish(rec)
        assert fresh.records() == []
        assert not [s for s in telemetry.get()._spans
                    if s["name"].startswith("kernel:")]
        assert "profiler.wgl.launches" not in telemetry.get().counters()

    def test_record_span_epoch_guard(self):
        telemetry.reset()
        e = telemetry.get().epoch
        assert telemetry.record_span("kernel:w", 0, 5, epoch=e)
        telemetry.reset()
        assert telemetry.record_span("kernel:w", 0, 5, epoch=e) is None

    def test_disabled_profiler_is_noop(self):
        telemetry.reset()
        p = profiler.Profiler(enabled=False)
        rec = p.begin("wgl")
        p.cache_event("wgl", True)
        p.record_host("pack", 100, entries=5)
        p.finish(rec)
        assert p.records() == [] and p.cache_stats == {}
        # bucket_cost must not pay the lowering either
        cost = p.bucket_cost(("b",), lambda: 1 / 0, True)
        assert cost == {k: None for k in profiler.COST_FIELDS}
        assert not [c for c in telemetry.get().counters()
                    if c.startswith("profiler.")]

    def test_span_mirror_cap_counts_drops(self, fresh, monkeypatch):
        """The telemetry-mirror cap is configurable and never silent:
        launches past it count profiler.<k>.spans_dropped instead of
        vanishing (ISSUE-7 no-silent-caps satellite)."""
        monkeypatch.setenv("JEPSEN_TPU_PROFILE_MAX_SPANS", "3")
        p = profiler.get()
        for _ in range(5):
            p.finish(p.begin("wgl"))
        spans = [s for s in telemetry.get().events()
                 if s["name"] == "kernel:wgl"]
        counters = telemetry.get().counters()
        assert len(spans) == 3
        assert counters["profiler.wgl.spans_dropped"] == 2
        # aggregates still saw every launch
        assert counters["profiler.wgl.launches"] == 5

    def test_span_mirror_cap_default_unchanged(self, fresh):
        assert profiler.max_mirrored_launches() == \
            profiler.MAX_MIRRORED_LAUNCHES

    def test_bucket_unclaim_re_fresh(self, fresh):
        """A failed first launch releases its bucket claim, so the
        retry's real recompile records a miss, not a phantom hit."""
        assert fresh.bucket_fresh("scc", ("x",)) is True
        fresh.bucket_unclaim("scc", ("x",))
        assert fresh.bucket_fresh("scc", ("x",)) is True
        assert fresh.cache_stats["scc"] == {"hits": 0, "misses": 2}

    def test_scc_failure_unclaims_bucket(self, fresh, monkeypatch):
        """Site-level: an scc device launch that dies keeps the bucket
        fresh for the retry (the wgl._timed_launch discard analog).
        Single-device path pinned via JEPSEN_TPU_SPMD=0 (the sharded
        factory is a separate bucket family)."""
        def boom(*a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: boom")

        monkeypatch.setenv("JEPSEN_TPU_SPMD", "0")
        monkeypatch.setattr(scc, "_jitted_scc", lambda *a, **k: boom)
        rng = np.random.default_rng(0)
        n, e = 2000, 25_000
        # _seen_buckets persists for the process (it mirrors the XLA
        # cache); unclaim so the bucket is fresh whatever ran before
        fresh.bucket_unclaim("scc", ("scc", scc._next_pow2(n + 1),
                                     scc._edge_pad(e)))
        with pytest.raises(RuntimeError):
            scc.scc_device(n, rng.integers(0, n, e),
                           rng.integers(0, n, e))
        assert fresh.cache_stats["scc"] == {"hits": 0, "misses": 1}
        # the claim was released: the same shape is fresh (miss) again
        n_pad, e_pad = scc._next_pow2(n + 1), scc._edge_pad(e)
        assert fresh.bucket_fresh("scc", ("scc", n_pad, e_pad)) is True

    def test_pending_overflow_finalizes_every_stray(self, fresh):
        """The parking-lot cap aggregates ALL strays, dropping none."""
        objs = [object() for _ in range(257)]
        for o in objs:
            fresh.attach(o, fresh.begin("wgl"))
        fresh.attach(object(), fresh.begin("wgl"))  # trips the sweep
        assert len(fresh.records()) == 257
        c = telemetry.get().counters()
        assert c["profiler.wgl.launches"] == 257

    def test_memory_analysis_env_off(self, fresh, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PROFILE_MEMORY", "0")
        assert not profiler._memory_analysis_enabled()
        monkeypatch.setenv("JEPSEN_TPU_PROFILE_MEMORY", "1")
        assert profiler._memory_analysis_enabled()


class TestProfileReport:
    def _metrics(self, fresh):
        _launch_small()
        return telemetry.get().metrics()

    def test_kernel_table(self, fresh):
        m = self._metrics(fresh)
        rows = rprofile.kernel_rows(m)
        by_kernel = {r["kernel"]: r for r in rows}
        assert "wgl" in by_kernel and "encode" in by_kernel
        w = by_kernel["wgl"]
        assert w["launches"] == 1
        assert w["cache"] == "0/1"
        assert w["flops"] != "-" and w["bytes"] != "-"
        assert w["peak_mem"] != "-"
        assert "compute" in w["split"]
        text = rprofile.profile_text(telemetry.get().events(), m)
        assert "FLOPs" in text and "wgl" in text
        assert "Slowest launches" in text
        html = rprofile.profile_html(m)
        assert "kernel profile" in html and "wgl" in html

    def test_profile_cli(self, fresh, tmp_path, capsys):
        from jepsen_tpu import cli

        _launch_small()
        telemetry.save(tmp_path)
        cmd = cli.profile_cmd()["profile"]
        p = argparse.ArgumentParser()
        cmd["parser_fn"](p)
        rc = cmd["run"](p.parse_args([str(tmp_path)]))
        out = capsys.readouterr().out
        assert rc == 0
        assert "FLOPs" in out and "peak mem" in out and "wgl" in out

    def test_empty_profile(self):
        assert "no kernel launches" in rprofile.profile_text([], {})
        assert rprofile.profile_html({}) == ""


class TestPrometheus:
    def test_text_scrape_parses(self, fresh):
        _launch_small()
        m = telemetry.get().metrics()
        text = rprofile.prometheus_text(m, run="reg/20260803")
        n = rprofile.validate_prometheus_text(text)
        assert n > 5
        assert "jepsen_tpu_profiler_wgl_launches" in text

    def test_endpoint_scrape_parses(self, fresh, tmp_path):
        from jepsen_tpu import web

        _launch_small()
        run = tmp_path / "reg" / "t1"
        run.mkdir(parents=True)
        telemetry.save(run)
        server = web.serve("127.0.0.1", 0, base=tmp_path)
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics?run=reg/t1",
                    timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/plain")
                body = resp.read().decode()
        finally:
            server.shutdown()
        assert rprofile.validate_prometheus_text(body) > 0
        assert 'run="reg/t1"' in body

    def test_endpoint_404_without_metrics(self, tmp_path):
        from jepsen_tpu import web

        server = web.serve("127.0.0.1", 0, base=tmp_path)
        try:
            port = server.server_address[1]
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics?run=nope",
                    timeout=10)
            assert ei.value.code == 404
        finally:
            server.shutdown()


class TestLedger:
    def test_slow_bleed_fires_on_drift(self):
        # three consecutive 10% drops — each under the 20% per-round
        # gate — accumulate into a flagged bleed
        v = ledger.slow_bleed([100.0, 100.0, 90.0, 81.0, 72.9])
        assert v["bleeding"] is True
        assert v["drop"] > 0.15

    def test_slow_bleed_silent_on_noise(self):
        v = ledger.slow_bleed([100.0, 96.0, 104.0, 99.0, 101.0])
        assert v["bleeding"] is False
        # a one-round dip that recovers is noise, not a bleed
        assert ledger.slow_bleed([100.0, 85.0, 100.0])["bleeding"] \
            is False

    def test_slow_bleed_needs_history(self):
        assert ledger.slow_bleed([100.0, 50.0])["bleeding"] is False

    def test_slow_bleed_lower_is_better(self):
        # seconds creeping UP is a bleed when lower is better
        v = ledger.slow_bleed([10.0, 10.0, 11.1, 12.3, 13.7],
                              higher_is_better=False)
        assert v["bleeding"] is True
        v = ledger.slow_bleed([13.7, 12.3, 11.1, 10.0, 10.0],
                              higher_is_better=False)
        assert v["bleeding"] is False

    def _entry(self, rnd, hl=70000.0, **kernels):
        return {"round": rnd, "ts": 1000.0 + rnd,
                "headline": {"metric": "m", "value": hl,
                             "unit": "ops/s"},
                "kernels": {k: ({"value": v, "higher_is_better": True}
                                if isinstance(v, (int, float)) else v)
                            for k, v in kernels.items()}}

    def test_detect_attributes_per_kernel(self):
        entries = [self._entry(i + 1,
                               wgl=100.0 * (0.9 ** max(0, i - 1)),
                               elle=50.0 + (i % 2))
                   for i in range(5)]
        verdicts = ledger.detect(entries)
        assert verdicts["wgl"]["bleeding"] is True
        assert verdicts["elle"]["bleeding"] is False

    def test_append_read_validate_roundtrip(self, tmp_path):
        path = tmp_path / ledger.LEDGER_FILE
        for i in range(3):
            ledger.append_entry(path, self._entry(i + 1, wgl=100.0))
        entries = ledger.read_entries(path)
        assert ledger.validate_entries(entries) == 3
        assert ledger.next_round(entries) == 4
        assert ledger.next_round(entries, floor=9) == 10
        # torn trailing line is dropped, not raised
        with open(path, "a") as f:
            f.write('{"round": 4, "ts"')
        assert len(ledger.read_entries(path)) == 3

    def test_validate_rejects_bad_entries(self):
        good = self._entry(1)
        with pytest.raises(ValueError, match="monotonic"):
            ledger.validate_entries([good, self._entry(1)])
        with pytest.raises(ValueError, match="missing"):
            ledger.validate_entries([{"round": 1, "ts": 1.0}])
        bad = self._entry(2)
        bad["headline"] = {"metric": "m"}
        with pytest.raises(ValueError, match="headline"):
            ledger.validate_entries([bad])


class TestRegressionGate:
    """The bench gate now compares against the BEST of the last 3
    rounds: two consecutive ~15% drops can't slip through."""

    def _gate(self, monkeypatch, tmp_path, rounds):
        import bench

        path = tmp_path / ledger.LEDGER_FILE
        for i, v in enumerate(rounds):
            ledger.append_entry(path, {
                "round": i + 1, "ts": float(i),
                "headline": {"metric": "m", "value": v,
                             "unit": "ops/s"},
                "kernels": {}})
        monkeypatch.setattr(bench, "_ledger_path", lambda: str(path))
        monkeypatch.setattr(bench, "_bench_rounds", lambda: [])
        return bench

    def test_two_15pct_drops_trip_the_gate(self, monkeypatch,
                                           tmp_path):
        bench = self._gate(monkeypatch, tmp_path, [100_000.0, 85_000.0])
        line = bench._check_regression(
            {"metric": "m", "value": 72_250.0, "unit": "ops/s"})
        # old gate: 72.25k vs 85k = -15%, passes. New gate: vs best of
        # the window (100k) = -27.75%, trips.
        assert line.get("regression") is True
        assert line["prev_value"] == 100_000.0
        assert line["prev_rounds"] == [1, 2]

    def test_single_small_drop_passes(self, monkeypatch, tmp_path):
        bench = self._gate(monkeypatch, tmp_path, [100_000.0])
        line = bench._check_regression(
            {"metric": "m", "value": 90_000.0, "unit": "ops/s"})
        assert "regression" not in line
        assert line["vs_prev"] == 0.9

    def test_ledger_update_appends_and_flags_bleed(self, monkeypatch,
                                                   tmp_path):
        # synthetic drift fixture: three 10% drops already on the
        # ledger; this round continues the drift. The per-round gate
        # (20%) never tripped, the bleed detector must.
        bench = self._gate(monkeypatch, tmp_path,
                           [100_000.0, 100_000.0, 90_000.0, 81_000.0])
        headline = {"metric": "m", "value": 72_900.0, "unit": "ops/s",
                    "runs_s": [1.0], "spread": 0.1}
        headline = bench._ledger_update([], headline)
        entries = ledger.read_entries(tmp_path / ledger.LEDGER_FILE)
        assert len(entries) == 5  # appended this round
        assert entries[-1]["round"] == 5
        assert headline["slow_bleed"]["headline"] > 0.15

    def test_ledger_entry_shape(self, monkeypatch, tmp_path):
        bench = self._gate(monkeypatch, tmp_path, [])
        lines = [{"metric": "elle list-append cycle check (10k txns)",
                  "value": 5000.0, "unit": "txns/s"},
                 {"metric": "time-to-first-anomaly (x)", "value": 3.2,
                  "unit": "s"}]
        headline = {"metric": "m", "value": 70_000.0, "unit": "ops/s",
                    "runs_s": [1.0], "spread": 0.1,
                    "encode_s": 2.5, "check_s": 11.5}
        entry = bench._ledger_entry(lines, headline)
        assert entry["round"] == 1
        assert entry["kernels"]["elle-append"]["value"] == 5000.0
        assert entry["kernels"]["anomaly"]["higher_is_better"] is False
        assert entry["kernels"]["encode"]["value"] == 2.5
        assert entry["kernels"]["wgl-segmented"]["value"] == 11.5
        ledger.validate_entries([entry | {"ts": 1.0}])


class TestScalingAttribution:
    def test_parallel_efficiency(self):
        eff = profiler.parallel_efficiency(
            {1: 8.0, 2: 4.0, 4: 2.0, 8: 1.0})
        assert eff == {1: 1.0, 2: 1.0, 4: 1.0, 8: 1.0}
        flat = profiler.parallel_efficiency(
            {1: 3.77, 2: 3.43, 4: 3.29, 8: 3.43})
        assert flat[8] < 0.2  # the MULTICHIP_r05 failure signature
        assert profiler.parallel_efficiency({2: 1.0}) == {}

    def test_check_efficiency_warns_below_floor(self):
        msgs = []
        bad = profiler.check_efficiency(
            {1: 1.0, 2: 0.9, 4: 0.3, 8: 0.14}, log=msgs.append)
        assert [n for n, _e in bad] == [4, 8]
        assert len(msgs) == 2 and "4 devices" in msgs[0]
        assert profiler.check_efficiency({1: 1.0, 8: 0.9},
                                         log=msgs.append) == []

    def test_work_balance(self):
        # the sharded launches' load-balance figure (the contiguous
        # device_work helper died with the blocked shard layout —
        # ensemble.shard_layout attributes work per device now)
        assert profiler.work_balance([40, 40, 40, 40]) == 1.0
        assert profiler.work_balance([80, 40]) == 0.75
        assert profiler.work_balance([]) is None
        assert profiler.work_balance([0, 0]) is None


class TestTelemetryFilters:
    def _spans(self):
        return [
            {"id": 1, "parent": None, "name": "run", "t0": 0,
             "t1": 100_000_000},
            {"id": 2, "parent": 1, "name": "analyze", "t0": 0,
             "t1": 90_000_000},
            {"id": 3, "parent": 2, "name": "kernel:wgl", "t0": 0,
             "t1": 50_000_000},
            {"id": 4, "parent": 2, "name": "tiny", "t0": 0,
             "t1": 10_000},
            {"id": 5, "parent": None, "name": "open-span", "t0": 0},
        ]

    def test_min_ms_keeps_ancestors_and_open_spans(self):
        kept = rtel.filter_spans(self._spans(), min_ms=1.0)
        names = {e["name"] for e in kept}
        assert names == {"run", "analyze", "kernel:wgl", "open-span"}

    def test_top_keeps_n_longest_plus_ancestors(self):
        kept = rtel.filter_spans(self._spans(), top=1)
        names = {e["name"] for e in kept}
        # longest closed span is "run"; the open span always survives
        assert names == {"run", "open-span"}

    def test_no_filter_is_identity(self):
        spans = self._spans()
        assert rtel.filter_spans(spans) == spans

    def test_telemetry_text_reports_filtering(self):
        out = rtel.telemetry_text(self._spans(), None, min_ms=1.0)
        assert "filtered: showing" in out
        assert "tiny" not in out

    def test_cli_flags(self, tmp_path, capsys):
        from jepsen_tpu import cli

        util.init_relative_time()
        telemetry.reset()
        with telemetry.span("phase"):
            pass
        telemetry.save(tmp_path)
        cmd = cli.telemetry_cmd()["telemetry"]
        p = argparse.ArgumentParser()
        cmd["parser_fn"](p)
        rc = cmd["run"](p.parse_args(
            [str(tmp_path), "--min-ms", "0.0001", "--top", "5"]))
        assert rc == 0
        assert "filtered" in capsys.readouterr().out
