"""Store tests: CRC'd incremental history log with crash recovery,
three-phase saves, load/browse/delete (mirrors
jepsen/test/jepsen/store_test.clj and store/format_test.clj)."""

import json

import pytest

from jepsen_tpu import checker, core, store, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.history import op
from jepsen_tpu.store import format as fmt


def test_history_log_roundtrip(tmp_path):
    p = tmp_path / "history.jlog"
    w = fmt.HistoryWriter(p)
    ops = [op(index=i, time=i * 10, type="invoke", process=i % 3,
              f="write", value={"k": [i, "x"]}) for i in range(50)]
    for o in ops:
        w.append(o)
    back = w.read_back()
    assert len(back) == 50
    assert back[7].value == {"k": [7, "x"]}
    assert back[7].process == 1


def test_history_log_recovers_torn_tail(tmp_path):
    p = tmp_path / "history.jlog"
    w = fmt.HistoryWriter(p)
    for i in range(10):
        w.append(op(index=i, type="ok", process=0, f="read", value=i))
    w.close()
    size = p.stat().st_size
    with open(p, "r+b") as f:  # tear the last record mid-payload
        f.truncate(size - 5)
    back = list(fmt.read_ops(p))
    assert len(back) == 9  # torn tail dropped, rest recovered


def test_history_log_recovers_corrupt_crc(tmp_path):
    p = tmp_path / "history.jlog"
    w = fmt.HistoryWriter(p)
    for i in range(5):
        w.append(op(index=i, type="ok", process=0, f="read", value=i))
    w.close()
    with open(p, "r+b") as f:
        f.seek(-2, 2)
        f.write(b"XX")
    assert len(list(fmt.read_ops(p))) == 4


def test_full_run_persists_and_loads(tmp_path):
    state = testing.AtomState()
    test = testing.noop_test()
    test.update(
        name="store-e2e", store_base=str(tmp_path),
        nodes=["n1"], concurrency=3,
        db=testing.AtomDB(state), client=testing.AtomClient(state),
        checker=checker.stats(),
        generator=gen.clients(gen.limit(30, lambda: {"f": "read"})))
    test = core.run(test)
    assert test["results"]["valid?"] is True

    d = store.path(test)
    assert (d / "test.json").exists()
    assert (d / "results.json").exists()
    assert (d / "history.jlog").exists()
    assert (d / "jepsen.log").exists()

    loaded = store.load(d)
    assert len(loaded["history"]) == 60
    assert loaded["results"]["valid?"] is True
    assert loaded["name"] == "store-e2e"
    # symlinks
    latest = tmp_path / "store-e2e" / "latest"
    assert latest.resolve() == d.resolve()
    assert not (tmp_path / "current").exists()  # cleared after save-2

    ts = list(store.tests(base=tmp_path))
    assert len(ts) == 1
    assert store.delete(base=tmp_path) == 1
    assert list(store.tests(base=tmp_path)) == []


def test_jsonable_degrades_gracefully():
    class Weird:
        def __repr__(self):
            return "<weird>"

    v = fmt.jsonable({"a": {1, 2}, "b": Weird(), "c": [op(type="ok")]})
    json.dumps(v)  # must be serializable
    assert v["b"] == "<weird>"


def test_crashed_lifecycle_releases_log_handler(tmp_path):
    import logging

    from jepsen_tpu import db as jdb

    class BoomDB(jdb.DB):
        def setup(self, test, node):
            raise RuntimeError("boom")

    before = len(logging.getLogger().handlers)
    test = testing.noop_test()
    test.update(name="crash", store_base=str(tmp_path), nodes=["n1"],
                concurrency=1, db=BoomDB(),
                generator=gen.clients(gen.limit(1, lambda: {"f": "read"})))
    try:
        core.run(test)
    except Exception:
        pass
    assert len(logging.getLogger().handlers) == before


def test_history_log_reopen_truncates_torn_tail(tmp_path):
    """Reopening a crashed log must cut back to the last intact record,
    or new appends land after the torn tail and vanish on read
    (round-2 advisor finding)."""
    p = tmp_path / "history.jlog"
    w = fmt.HistoryWriter(p)
    for i in range(10):
        w.append(op(index=i, type="ok", process=0, f="read", value=i))
    w.close()
    with open(p, "r+b") as f:  # crash mid-record
        f.truncate(p.stat().st_size - 5)
    w2 = fmt.HistoryWriter(p)
    w2.append(op(index=100, type="ok", process=1, f="read", value=100))
    back = w2.read_back()
    assert len(back) == 10  # 9 intact + 1 new; none silently lost
    assert back[-1].value == 100


def test_history_log_reopen_bad_magic_restarts(tmp_path):
    p = tmp_path / "history.jlog"
    p.write_bytes(b"garbage")
    w = fmt.HistoryWriter(p)
    w.append(op(index=0, type="ok", process=0, f="read", value=1))
    assert [o.value for o in w.read_back()] == [1]


class TestChunkedLazyHistory:
    def test_lazy_matches_eager(self, tmp_path):
        p = tmp_path / "history.jlog"
        w = fmt.HistoryWriter(p, chunk_size=16)
        for i in range(100):
            w.append(op(index=i, time=i * 10, type="ok", process=i % 3,
                        f="read", value=i))
        w.close()
        lazy = fmt.read_history_lazy(p)
        eager = list(fmt.read_ops(p))
        assert len(lazy) == len(eager) == 100
        assert lazy[0].value == 0 and lazy[99].value == 99
        assert lazy[-1].value == 99
        assert [o.value for o in lazy] == [o.value for o in eager]
        # index sealed 6 chunks of 16
        assert len(fmt._read_index(p)) == 6

    def test_lazy_reads_only_touched_chunks(self, tmp_path):
        p = tmp_path / "history.jlog"
        w = fmt.HistoryWriter(p, chunk_size=32)
        for i in range(200):
            w.append(op(index=i, type="ok", process=0, f="read", value=i))
        w.close()
        lazy = fmt.read_history_lazy(p)
        lazy[5]
        assert len(lazy._cache) == 1  # only one chunk decoded

    def test_lazy_survives_torn_tail(self, tmp_path):
        p = tmp_path / "history.jlog"
        w = fmt.HistoryWriter(p, chunk_size=8)
        for i in range(30):
            w.append(op(index=i, type="ok", process=0, f="read", value=i))
        w.close()
        with open(p, "r+b") as f:
            f.truncate(p.stat().st_size - 5)
        lazy = fmt.read_history_lazy(p)
        assert len(lazy) == 29
        assert lazy[28].value == 28

    def test_writer_reopen_rebuilds_index(self, tmp_path):
        p = tmp_path / "history.jlog"
        w = fmt.HistoryWriter(p, chunk_size=8)
        for i in range(20):
            w.append(op(index=i, type="ok", process=0, f="read", value=i))
        w.close()
        w2 = fmt.HistoryWriter(p, chunk_size=8)
        for i in range(20, 30):
            w2.append(op(index=i, type="ok", process=0, f="read",
                         value=i))
        w2.close()
        lazy = fmt.read_history_lazy(p)
        assert len(lazy) == 30
        assert [o.value for o in lazy] == list(range(30))


class TestPartialResults:
    def test_roundtrip_and_crash_tolerance(self, tmp_path):
        p = tmp_path / "results.partial.jlog"
        w = fmt.PartialResultsWriter(p)
        w.put("stats", {"valid?": True, "ok-count": 5})
        w.put("lin", {"valid?": False})
        w.close()
        got = fmt.read_partial_results(p)
        assert got["stats"]["ok-count"] == 5
        assert got["lin"]["valid?"] is False
        with open(p, "r+b") as f:  # torn tail drops only the tail
            f.truncate(p.stat().st_size - 3)
        got = fmt.read_partial_results(p)
        assert "stats" in got

    def test_compose_streams_partials(self, tmp_path):
        from jepsen_tpu import checker as chk
        from jepsen_tpu.history import History

        class Boom(chk.Checker):
            def check(self, test, hist, opts=None):
                raise RuntimeError("checker crashed")

        p = tmp_path / "results.partial.jlog"
        w = fmt.PartialResultsWriter(p)
        hist = History([op(type="invoke", process=0, f="read", value=None),
                        op(type="ok", process=0, f="read", value=1)])
        c = chk.compose({"stats": chk.stats(), "boom": Boom()})
        res = c.check({}, hist, {"partial_results": w})
        w.close()
        got = fmt.read_partial_results(p)
        assert got["stats"]["valid?"] is True
        assert got["boom"]["valid?"] == "unknown"
        assert res["valid?"] == "unknown"

    def test_load_results_falls_back_to_partials(self, tmp_path):
        w = fmt.PartialResultsWriter(tmp_path / "results.partial.jlog")
        w.put("stats", {"valid?": True})
        w.close()
        got = store.load_results(tmp_path)
        assert got["partial?"] is True
        assert got["valid?"] == "unknown"
        assert got["stats"]["valid?"] is True


class TestNativeCodec:
    def test_native_scan_agrees_with_python(self, tmp_path):
        from jepsen_tpu import native

        if native.jlog() is None:
            import pytest
            pytest.skip("no C toolchain")
        p = tmp_path / "history.jlog"
        w = fmt.HistoryWriter(p)
        for i in range(50):
            w.append(op(index=i, type="ok", process=0, f="read",
                        value={"deep": [i, "x"]}))
        w.close()
        buf = p.read_bytes()
        offs, end = native.scan(buf, len(fmt.MAGIC))
        assert len(offs) == 50
        assert end == len(buf)
        # torn tail: native stops exactly where python does
        with open(p, "r+b") as f:
            f.truncate(p.stat().st_size - 2)
        buf = p.read_bytes()
        offs, end = native.scan(buf, len(fmt.MAGIC))
        assert len(offs) == 49
        assert end == fmt._valid_prefix_end(p)

    def test_native_frame_matches_python(self):
        from jepsen_tpu import native

        if native.jlog() is None:
            import pytest
            pytest.skip("no C toolchain")
        import json as j
        import struct
        import zlib

        payloads = [j.dumps({"i": i}).encode() for i in range(20)]
        H = struct.Struct("<II")
        exp = b"".join(H.pack(len(x), zlib.crc32(x)) + x
                       for x in payloads)
        assert native.frame(payloads) == exp


class TestStoreReviewRegressions:
    def test_lazy_bad_magic_raises_cleanly(self, tmp_path):
        p = tmp_path / "history.jlog"
        p.write_bytes(b"")
        with pytest.raises((ValueError, OSError)):
            fmt.read_history_lazy(p)
        p.write_bytes(b"garbage!")
        with pytest.raises(ValueError):
            fmt.read_history_lazy(p)

    def test_bulk_write_history_roundtrip(self, tmp_path):
        p = tmp_path / "history.jlog"
        ops = [op(index=i, time=i, type="ok", process=0, f="read",
                  value=i) for i in range(1000)]
        fmt.write_history(p, ops, chunk_size=128)
        lazy = fmt.read_history_lazy(p)
        assert len(lazy) == 1000
        assert [o.value for o in lazy] == list(range(1000))
        assert len(fmt._read_index(p)) == 1000 // 128

    def test_nested_compose_does_not_pollute_partials(self, tmp_path):
        from jepsen_tpu import checker as chk
        from jepsen_tpu.history import History

        w = fmt.PartialResultsWriter(tmp_path / "r.jlog")
        inner = chk.compose({"stats": chk.stats(),
                             "bank-ish": chk.unbridled_optimism()})
        outer = chk.compose({"workload": inner, "stats": chk.stats()})
        hist = History([op(type="invoke", process=0, f="read", value=None),
                        op(type="ok", process=0, f="read", value=1)])
        outer.check({}, hist, {"partial_results": w})
        w.close()
        got = fmt.read_partial_results(tmp_path / "r.jlog")
        assert set(got) == {"workload", "stats"}  # no inner flattening
        assert got["workload"]["bank-ish"]["valid?"] is True


class TestCrashRecovery:
    """ISSUE-5 satellite: the exact crash-window behaviors the resume
    path depends on."""

    def _write(self, p, n=10):
        w = fmt.HistoryWriter(p)
        for i in range(n):
            w.append(op(index=i, type="ok", process=0, f="read",
                        value=i))
        w.close()
        return p

    def test_valid_prefix_end_drops_torn_final_record(self, tmp_path):
        p = self._write(tmp_path / "history.jlog")
        full = p.stat().st_size
        assert fmt._valid_prefix_end(p) == full
        with open(p, "r+b") as f:  # crash mid-append of record 10
            f.truncate(full - 3)
        end = fmt._valid_prefix_end(p)
        assert end < full - 3
        # the prefix end is exactly the 9-record boundary: re-reading
        # from it yields nothing (no half record counted)
        with open(p, "r+b") as f:
            f.truncate(end)
        assert len(list(fmt.read_ops(p))) == 9
        assert fmt._valid_prefix_end(p) == end

    def test_lazy_history_truncated_log_yields_sealed_prefix(
            self, tmp_path):
        p = tmp_path / "history.jlog"
        w = fmt.HistoryWriter(p, chunk_size=8)
        for i in range(40):
            w.append(op(index=i, type="ok", process=0, f="read",
                        value=i))
        w.close()
        # crash tears the tail back into the 4th chunk
        with open(p, "r+b") as f:
            f.truncate(fmt._read_index(p)[3][1] + 7)
        lazy = fmt.read_history_lazy(p)
        assert len(lazy) == 32  # 4 sealed chunks survive
        assert [o.value for o in lazy] == list(range(32))

    def test_read_history_roundtrips_after_mid_append_crash(
            self, tmp_path):
        """A writer that dies mid-append leaves a partial frame; the
        recovered history is the full pre-crash prefix, and a reopened
        writer continues from exactly there."""
        import struct

        p = self._write(tmp_path / "history.jlog", n=12)
        with open(p, "ab") as f:  # half-written frame: header only
            f.write(struct.pack("<II", 999, 12345))
            f.write(b"{\"par")
        hist = fmt.read_history(p)
        assert len(hist) == 12
        assert [o.value for o in hist] == list(range(12))
        w2 = fmt.HistoryWriter(p)
        w2.append(op(index=12, type="ok", process=0, f="read",
                     value=12))
        assert [o.value for o in w2.read_back()] == list(range(13))

    def test_spec_roundtrip(self, tmp_path):
        test = {"name": "spec-rt", "store_base": str(tmp_path),
                "store_dir": str(tmp_path / "r"),
                "spec": {"workload": "register",
                         "opts": {"nodes": ["n1"], "ops": 10}}}
        (tmp_path / "r").mkdir()
        store.save_spec(test)
        got = store.load_spec(tmp_path / "r")
        assert got == test["spec"]
        assert store.load_spec(tmp_path) is None  # absent = None


class TestRepl:
    """jepsen_tpu.repl helpers (mirror jepsen/src/jepsen/repl.clj)."""

    def _run_one(self, tmp_path, monkeypatch, name="repl-test"):
        import jepsen_tpu.store as store_mod
        from jepsen_tpu import checker as chk
        from jepsen_tpu import core, generator as gen, testing

        monkeypatch.setattr(store_mod, "BASE", tmp_path / "store")
        state = testing.AtomState()
        t = testing.noop_test()
        t.update(name=name, nodes=["n1"], concurrency=2,
                 db=testing.AtomDB(state),
                 client=testing.AtomClient(state),
                 checker=chk.compose({"stats": chk.stats()}),
                 generator=gen.clients(gen.limit(
                     20, lambda: {"f": "read"})))
        return core.run(t)

    def test_latest_test_roundtrip(self, tmp_path, monkeypatch):
        from jepsen_tpu import repl

        self._run_one(tmp_path, monkeypatch, "repl-a")
        self._run_one(tmp_path, monkeypatch, "repl-b")
        t = repl.latest_test()
        assert t is not None and len(t["history"]) == 40
        assert t["results"]["valid?"] is True
        # by-name selection
        ta = repl.latest_test("repl-a")
        assert ta["name"] == "repl-a"

    def test_latest_test_empty_store(self, tmp_path, monkeypatch):
        import jepsen_tpu.store as store_mod
        from jepsen_tpu import repl

        monkeypatch.setattr(store_mod, "BASE", tmp_path / "nothing")
        assert repl.latest_test() is None

    def test_summary(self, tmp_path, monkeypatch):
        from jepsen_tpu import repl

        self._run_one(tmp_path, monkeypatch)
        s = repl.summary(repl.latest_test())
        assert s["valid?"] is True and s["ops"] == 40
        assert s["by-type"] == {"invoke": 20, "ok": 20}
        assert "stats" in s["checkers"]
        assert repl.summary(None) == {}
