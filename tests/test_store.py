"""Store tests: CRC'd incremental history log with crash recovery,
three-phase saves, load/browse/delete (mirrors
jepsen/test/jepsen/store_test.clj and store/format_test.clj)."""

import json

from jepsen_tpu import checker, core, store, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.history import op
from jepsen_tpu.store import format as fmt


def test_history_log_roundtrip(tmp_path):
    p = tmp_path / "history.jlog"
    w = fmt.HistoryWriter(p)
    ops = [op(index=i, time=i * 10, type="invoke", process=i % 3,
              f="write", value={"k": [i, "x"]}) for i in range(50)]
    for o in ops:
        w.append(o)
    back = w.read_back()
    assert len(back) == 50
    assert back[7].value == {"k": [7, "x"]}
    assert back[7].process == 1


def test_history_log_recovers_torn_tail(tmp_path):
    p = tmp_path / "history.jlog"
    w = fmt.HistoryWriter(p)
    for i in range(10):
        w.append(op(index=i, type="ok", process=0, f="read", value=i))
    w.close()
    size = p.stat().st_size
    with open(p, "r+b") as f:  # tear the last record mid-payload
        f.truncate(size - 5)
    back = list(fmt.read_ops(p))
    assert len(back) == 9  # torn tail dropped, rest recovered


def test_history_log_recovers_corrupt_crc(tmp_path):
    p = tmp_path / "history.jlog"
    w = fmt.HistoryWriter(p)
    for i in range(5):
        w.append(op(index=i, type="ok", process=0, f="read", value=i))
    w.close()
    with open(p, "r+b") as f:
        f.seek(-2, 2)
        f.write(b"XX")
    assert len(list(fmt.read_ops(p))) == 4


def test_full_run_persists_and_loads(tmp_path):
    state = testing.AtomState()
    test = testing.noop_test()
    test.update(
        name="store-e2e", store_base=str(tmp_path),
        nodes=["n1"], concurrency=3,
        db=testing.AtomDB(state), client=testing.AtomClient(state),
        checker=checker.stats(),
        generator=gen.clients(gen.limit(30, lambda: {"f": "read"})))
    test = core.run(test)
    assert test["results"]["valid?"] is True

    d = store.path(test)
    assert (d / "test.json").exists()
    assert (d / "results.json").exists()
    assert (d / "history.jlog").exists()
    assert (d / "jepsen.log").exists()

    loaded = store.load(d)
    assert len(loaded["history"]) == 60
    assert loaded["results"]["valid?"] is True
    assert loaded["name"] == "store-e2e"
    # symlinks
    latest = tmp_path / "store-e2e" / "latest"
    assert latest.resolve() == d.resolve()
    assert not (tmp_path / "current").exists()  # cleared after save-2

    ts = list(store.tests(base=tmp_path))
    assert len(ts) == 1
    assert store.delete(base=tmp_path) == 1
    assert list(store.tests(base=tmp_path)) == []


def test_jsonable_degrades_gracefully():
    class Weird:
        def __repr__(self):
            return "<weird>"

    v = fmt.jsonable({"a": {1, 2}, "b": Weird(), "c": [op(type="ok")]})
    json.dumps(v)  # must be serializable
    assert v["b"] == "<weird>"


def test_crashed_lifecycle_releases_log_handler(tmp_path):
    import logging

    from jepsen_tpu import db as jdb

    class BoomDB(jdb.DB):
        def setup(self, test, node):
            raise RuntimeError("boom")

    before = len(logging.getLogger().handlers)
    test = testing.noop_test()
    test.update(name="crash", store_base=str(tmp_path), nodes=["n1"],
                concurrency=1, db=BoomDB(),
                generator=gen.clients(gen.limit(1, lambda: {"f": "read"})))
    try:
        core.run(test)
    except Exception:
        pass
    assert len(logging.getLogger().handlers) == before


def test_history_log_reopen_truncates_torn_tail(tmp_path):
    """Reopening a crashed log must cut back to the last intact record,
    or new appends land after the torn tail and vanish on read
    (round-2 advisor finding)."""
    p = tmp_path / "history.jlog"
    w = fmt.HistoryWriter(p)
    for i in range(10):
        w.append(op(index=i, type="ok", process=0, f="read", value=i))
    w.close()
    with open(p, "r+b") as f:  # crash mid-record
        f.truncate(p.stat().st_size - 5)
    w2 = fmt.HistoryWriter(p)
    w2.append(op(index=100, type="ok", process=1, f="read", value=100))
    back = w2.read_back()
    assert len(back) == 10  # 9 intact + 1 new; none silently lost
    assert back[-1].value == 100


def test_history_log_reopen_bad_magic_restarts(tmp_path):
    p = tmp_path / "history.jlog"
    p.write_bytes(b"garbage")
    w = fmt.HistoryWriter(p)
    w.append(op(index=0, type="ok", process=0, f="read", value=1))
    assert [o.value for o in w.read_back()] == [1]
