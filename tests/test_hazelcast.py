"""Hazelcast suite tests: DB orchestration via the dummy remote, a
scripted FakeHz speaking the client jar's line protocol, and
clusterless e2e lock/semaphore/cas/queue/id runs — healthy and with
seeded mutual-exclusion violations (mirrors
hazelcast/src/jepsen/hazelcast.clj's client + workload map)."""

import threading

import pytest

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import hazelcast as hz


def make_test(responder=None, nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return t


def cmds(test, node):
    return [a for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_member_config(self):
        cfg = hz.member_config({"nodes": ["n1", "n2", "n3"]})
        assert "- n1:5701" in cfg and "- n3:5701" in cfg
        assert "cp-member-count: 3" in cfg
        assert "multicast:\n        enabled: false" in cfg

    def test_start_uses_daemon_helpers(self):
        test = make_test()
        db = hz.HzDB()
        with control.with_session(test, "n1"):
            db.start(test, "n1")
        got = " ; ".join(a.cmd for a in cmds(test, "n1"))
        assert "bin/hz" in got and "start" in got
        assert hz.CONFIG in got

    def test_kill_greps_jvm(self):
        test = make_test()
        db = hz.HzDB()
        with control.with_session(test, "n1"):
            db.kill(test, "n1")
        got = " ; ".join(a.cmd for a in cmds(test, "n1"))
        assert "com.hazelcast" in got


class FakeHz:
    """The client jar's line protocol over in-memory CP structures.
    broken='steal' grants a busy lock anyway with a STALE fence;
    broken='overfill' hands out more semaphore permits than exist."""

    def __init__(self, broken=None, permits=2):
        self.lock = threading.Lock()
        self.broken = broken
        self.permits = permits
        self.locks = {}      # name -> (owner, fence, count)
        self.fences = {}     # name -> next fence
        self.sems = {}       # name -> {owner: count}
        self.longs = {}      # name -> int
        self.refs = {}       # name -> int | None (nil)
        self.ids = {}        # name -> int
        self.queues = {}     # name -> list
        self.attempts = 0

    def cmd(self, session, line: str) -> str:
        with self.lock:
            return self._dispatch(session, line.split())

    def _dispatch(self, who, parts):
        kind = parts[0]
        if kind == "lock":
            return self._lock(who, parts[1], parts[2])
        if kind == "sem":
            return self._sem(who, parts[1], parts[2])
        if kind == "long":
            return self._long(parts[1:])
        if kind == "ref":
            return self._ref(parts[1:])
        if kind == "id":
            n = self.ids.get(parts[2], 0)
            self.ids[parts[2]] = n + 1
            return f"OK {n}"
        if kind == "q":
            q = self.queues.setdefault(parts[2], [])
            if parts[1] == "offer":
                q.append(int(parts[3]))
                return "OK"
            if not q:
                return "EMPTY"
            return f"OK {q.pop(0)}"
        return f"ERR unknown {kind}"

    def _lock(self, who, f, name):
        owner, fence, count = self.locks.get(name, (None, 0, 0))
        if f == "acquire":
            self.attempts += 1
            if owner is None or owner == who:
                nf = fence if owner == who else \
                    self.fences.setdefault(name, 0) + 1
                self.fences[name] = nf
                self.locks[name] = (who, nf, count + 1)
                return f"OK {nf}"
            if self.broken == "steal" and self.attempts % 3 == 0:
                # grants with the PREVIOUS holder's fence: stale token
                self.locks[name] = (who, fence, 1)
                return f"OK {fence}"
            return "BUSY"
        if owner != who:
            return "ERR not-owner"
        if count <= 1:
            self.locks[name] = (None, fence, 0)
        else:
            self.locks[name] = (owner, fence, count - 1)
        return "OK"

    def _sem(self, who, f, name):
        held = self.sems.setdefault(name, {})
        total = sum(held.values())
        limit = self.permits + (1 if self.broken == "overfill" else 0)
        if f == "acquire":
            if total < limit:
                held[who] = held.get(who, 0) + 1
                return "OK"
            return "BUSY"
        if held.get(who, 0) > 0:
            held[who] -= 1
            return "OK"
        return "ERR not-permit-owner"

    def _ref(self, parts):
        # IAtomicReference: initial nil, CAS against nil works
        f, name = parts[0], parts[1]
        v = self.refs.get(name)
        if f == "read":
            return f"OK {'nil' if v is None else v}"
        if f == "write":
            self.refs[name] = int(parts[2])
            return "OK"
        a, b = int(parts[2]), int(parts[3])
        if v == a:
            self.refs[name] = b
            return "OK"
        return "FAIL"

    def _long(self, parts):
        f, name = parts[0], parts[1]
        v = self.longs.get(name, 0)
        if f == "read":
            return f"OK {v}"
        if f == "write":
            self.longs[name] = int(parts[2])
            return "OK"
        a, b = int(parts[2]), int(parts[3])
        if v == a:
            self.longs[name] = b
            return "OK"
        return "FAIL"


class FakeConsoleFactory:
    """console_factory plug for the suite's clients: sessions are the
    per-process names the clients pass (the jar's named-CP-session
    model), falling back to a per-console identity."""

    def __init__(self, state=None):
        self.state = state or FakeHz()
        self._n = 0

    def __call__(self, test, node, timeout=10.0):
        self._n += 1
        factory, default = self, f"{node}#{self._n}"

        class _Console:
            def cmd(self, line, session=None):
                return factory.state.cmd(session or default, line)

        return _Console()


def run_clusterless(workload: dict, nodes=3, concurrency=6) -> dict:
    t = testing.noop_test()
    t.update(
        nodes=[f"n{i}" for i in range(nodes)],
        concurrency=concurrency,
        client=workload["client"],
        checker=workload["checker"],
        generator=gen.clients(workload["generator"]))
    return core.run(t)


class TestWorkloadsEndToEnd:
    def _wl(self, name, state, **opts):
        w = hz.WORKLOADS[name](dict({"ops": 60, "stagger": 0}, **opts))
        fac = FakeConsoleFactory(state)
        w["client"].console_factory = fac
        return w

    def test_lock_healthy(self):
        t = run_clusterless(self._wl("lock", FakeHz()))
        assert t["results"]["valid?"] is True, t["results"]

    def test_fenced_lock_detects_steal(self):
        # per-process sessions make the steal a two-holder violation
        t = run_clusterless(
            self._wl("fenced-lock", FakeHz(broken="steal")))
        assert t["results"]["valid?"] is False

    def test_semaphore_healthy_and_overfilled(self):
        t = run_clusterless(self._wl("semaphore", FakeHz()))
        assert t["results"]["valid?"] is True, t["results"]
        t = run_clusterless(
            self._wl("semaphore", FakeHz(broken="overfill")))
        assert t["results"]["valid?"] is False

    def test_cas_long(self):
        t = run_clusterless(self._wl("cas-long", FakeHz(), ops=50))
        assert t["results"]["valid?"] is True, t["results"]

    def test_cas_reference_nil_initial(self):
        t = run_clusterless(self._wl("cas-reference", FakeHz(),
                                     ops=50))
        assert t["results"]["valid?"] is True, t["results"]
        # non-vacuous: values were really read back
        reads = [o.value for o in t["history"]
                 if o.type == "ok" and o.f == "read"]
        assert any(v is not None for v in reads)

    def test_cas_reference_protocol_nil(self):
        fac = FakeConsoleFactory(FakeHz())
        c = hz.CasRefClient(console_factory=fac).open(
            {"nodes": ["n1"]}, "n1")
        r = c.invoke({}, Op(type="invoke", process=0, f="read",
                            value=None))
        assert r.type == "ok" and r.value is None
        assert c.invoke({}, Op(type="invoke", process=0, f="cas",
                               value=[0, 3])).type == "fail"
        assert c.invoke({}, Op(type="invoke", process=0, f="write",
                               value=2)).type == "ok"
        assert c.invoke({}, Op(type="invoke", process=0, f="read",
                               value=None)).value == 2

    def test_id_gen_unique(self):
        t = run_clusterless(self._wl("id-gen", FakeHz(), ops=50))
        assert t["results"]["valid?"] is True, t["results"]

    def test_queue(self):
        t = run_clusterless(self._wl("queue", FakeHz(), ops=40))
        assert t["results"]["valid?"] is True, t["results"]

    def test_workload_registry_builds(self):
        for name, fn in hz.WORKLOADS.items():
            w = fn({"ops": 5})
            assert {"generator", "checker", "client"} <= set(w), name


class TestClientProtocol:
    def _client(self, cls, state=None, **kw):
        fac = FakeConsoleFactory(state)
        c = cls(console_factory=fac, **kw)
        return c.open({"nodes": ["n1"]}, "n1"), fac.state

    def test_lock_fence_monotonic_across_holders(self):
        c1, state = self._client(hz.LockClient)
        r1 = c1.invoke({}, Op(type="invoke", process=0, f="acquire",
                              value=None))
        assert r1.type == "ok" and r1.value["fence"] == 1
        assert c1.invoke({}, Op(type="invoke", process=0, f="release",
                                value=None)).type == "ok"
        r2 = c1.invoke({}, Op(type="invoke", process=0, f="acquire",
                              value=None))
        assert r2.value["fence"] == 2

    def test_busy_lock_fails(self):
        state = FakeHz()
        fac = FakeConsoleFactory(state)
        c1 = hz.LockClient(console_factory=fac).open(
            {"nodes": ["n1"]}, "n1")
        c2 = hz.LockClient(console_factory=fac).open(
            {"nodes": ["n1"]}, "n1")
        assert c1.invoke({}, Op(type="invoke", process=0, f="acquire",
                                value=None)).type == "ok"
        assert c2.invoke({}, Op(type="invoke", process=1, f="acquire",
                                value=None)).type == "fail"

    def test_cas_long_semantics(self):
        c, _ = self._client(hz.CasLongClient)
        assert c.invoke({}, Op(type="invoke", process=0, f="write",
                               value=3)).type == "ok"
        r = c.invoke({}, Op(type="invoke", process=0, f="read",
                            value=None))
        assert r.value == 3
        assert c.invoke({}, Op(type="invoke", process=0, f="cas",
                               value=[3, 4])).type == "ok"
        assert c.invoke({}, Op(type="invoke", process=0, f="cas",
                               value=[3, 4])).type == "fail"
