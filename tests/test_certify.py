"""Verdict certificates + the search explorer (ISSUE 10).

The rejection matrix pins the validator's whole point: a tampered
linearization order, a forged cycle edge, and a stale certificate
replayed against an edited history must all fail loudly, while
device- and host-derived certificates for the same seeded histories
must both validate and agree. The explorer half pins the kernel's
search-dynamics outputs (per-level frontier occupancy, states,
dedup hits, witness position) through the profiler, the profile CLI
columns, the Perfetto counter track, and the web panel."""

import copy
import json

import pytest

from jepsen_tpu import checker, core, store, telemetry, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.checker import models
from jepsen_tpu.history import History, op
from jepsen_tpu.tpu import certify, elle, synth, wgl
from jepsen_tpu.tpu.encode import encode


def _register_hist(n=200, seed=3, crash_p=0.1):
    return synth.register_history(n, n_procs=4, seed=seed,
                                  crash_p=crash_p)


def _invalid_hist(n=1500, seed=5, at=0.6):
    h, _bad = synth.corrupt_register_history(
        synth.register_history(n, n_procs=4, seed=seed), at_frac=at)
    return h


def _cyclic_append_hist():
    """A two-txn ww cycle (G0) witnessed by a third txn's reads."""
    ops = []

    def txn(p, mops, ok_mops=None):
        ops.append(op(index=len(ops), time=len(ops), type="invoke",
                      process=p, f="txn", value=mops))
        ops.append(op(index=len(ops), time=len(ops), type="ok",
                      process=p, f="txn", value=ok_mops or mops))

    txn(0, [["append", "x", 1], ["append", "y", 2]])
    txn(1, [["append", "x", 2], ["append", "y", 1]])
    txn(2, [["r", "x", None], ["r", "y", None]],
        [["r", "x", [1, 2]], ["r", "y", [1, 2]]])
    return History(ops)


class TestSchema:
    def test_absent_is_schema_valid(self):
        certify.validate_schema(certify.absent("host floor"))

    def test_absent_requires_reason(self):
        with pytest.raises(certify.CertificateError):
            certify.validate_schema({"v": 1, "absent": ""})

    def test_unknown_version_rejected(self):
        with pytest.raises(certify.CertificateError):
            certify.validate_schema({"v": 99, "kind": "wgl"})

    def test_full_cert_schema(self):
        h = _register_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        certify.validate_schema(out["certificate"])

    def test_absent_validate_raises(self):
        with pytest.raises(certify.CertificateError):
            certify.validate(History([]), certify.absent("nope"))


class TestWglValid:
    def test_valid_certificate_validates(self):
        h = _register_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        assert out["valid?"] is True
        assert "absent" not in out["certificate"]
        certify.validate(h, out["certificate"])

    def test_segmented_certificate_composes(self):
        h = synth.register_history(6000, n_procs=4, seed=7)
        out = wgl.analysis(models.cas_register(), h, certify=True)
        assert out["analyzer"] == "tpu-segmented"
        cert = out["certificate"]
        assert len(cert["segments"]) > 1  # really per-segment
        certify.validate(h, cert)

    def test_tampered_order_rejected(self):
        h = _register_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        cert = copy.deepcopy(out["certificate"])
        order = cert["segments"][0]["order"]
        order[0], order[-1] = order[-1], order[0]
        with pytest.raises(certify.CertificateError):
            certify.validate(h, cert)

    def test_dropped_op_rejected(self):
        """A 'proof' that simply omits a completed op is not a
        whole-history proof."""
        h = _register_hist(crash_p=0.0)
        out = wgl.analysis(models.cas_register(), h, certify=True)
        cert = copy.deepcopy(out["certificate"])
        cert["segments"][0]["order"].pop()
        with pytest.raises(certify.CertificateError,
                           match="omits"):
            certify.validate(h, cert)

    def test_discarding_completed_op_rejected(self):
        h = _register_hist(crash_p=0.0)
        out = wgl.analysis(models.cas_register(), h, certify=True)
        cert = copy.deepcopy(out["certificate"])
        cert["segments"][0]["order"][0][1] = "discard"
        with pytest.raises(certify.CertificateError):
            certify.validate(h, cert)

    def test_stale_certificate_rejected(self):
        h = _register_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        edited = History(list(h)[:-2], assign_indices=False)
        with pytest.raises(certify.CertificateError, match="stale"):
            certify.validate(edited, out["certificate"])


class TestWglInvalid:
    def test_witness_certificate_validates(self):
        h = _invalid_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        assert out["valid?"] is False
        cert = out["certificate"]
        assert "absent" not in cert, cert
        certify.validate(h, cert)

    def test_segmented_witness_validates(self):
        h = _invalid_hist(n=6000, seed=11, at=0.5)
        out = wgl.analysis(models.cas_register(), h, certify=True)
        assert out["valid?"] is False
        cert = out["certificate"]
        assert cert["segments"], "pre-witness segments certified too"
        certify.validate(h, cert)

    def test_tampered_witness_state_rejected(self):
        h = _invalid_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        cert = copy.deepcopy(out["certificate"])
        cert["witness"]["state"] = 999_999
        with pytest.raises(certify.CertificateError):
            certify.validate(h, cert)

    def test_unstuck_witness_rejected(self):
        """Claiming an op is stuck when it actually applies must
        fail — the validator re-steps the model itself."""
        h = _register_hist(crash_p=0.0)  # valid history
        out = wgl.analysis(models.cas_register(), h, certify=True)
        good = out["certificate"]
        # forge an 'invalid' certificate out of the valid proof: the
        # prefix replays fine, but the claimed stuck op applies
        order = good["segments"][0]["order"]
        forged = {
            "v": 1, "kind": "wgl", "verdict": "invalid",
            "model": good["model"], "history": good["history"],
            "segments": [],
            "witness": {"op-index": order[-1][0],
                        "prefix": order[:-1],
                        "pending": [order[-1][0]]},
        }
        with pytest.raises(certify.CertificateError):
            certify.validate(h, forged)

    def test_witness_position_attached(self):
        h = _invalid_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        s = out["search"]
        assert 0.0 <= s["witness-position"] <= 1.0
        assert s["witness-entry"] < s["entries"]


class TestDeviceHostEquivalence:
    @pytest.mark.parametrize("seed", [21, 22, 23])
    def test_certificates_agree_on_seeded_histories(self, seed):
        """The device kernel's verdict+proof and the host search's
        must agree and both validate — the parity invariant the
        certificate layer turns into a per-run check."""
        h = synth.register_history(300, n_procs=4, seed=seed,
                                   crash_p=0.15)
        m = models.cas_register()
        dev = wgl.analysis(m, h, algorithm="tpu", certify=True)
        host = wgl.analysis(m, h, algorithm="wgl", certify=True)
        assert dev["valid?"] == host["valid?"]
        for out in (dev, host):
            assert "absent" not in out["certificate"]
            certify.validate(h, out["certificate"])

    def test_invalid_agrees_too(self):
        h = _invalid_hist(n=900, seed=31, at=0.4)
        m = models.cas_register()
        dev = wgl.analysis(m, h, algorithm="tpu", certify=True)
        host = wgl.analysis(m, h, algorithm="wgl", certify=True)
        assert dev["valid?"] is False and host["valid?"] is False
        certify.validate(h, dev["certificate"])
        certify.validate(h, host["certificate"])


class TestElleCertificates:
    def test_valid_append_certificate(self):
        h = synth.list_append_history(300, seed=4)
        res = elle.check_list_append(h, {"certify": True})
        assert res["valid?"] is True
        certify.validate(h, res["certificate"])

    def test_valid_rw_certificate(self):
        h = synth.rw_register_history(300, seed=9)
        res = elle.check_rw_register(h, {"certify": True})
        assert res["valid?"] is True
        certify.validate(h, res["certificate"])

    def test_cycle_certificate_validates(self):
        h = _cyclic_append_hist()
        res = elle.check_list_append(h, {"certify": True})
        assert res["valid?"] is False
        cert = res["certificate"]
        assert cert["cycle"], cert
        certify.validate(h, cert)

    def test_forged_cycle_edge_rejected(self):
        h = _cyclic_append_hist()
        res = elle.check_list_append(h, {"certify": True})
        cert = copy.deepcopy(res["certificate"])
        cert["cycle"][0]["value"] = 777
        with pytest.raises(certify.CertificateError, match="forged"):
            certify.validate(h, cert)

    def test_broken_cycle_chain_rejected(self):
        h = _cyclic_append_hist()
        res = elle.check_list_append(h, {"certify": True})
        cert = copy.deepcopy(res["certificate"])
        cert["cycle"][0]["to"] = cert["cycle"][0]["from"]
        with pytest.raises(certify.CertificateError):
            certify.validate(h, cert)

    def test_tampered_topo_order_rejected(self):
        ops = []
        ops.append(op(index=0, time=0, type="invoke", process=0,
                      f="txn", value=[["append", "x", 1]]))
        ops.append(op(index=1, time=1, type="ok", process=0,
                      f="txn", value=[["append", "x", 1]]))
        ops.append(op(index=2, time=2, type="invoke", process=1,
                      f="txn", value=[["r", "x", None]]))
        ops.append(op(index=3, time=3, type="ok", process=1,
                      f="txn", value=[["r", "x", [1]]]))
        h = History(ops)
        res = elle.check_list_append(h, {"certify": True})
        assert res["valid?"] is True
        cert = copy.deepcopy(res["certificate"])
        cert["topo-order"] = list(reversed(cert["topo-order"]))
        with pytest.raises(certify.CertificateError):
            certify.validate(h, cert)

    def test_g1a_certificate(self):
        ops = []
        ops.append(op(index=0, time=0, type="invoke", process=0,
                      f="txn", value=[["append", "x", 1]]))
        ops.append(op(index=1, time=1, type="fail", process=0,
                      f="txn", value=[["append", "x", 1]]))
        ops.append(op(index=2, time=2, type="invoke", process=1,
                      f="txn", value=[["r", "x", None]]))
        ops.append(op(index=3, time=3, type="ok", process=1,
                      f="txn", value=[["r", "x", [1]]]))
        h = History(ops)
        res = elle.check_list_append(h, {"certify": True})
        assert res["valid?"] is False
        cert = res["certificate"]
        if "absent" not in cert:
            assert cert.get("anomaly", {}).get("class") == "G1a"
            certify.validate(h, cert)
            bad = copy.deepcopy(cert)
            bad["anomaly"]["value"] = 42
            with pytest.raises(certify.CertificateError):
                certify.validate(h, bad)

    def test_search_stats_attached(self):
        h = synth.list_append_history(300, seed=4)
        res = elle.check_list_append(h)
        s = res["search"]
        assert s["edges"] == res["edge-count"]
        assert s["per-key-edges"]
        assert s["keys"] >= len(s["per-key-edges"])


class TestStampResults:
    def test_stamp_marks_certified(self):
        h = _register_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        results = {"workload": out, "valid?": out["valid?"]}
        counts = certify.stamp_results(results, h)
        assert counts == {"certified": 1, "errors": 0, "absent": 0}
        assert results["workload"]["certified"] is True

    def test_stamp_marks_error_on_tamper(self):
        h = _register_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        order = out["certificate"]["segments"][0]["order"]
        order[0], order[-1] = order[-1], order[0]
        results = {"workload": out}
        counts = certify.stamp_results(results, h)
        assert counts["errors"] == 1
        assert "certificate-error" in results["workload"]

    def test_stamp_counts_absent(self):
        results = {"w": {"valid?": True,
                         "certificate": certify.absent("host floor")}}
        counts = certify.stamp_results(results, History([]))
        assert counts == {"certified": 0, "errors": 0, "absent": 1}
        assert "certified" not in results["w"]

    def test_disabled_extraction_is_honestly_absent(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_CERTIFY", "0")
        h = _register_hist()
        out = wgl.analysis(models.cas_register(), h, certify=True)
        assert "absent" in out["certificate"]


class TestIndependentKeys:
    def test_per_key_certificates_validate_against_full_history(self):
        from jepsen_tpu import independent

        ops = []
        t = [0]

        def add(p, f, v, typ="invoke"):
            ops.append(op(index=len(ops), time=t[0], type=typ,
                          process=p, f=f, value=v))
            t[0] += 1

        for k in ("a", "b"):
            add(0, "write", (k, 1))
            add(0, "write", (k, 1), "ok")
            add(1, "read", (k, None))
            add(1, "read", (k, 1), "ok")
        h = History(ops, assign_indices=False)
        inner = checker.linearizable({"model": models.register()})
        res = independent.checker(inner).check({}, h, {})
        assert res["valid?"] is True
        for k, r in res["results"].items():
            cert = r["certificate"]
            assert cert["key"] == k
            certify.validate(h, cert)
        counts = certify.stamp_results(res, h)
        assert counts["certified"] == 2 and counts["errors"] == 0


class TestSearchExplorer:
    def test_kernel_reports_search_shape(self):
        from jepsen_tpu.tpu import profiler

        telemetry.reset()
        profiler.reset()
        m = models.register(0)
        encs = [encode(m, _register_hist(80, seed=s))
                for s in range(41, 45)]
        res = wgl.check_batch(encs)
        assert set(res) <= {wgl.VALID, wgl.INVALID, wgl.UNKNOWN}
        c = telemetry.get().counters()
        assert c["wgl.search.levels"] >= 1
        assert c["wgl.search.states"] >= 1
        g = telemetry.get().gauges()
        assert g["wgl.search.frontier-peak"] >= 1
        recs = [r for r in profiler.get().records()
                if r["kernel"] == "wgl"]
        assert recs
        r = recs[-1]
        assert r["frontier_peak"] >= 1
        assert r["states_explored"] >= 1
        assert isinstance(r["frontier_curve"], list)
        assert len(r["frontier_curve"]) <= 32

    def test_profile_table_has_explorer_columns(self):
        from jepsen_tpu.reports import profile as rprofile

        metrics = {"counters": {
            "profiler.wgl.launches": 3,
            "profiler.wgl.states": 1200,
            "profiler.wgl.dedup_hits": 30,
        }, "gauges": {"profiler.wgl.frontier_peak": 64}}
        text = rprofile.profile_text([], metrics)
        assert "frontier" in text and "dedup" in text
        rows = {r["kernel"]: r for r in rprofile.kernel_rows(metrics)}
        assert rows["wgl"]["frontier"] == "64"
        assert rows["wgl"]["states"] == "1.2k"

    def test_trace_gains_frontier_counter_track(self):
        from jepsen_tpu.reports import trace as rtrace

        spans = [{"name": "kernel:wgl", "t0": 1000, "t1": 9000,
                  "thread": "t", "attrs": {
                      "frontier_curve": [1, 4, 9, 4, 1],
                      "frontier_peak": 9}}]
        doc = rtrace.chrome_trace({}, [], spans)
        counters = [e for e in doc["traceEvents"]
                    if e.get("ph") == "C"
                    and e["name"] == "wgl frontier"]
        assert len(counters) == 5
        assert counters[2]["args"]["frontier"] == 9.0
        rtrace.validate_chrome_trace(doc)

    def test_web_explorer_panel(self, tmp_path):
        from jepsen_tpu import web

        d = tmp_path / "demo" / "t1"
        d.mkdir(parents=True)
        (d / "telemetry.jsonl").write_text(json.dumps(
            {"name": "kernel:wgl", "t0": 0, "t1": 100,
             "thread": "t",
             "attrs": {"frontier_curve": [1, 5, 2],
                       "frontier_peak": 5, "iterations": 3,
                       "states_explored": 8}}) + "\n")
        h = _invalid_hist(n=400, seed=5, at=0.5)
        out = wgl.analysis(models.cas_register(), h, certify=True)
        (d / "results.json").write_text(json.dumps(
            {"workload": {"valid?": False,
                          "search": out["search"],
                          "certificate": out["certificate"],
                          "certified": True}}, default=repr))
        html = web._explorer_html(d, "demo/t1")
        assert "search explorer" in html
        assert "polyline" in html                 # the sparkline
        assert "witnessed at" in html             # the marker
        assert "certified" in html

    def test_ledger_accepts_search_fields(self):
        from jepsen_tpu import ledger

        entry = {"round": 1, "ts": 1.0,
                 "headline": {"value": 10.0}, "kernels": {},
                 "search": {"witness_position": 0.85,
                            "frontier_peak": 128}}
        assert ledger.validate_entries([entry]) == 1
        bad = dict(entry, search={"witness_position": "nope"})
        with pytest.raises(ValueError):
            ledger.validate_entries([bad])


class TestCoverageWitnessPosition:
    def test_witness_frac_folds_into_atlas_cells(self):
        from jepsen_tpu import coverage

        results = {"valid?": False, "workload": {
            "valid?": False,
            "anomaly-classes": {"nonlinearizable": "witnessed"},
            "op-indices": [3],
            "search": {"witness-position": 0.12, "witness-entry": 3,
                       "entries": 25}}}
        test = {"name": "wf", "history": [], "results": results,
                "spec": {"workload": "register", "opts": {}}}
        rec = coverage.build_record(test,
                                    recorder=coverage.Recorder())
        coverage.validate_record(rec)
        [a] = [a for a in rec["anomalies"]
               if a["class"] == "nonlinearizable"]
        assert a["witness-frac"] == 0.12
        entry = coverage.atlas_entry(rec)
        coverage.validate_atlas([entry])
        assert entry["witness-frac"] == {"nonlinearizable": 0.12}
        cells = coverage.aggregate([entry])
        cell = cells[("none", "register", "nonlinearizable")]
        assert cell["earliest-witness-frac"] == 0.12
        # the witnessed detail names the localization percentile
        text = coverage.coverage_text(cells, ["register"])
        assert "earliest witness at 12%" in text

    def test_bad_witness_frac_rejected(self):
        from jepsen_tpu import coverage

        rec = {"schema": 1, "run": "r", "ts": 1.0, "workload": "w",
               "faults": [], "valid": False,
               "anomalies": [{"class": "x", "checker": "c",
                              "outcome": "witnessed",
                              "witness-frac": 7.0}]}
        with pytest.raises(ValueError):
            coverage.validate_record(rec)


class TestSeededRunArtifacts:
    """The tier-1 acceptance invariant: a seeded end-to-end run's
    results carry schema-valid certificates that independently
    re-validate from the stored artifacts — and the certify CLI
    agrees."""

    def _run(self, tmp_path):
        state = testing.AtomState()
        test = testing.noop_test()
        test.update(
            name="certify-e2e", store_base=str(tmp_path),
            nodes=["n1", "n2"], concurrency=2,
            db=testing.AtomDB(state),
            client=testing.AtomClient(state, latency_s=0.0),
            checker=checker.compose({
                "linear": checker.linearizable(
                    {"model": models.cas_register(0)}),
                "stats": checker.stats()}),
            generator=gen.clients(gen.limit(40,
                                            lambda: {"f": "read"})))
        return core.run(test)

    def test_run_certificate_roundtrip(self, tmp_path):
        t = self._run(tmp_path)
        res = t["results"]
        assert res["linear"]["certified"] is True
        d = store.path(t)
        with open(d / "results.json") as f:
            loaded = json.load(f)
        cert = loaded["linear"]["certificate"]
        certify.validate_schema(cert)
        from jepsen_tpu.store import format as fmt

        hist = fmt.read_history(d / "history.jlog")
        certify.validate(hist, cert)

    def test_offline_analyze_restamps_certificates(self, tmp_path):
        """`analyze --resume` re-enters core.analyze, so offline
        re-analysis re-extracts AND re-validates proofs against the
        recovered history (the crash-recovery story keeps the proof
        plane)."""
        from jepsen_tpu import resume

        state = testing.AtomState()
        test = testing.noop_test()
        test.update(
            name="certify-offline", store_base=str(tmp_path),
            nodes=["n1", "n2"], concurrency=2,
            db=testing.AtomDB(state),
            client=testing.AtomClient(state, latency_s=0.0),
            checker=checker.compose({
                "linear": checker.linearizable(
                    {"model": models.cas_register(0)}),
                "stats": checker.stats()}),
            spec={"workload": "register", "opts": {}},
            generator=gen.clients(gen.limit(30,
                                            lambda: {"f": "read"})))
        t = core.run(test)
        d = store.path(t)

        def rebuild(opts):
            return {"checker": checker.compose({
                "linear": checker.linearizable(
                    {"model": models.cas_register(0)}),
                "stats": checker.stats()})}

        t2 = resume.analyze_run(d, resume=False, test_fn=rebuild)
        res = t2["results"]
        assert res["linear"]["certified"] is True
        assert res["analysis"]["certificates"]["certified"] >= 1
        assert res["analysis"]["certificates"]["errors"] == 0

    def test_certify_cli(self, tmp_path, capsys):
        import argparse

        from jepsen_tpu import cli as jcli

        t = self._run(tmp_path)
        d = store.path(t)
        cmd = jcli.certify_cmd()["certify"]
        ns = argparse.Namespace(test=str(d), timestamp="latest",
                                store=None, print_=False)
        assert cmd["run"](ns) == 0
        out = capsys.readouterr().out
        assert "certified" in out
        # tamper the stored certificate: the CLI must fail it
        with open(d / "results.json") as f:
            res = json.load(f)
        order = res["linear"]["certificate"]["segments"][0]["order"]
        if len(order) > 1:
            order[0], order[-1] = order[-1], order[0]
        else:
            order[0][0] += 1
        with open(d / "results.json", "w") as f:
            json.dump(res, f, default=repr)
        assert cmd["run"](ns) == 1
