"""Fleet suite (ISSUE 13): crash-safe multi-tenant
checking-as-a-service.

The robustness contract under test: no lost chunks, no wedged queues,
no verdict ever silently wrong or silently dropped — under chaos frame
loss, mid-stream server SIGKILL, and quota saturation. The acceptance
invariant (TestMultiTenantE2E / TestChaosFleet): N concurrent seeded
runs streamed through ONE server — including a kill+restart schedule
and a chaos-framed schedule — produce per-run verdicts and validating
certificates IDENTICAL to solo runs, with admission control rejecting
(never corrupting) the over-quota tenant.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

from jepsen_tpu import chaos, core, ledger, telemetry, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.checker import models
from jepsen_tpu.fleet import client as fclient
from jepsen_tpu.fleet import flightrec
from jepsen_tpu.fleet import scheduler as fsched
from jepsen_tpu.fleet import server as fserver
from jepsen_tpu.fleet import wal as fwal
from jepsen_tpu.fleet import wire
from jepsen_tpu.reports import trace as rtrace
from jepsen_tpu.history import History, op as make_op
from jepsen_tpu.tpu import certify, synth, wgl

SEED = 4242


def seeded_hist(seed, n=300, corrupt=False):
    h = synth.register_history(n, seed=seed)
    if corrupt:
        h, _ = synth.corrupt_register_history(h)
    return h


def stream_run(addr, tenant, run, hist, chunk=50, transport=None,
               io_timeout_s=3.0, deadline_s=120.0):
    """Streams a history and returns the verdict envelope, retrying
    whole chunks across server restarts (what a polite tenant does
    with its retry-after budget)."""
    c = fclient.FleetClient(addr, tenant, run, model="cas-register",
                            transport=transport,
                            io_timeout_s=io_timeout_s)
    ops = list(hist)
    deadline = time.monotonic() + deadline_s
    i = 0
    while i < len(ops):
        try:
            c.send_chunk(ops[i:i + chunk])
            i += chunk
        except fclient.FleetError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.2)
    env = c.finish(timeout_s=deadline_s)
    c.close()
    return env


def solo_verdict(hist):
    return wgl.analysis(models.cas_register(), hist, certify=True)


def assert_verdict_matches_solo(hist, fleet_result, solo):
    """The acceptance comparison: same verdict, and the fleet's
    certificate independently validates against the raw history —
    for valid runs the proofs are bit-identical."""
    assert fleet_result["valid?"] == solo["valid?"]
    certify.validate(hist, fleet_result["certificate"])
    if solo["valid?"] is True:
        assert json.dumps(fwal.json_safe(solo["certificate"]),
                          sort_keys=True) == \
            json.dumps(fleet_result["certificate"], sort_keys=True)


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------

class TestWire:
    def test_frame_round_trip(self):
        a, b = socket.socketpair()
        try:
            msg = {"type": "chunk", "seq": 3, "ops": [{"f": "read"}]}
            wire.send_msg(a, msg)
            assert wire.recv_msg(b) == msg
        finally:
            a.close()
            b.close()

    def test_corrupt_frame_raises(self):
        a, b = socket.socketpair()
        try:
            buf = bytearray(wire.frame_msg({"type": "fin"}))
            buf[-1] ^= 0xFF  # flip a payload byte: CRC must catch it
            a.sendall(bytes(buf))
            with pytest.raises(wire.FrameError):
                wire.recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_torn_frame_raises(self):
        a, b = socket.socketpair()
        try:
            buf = wire.frame_msg({"type": "fin", "chunks": 9})
            a.sendall(buf[:len(buf) // 2])
            a.close()
            with pytest.raises(wire.FrameError):
                wire.recv_msg(b)
        finally:
            b.close()

    def test_ops_wire_round_trip(self):
        ops = [make_op(index=0, time=1, type="invoke", process=2,
                       f="write", value=3)]
        back = wire.ops_from_wire(wire.ops_to_wire(ops))
        assert back[0].to_dict() == ops[0].to_dict()


# ---------------------------------------------------------------------------
# the WAL
# ---------------------------------------------------------------------------

class TestWAL:
    def test_append_replay_round_trip(self, tmp_path):
        p = tmp_path / "t.wal"
        w = fwal.RunWAL(p)
        w.append({"t": "hello", "tenant": "a", "run": "r",
                  "model": "cas-register", "weight": 1.0})
        w.append({"t": "chunk", "seq": 1, "ops": [{"f": "read"}]})
        w.append({"t": "chunk", "seq": 2, "ops": [{"f": "write"}]})
        w.append({"t": "fin", "chunks": 2})
        w.close()
        folded = fwal.replay(p)
        assert folded["last_seq"] == 2
        assert folded["fin"]["chunks"] == 2
        assert folded["hello"]["model"] == "cas-register"

    def test_torn_tail_dropped(self, tmp_path):
        p = tmp_path / "t.wal"
        w = fwal.RunWAL(p)
        w.append({"t": "chunk", "seq": 1, "ops": []})
        w.append({"t": "chunk", "seq": 2, "ops": []})
        w.close()
        raw = p.read_bytes()
        p.write_bytes(raw[:-3])  # tear the tail record
        folded = fwal.replay(p)
        assert folded["last_seq"] == 1  # seq 2 must be re-sent

    def test_duplicate_seq_first_wins(self, tmp_path):
        p = tmp_path / "t.wal"
        w = fwal.RunWAL(p)
        w.append({"t": "chunk", "seq": 1, "ops": [{"v": "first"}]})
        w.append({"t": "chunk", "seq": 1, "ops": [{"v": "second"}]})
        w.close()
        assert fwal.replay(p)["chunks"][1] == [{"v": "first"}]

    def test_seq_gap_truncates_resume_point(self, tmp_path):
        p = tmp_path / "t.wal"
        w = fwal.RunWAL(p)
        w.append({"t": "chunk", "seq": 1, "ops": []})
        w.append({"t": "chunk", "seq": 3, "ops": []})
        w.close()
        folded = fwal.replay(p)
        assert folded["last_seq"] == 1
        assert 3 not in folded["chunks"]

    def test_verdict_write_deterministic_and_atomic(self, tmp_path):
        v = {"run": "r", "result": {"valid?": True, "z": 1, "a": 2}}
        fwal.write_verdict(tmp_path, "t", "r", v)
        b1 = fwal.verdict_path(tmp_path, "t", "r").read_bytes()
        fwal.write_verdict(tmp_path, "t", "r", dict(reversed(
            list(v.items()))))
        b2 = fwal.verdict_path(tmp_path, "t", "r").read_bytes()
        assert b1 == b2  # key order can't change the bytes
        assert not list(tmp_path.glob("**/*.tmp"))

    def test_unsafe_names_rejected(self):
        assert not fwal.safe_name("../etc")
        assert not fwal.safe_name(".hidden")
        assert not fwal.safe_name("a/b")
        assert fwal.safe_name("tenant-1.run_2")


# ---------------------------------------------------------------------------
# wgl.check_slices — the fleet's batching entry point
# ---------------------------------------------------------------------------

class TestCheckSlices:
    def test_matches_host_reach(self):
        from jepsen_tpu.tpu import encode as enc_mod

        m = models.cas_register()
        slices = []
        expect = []
        for seed in (1, 2, 3):
            enc = enc_mod.encode(m, seeded_hist(seed, 120))
            cuts = wgl.valid_cut_points(enc)
            hi = int(cuts[len(cuts) // 2]) if len(cuts) else enc.m
            seg = enc.segment(0, hi)
            slices.append((seg, 0))
            expect.append(wgl.search_host_reach(seg))
        out, unk = wgl.check_slices(slices)
        assert not unk.any()
        assert [int(x) for x in out] == expect

    def test_shared_enc_multiple_start_states(self):
        from jepsen_tpu.tpu import encode as enc_mod

        m = models.cas_register()
        enc = enc_mod.encode(m, seeded_hist(4, 80))
        seg = enc.segment(0, min(enc.m, 40))
        rows = [(seg.with_init(s), s)
                for s in range(min(enc.n_states, 3))]
        out, unk = wgl.check_slices(rows)
        assert len(out) == len(rows)
        for (sl, s), mask, u in zip(rows, out, unk):
            if not u:
                assert int(mask) == wgl.search_host_reach(sl)

    def test_empty(self):
        out, unk = wgl.check_slices([])
        assert len(out) == 0 and len(unk) == 0


# ---------------------------------------------------------------------------
# scheduler: weighted fairness, cross-tenant packing, no wedged queues
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_weighted_fair_drain(self):
        s = fsched.Scheduler(max_batch=12)
        s.set_weight("heavy", 2.0)
        s.set_weight("light", 1.0)
        for i in range(20):
            s.submit("slice", "heavy", "r", i)
            s.submit("slice", "light", "r", i)
        with s._lock:
            batch = s._drain_fair_locked()
        by = {}
        for item in batch:
            by[item.tenant] = by.get(item.tenant, 0) + 1
        # a 2:1 weight ratio drains a backlogged round 2:1
        assert by["heavy"] == 2 * by["light"]

    def test_idle_tenant_share_redistributed(self):
        s = fsched.Scheduler(max_batch=8)
        s.set_weight("idle", 10.0)  # huge weight, zero work
        for i in range(8):
            s.submit("slice", "busy", "r", i)
        with s._lock:
            batch = s._drain_fair_locked()
        assert len(batch) == 8  # busy gets the whole batch

    def test_stop_resolves_leftovers_no_wedge(self):
        s = fsched.Scheduler()
        item = s.submit("final", "t", "r",
                        {"engine": "wgl", "model": "cas-register",
                         "history": History([])})
        s.stop()  # never started: queued work must still resolve
        assert item.done.wait(timeout=5)
        assert item.result["valid?"] == "unknown"

    def test_batch_failure_never_wedges(self, monkeypatch):
        s = fsched.Scheduler()
        monkeypatch.setattr(
            wgl, "analysis_batch_streamed",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("boom")))
        items = [s.submit("final", "t", f"r{i}",
                          {"engine": "wgl", "model": "cas-register",
                           "history": seeded_hist(1, 40)})
                 for i in range(2)]
        with s._lock:
            batch = s._drain_fair_locked()
        s._run_batch(batch)
        for i in items:
            assert i.done.is_set()
            assert i.result["valid?"] == "unknown"

    def test_breaker_opens_then_host_floor_still_correct(
            self, monkeypatch):
        # SYSTEMIC failure: every run in the batch dies on device, so
        # per-run attribution finds no survivor and the FLEET breaker
        # (not per-run quarantine) takes the hit
        s = fsched.Scheduler()
        s._breaker.cooldown_s = 3600  # stay open for the test
        monkeypatch.setattr(
            wgl, "analysis_batch_streamed",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("device dead")))
        hist = seeded_hist(2, 60)
        for i in range(fsched.BREAKER_THRESHOLD):
            items = [s.submit("final", "t", f"r{i}{j}",
                              {"engine": "wgl",
                               "model": "cas-register",
                               "history": hist})
                     for j in range(2)]
            with s._lock:
                batch = s._drain_fair_locked()
            s._run_batch(batch)
            for it in items:
                assert it.result["valid?"] == "unknown"
        assert s._breaker.opened_at is not None
        # systemic failure opens the breaker WITHOUT quarantining
        # anyone — no single run was at fault
        assert s.stats()["quarantine"] == []
        # breaker open: finals route to the pure-host search and the
        # verdict is still CORRECT (slower, never wrong)
        item = s.submit("final", "t", "rz",
                        {"engine": "wgl", "model": "cas-register",
                         "history": hist})
        with s._lock:
            batch = s._drain_fair_locked()
        s._run_batch(batch)
        assert item.result["valid?"] is True
        assert s.stats()["host_floor"] == 1

    def test_poison_run_quarantined_not_systemic(self, monkeypatch):
        # ONE run's history kills the shared launch: attribution
        # bisects along run boundaries, quarantines the offender to
        # the solo host lane, and the fleet breaker stays CLOSED —
        # healthy runs keep their device-batched verdicts
        s = fsched.Scheduler()
        poison = seeded_hist(3, 60)
        real = wgl.analysis_batch_streamed

        def selective(model, hists, **kw):
            if any(h is poison for h in hists):
                raise RuntimeError("device dead")
            return real(model, hists, **kw)

        monkeypatch.setattr(wgl, "analysis_batch_streamed", selective)
        items = [s.submit("final", "t", f"r{j}",
                          {"engine": "wgl", "model": "cas-register",
                           "history": seeded_hist(10 + j, 60)})
                 for j in range(2)]
        bad = s.submit("final", "t", "rbad",
                       {"engine": "wgl", "model": "cas-register",
                        "history": poison})
        with s._lock:
            batch = s._drain_fair_locked()
        s._run_batch(batch)
        # healthy runs got their verdicts via solo-device retry
        for it in items:
            assert it.result["valid?"] is True
        # the poison run still got a CORRECT verdict (host lane)
        assert bad.result["valid?"] is True
        st = s.stats()
        assert [q["run"] for q in st["quarantine"]] == ["rbad"]
        assert s._breaker.opened_at is None
        # quarantined: the next final for that run skips the shared
        # batch entirely and is served from the host lane
        bad2 = s.submit("final", "t", "rbad",
                        {"engine": "wgl", "model": "cas-register",
                         "history": poison})
        with s._lock:
            batch = s._drain_fair_locked()
        s._run_batch(batch)
        assert bad2.result["valid?"] is True
        assert s._breaker.opened_at is None


# ---------------------------------------------------------------------------
# streaming checks
# ---------------------------------------------------------------------------

class TestStreaming:
    def _drive(self, hist, seed_chunks=100):
        sched = fsched.Scheduler(window_s=0.01).start()
        try:
            sr = fsched.StreamingRun("cas-register", sched, "t", "r")
            ops = list(hist)
            for i in range(0, len(ops), seed_chunks):
                sr.add_ops(ops[i:i + seed_chunks])
            sr.step()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                st = sr.status()
                if st["state"] != "streaming" or \
                        st["checked-frac"] > 0:
                    # one more step to push past the last cut
                    with sr._lock:
                        busy = sr._inflight
                    if not busy:
                        return sr
                time.sleep(0.05)
            return sr
        finally:
            sched.stop()

    def test_valid_stream_tightens(self):
        sr = self._drive(seeded_hist(21, 600))
        st = sr.status()
        assert st["state"] in ("streaming",)
        assert st["checked-frac"] > 0  # the prefix is certified

    def test_corrupt_stream_goes_tentative_invalid(self):
        telemetry.reset()
        h, _ = synth.corrupt_register_history(
            synth.register_history(600, seed=22), at_frac=0.2)
        sched = fsched.Scheduler(window_s=0.01).start()
        try:
            sr = fsched.StreamingRun("cas-register", sched, "t", "r")
            ops = list(h)
            deadline = time.monotonic() + 60
            i = 0
            while i < len(ops) and time.monotonic() < deadline:
                sr.add_ops(ops[i:i + 100])
                i += 100
                sr.step()
                if sr.status()["state"] == "tentative-invalid":
                    break
            deadline = time.monotonic() + 30
            while sr.status()["state"] == "streaming" and \
                    time.monotonic() < deadline:
                sr.step()
                time.sleep(0.05)
            # the verdict tightened to invalid BEFORE fin
            assert sr.status()["state"] == "tentative-invalid"
        finally:
            sched.stop()

    def test_unsupported_model_degrades_honestly(self):
        sched = fsched.Scheduler()
        sr = fsched.StreamingRun("no-such-model", sched, "t", "r")
        sr.add_ops(list(seeded_hist(1, 200)))
        assert sr.status()["state"] == "unsupported"


# ---------------------------------------------------------------------------
# server end-to-end
# ---------------------------------------------------------------------------

class TestServerE2E:
    def test_single_tenant_verdict_matches_solo(self, tmp_path):
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            for name, corrupt in (("valid", False), ("bad", True)):
                h = seeded_hist(SEED, 300, corrupt=corrupt)
                env = stream_run(srv.addr, "t1", f"r-{name}", h)
                assert_verdict_matches_solo(h, env["result"],
                                            solo_verdict(h))
        finally:
            srv.stop()

    def test_model_initial_value_in_spec(self, tmp_path):
        """A DB that seeds its register (AtomDB writes 0) checked
        against an initial-None model is PROVABLY nonlinearizable on
        the first read — so the wire's model spec must carry the
        initial value (the verify-skill gotcha, fleet edition)."""
        from jepsen_tpu.history import op as mk

        ops = []
        for i, (t, p, f, v) in enumerate((
                ("invoke", 0, "read", None), ("ok", 0, "read", 0),
                ("invoke", 1, "write", 3), ("ok", 1, "write", 3),
                ("invoke", 0, "read", None), ("ok", 0, "read", 3))):
            ops.append(mk(index=i, time=i, type=t, process=p, f=f,
                          value=v))
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            c0 = fclient.FleetClient(srv.addr, "t", "no-initial",
                                     model="register")
            c0.send_chunk(ops)
            r0 = c0.finish()["result"]
            assert r0["valid?"] is False  # read 0 vs initial None
            c1 = fclient.FleetClient(srv.addr, "t", "seeded",
                                     model="register", initial=0)
            c1.send_chunk(ops)
            r1 = c1.finish()["result"]
            assert r1["valid?"] is True
            certify.validate(History(ops), r1["certificate"])
        finally:
            srv.stop()

    def test_stats_and_prometheus_labels(self, tmp_path):
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            stream_run(srv.addr, "acme", "r1", seeded_hist(1, 120))
            st = srv.stats()
            assert st["tenants"]["acme"]["verdicts"] == 1
            assert st["tenants"]["acme"]["ops"] == len(
                seeded_hist(1, 120))
            text = srv.prometheus_text()
            assert 'jepsen_fleet_tenant_ops{tenant="acme"}' in text
            assert "jepsen_fleet_scheduler_launches" in text
        finally:
            srv.stop()

    def test_duplicate_and_out_of_order_chunks(self, tmp_path):
        """Raw-socket protocol check: duplicates re-ack idempotently,
        gaps resync — no corruption either way."""
        srv = fserver.FleetServer(tmp_path / "fleet",
                                  stream_checks=False).start()
        try:
            s = socket.create_connection(srv.addr, timeout=5)
            wire.send_magic(s)
            wire.send_msg(s, {"type": "hello", "tenant": "t",
                              "run": "r", "model": "cas-register"})
            assert wire.recv_msg(s)["type"] == "helloed"
            ops = wire.ops_to_wire(list(seeded_hist(2, 30)))
            wire.send_msg(s, {"type": "chunk", "seq": 1, "ops": ops})
            assert wire.recv_msg(s)["seq"] == 1
            # duplicate: idempotent re-ack
            wire.send_msg(s, {"type": "chunk", "seq": 1, "ops": ops})
            assert wire.recv_msg(s)["seq"] == 1
            # gap: resync ack names the journaled prefix
            wire.send_msg(s, {"type": "chunk", "seq": 5, "ops": ops})
            r = wire.recv_msg(s)
            assert r["seq"] == 1 and r.get("resync")
            s.close()
            folded = fwal.replay(
                fwal.wal_path(tmp_path / "fleet", "t", "r"))
            assert folded["last_seq"] == 1  # journaled exactly once
        finally:
            srv.stop()

    def test_fleet_page_and_metrics(self, tmp_path):
        from jepsen_tpu import web

        base = tmp_path / "store"
        # no server: the page renders an honest absence
        assert "no fleet server" in web.fleet_html(base)
        srv = fserver.FleetServer(base / "fleet").start()
        try:
            stream_run(srv.addr, "acme", "r1", seeded_hist(1, 100))
            html = web.fleet_html(base)
            assert "acme" in html and "verdicts" in html
            st, addr = web._fleet_stats(base)
            assert st is not None
            text = fserver.prometheus_from_stats(st)
            assert 'tenant="acme"' in text
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_ninth_tenant_rejected_in_flight_unharmed(self, tmp_path):
        quotas = fserver.Quotas(max_tenants=3, max_total_streams=8)
        srv = fserver.FleetServer(tmp_path / "fleet",
                                  quotas=quotas).start()
        try:
            hists = {f"t{i}": seeded_hist(100 + i, 200)
                     for i in range(3)}
            clients = {}
            for t, h in hists.items():
                c = fclient.FleetClient(srv.addr, t, "r",
                                        io_timeout_s=3)
                c.send_chunk(list(h)[:50])  # streams now in flight
                clients[t] = c
            # the over-quota tenant is REJECTED with retry-after...
            with pytest.raises(fclient.FleetRejected) as ei:
                fclient.FleetClient(srv.addr, "t-late", "r",
                                    io_timeout_s=3).send_chunk(
                    list(hists["t0"])[:10])
            assert ei.value.retry_after is not None
            assert srv.stats()["rejected"] >= 1
            # ...and every in-flight stream completes unharmed
            for t, c in clients.items():
                ops = list(hists[t])
                for i in range(50, len(ops), 50):
                    c.send_chunk(ops[i:i + 50])
                env = c.finish()
                assert_verdict_matches_solo(hists[t], env["result"],
                                            solo_verdict(hists[t]))
        finally:
            srv.stop()

    def test_colliding_run_name_rejected_not_stale_verdict(
            self, tmp_path):
        """Re-submitting a DIFFERENT history under an existing run
        name must fail loudly — never silently return the old run's
        verdict as if computed on the new data. claim() stays the
        legitimate way to fetch an existing verdict."""
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            h1 = seeded_hist(61, 150)
            env1 = stream_run(srv.addr, "t", "r", h1)
            assert env1["result"]["valid?"] is True
            c2 = fclient.FleetClient(srv.addr, "t", "r",
                                     io_timeout_s=3)
            with pytest.raises(fclient.FleetError,
                               match="colliding run name"):
                c2.send_chunk(list(seeded_hist(62, 150))[:50])
            # the fresh-client verdict fetch still works
            env = fclient.FleetClient(srv.addr, "t", "r",
                                      io_timeout_s=3).claim()
            assert env["result"]["valid?"] is True
        finally:
            srv.stop()

    def test_bad_names_and_models_rejected_without_retry(
            self, tmp_path):
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            with pytest.raises(fclient.FleetRejected) as ei:
                fclient.FleetClient(srv.addr, "../evil", "r").status()
            assert ei.value.retry_after is None
            with pytest.raises(fclient.FleetRejected):
                fclient.FleetClient(srv.addr, "t", "r",
                                    model="no-such-model").status()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# crash schedules
# ---------------------------------------------------------------------------

class TestCrashSafety:
    def test_sigkill_midstream_replays_byte_identical(self, tmp_path):
        h = seeded_hist(SEED, 400)
        ops = list(h)
        chunks = [ops[i:i + 50] for i in range(0, len(ops), 50)]

        # clean reference
        ref_base = tmp_path / "ref"
        srv = fserver.FleetServer(ref_base).start()
        c = fclient.FleetClient(srv.addr, "t1", "r1", io_timeout_s=3)
        for ch in chunks:
            c.send_chunk(ch)
        c.finish()
        srv.stop()
        ref = fwal.verdict_path(ref_base, "t1", "r1").read_bytes()

        # SIGKILL mid-stream, restart on the same WAL dir
        base = tmp_path / "crash"
        srv = fserver.FleetServer(base).start()
        c = fclient.FleetClient(srv.addr, "t1", "r1", io_timeout_s=2)
        for ch in chunks[:4]:
            c.send_chunk(ch)
        port = srv.addr[1]
        srv.kill()
        srv2 = fserver.FleetServer(base, port=port).start()
        for ch in chunks[4:]:
            c.send_chunk(ch)
        env = c.finish()
        assert env["result"]["valid?"] is True
        got = fwal.verdict_path(base, "t1", "r1").read_bytes()
        assert got == ref  # byte-identical replay
        srv2.stop()

    def test_fin_crash_recovery_resubmits(self, tmp_path):
        h = seeded_hist(SEED, 400)
        ops = list(h)
        base = tmp_path / "fleet"
        sched = fsched.Scheduler()
        srv = fserver.FleetServer(base, scheduler=sched).start()
        c = fclient.FleetClient(srv.addr, "t1", "r1", io_timeout_s=1)
        for i in range(0, len(ops), 50):
            c.send_chunk(ops[i:i + 50])
        sched._stop.set()  # freeze: the fin's final check never runs
        time.sleep(0.4)
        with pytest.raises(fclient.FleetError):
            c.finish(timeout_s=2)
        srv.kill()
        # restart: recovery finds fin-without-verdict and re-submits
        srv2 = fserver.FleetServer(base).start()
        assert srv2.stats()["recovered"] == 1
        env = fclient.FleetClient(srv2.addr, "t1", "r1",
                                  io_timeout_s=3).claim()
        assert_verdict_matches_solo(h, env["result"], solo_verdict(h))
        srv2.stop()


# ---------------------------------------------------------------------------
# the acceptance invariants: concurrency, chaos, kill — vs solo
# ---------------------------------------------------------------------------

def _concurrent_runs(addr, hists, transports=None, barrier=None,
                     out=None, chunk=50):
    out = out if out is not None else {}
    errs = []

    def one(tenant, h):
        try:
            t = (transports or {}).get(tenant)
            c = fclient.FleetClient(addr, tenant, "r", model="cas-register",
                                    transport=t, io_timeout_s=2.0)
            ops = list(h)
            deadline = time.monotonic() + 180
            i = 0
            while i < len(ops):
                try:
                    c.send_chunk(ops[i:i + chunk])
                    i += chunk
                except fclient.FleetError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.2)
            if barrier is not None:
                barrier.wait(timeout=60)
            out[tenant] = c.finish(timeout_s=180)
            c.close()
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append((tenant, e))

    threads = [threading.Thread(target=one, args=(t, h), daemon=True)
               for t, h in hists.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errs, errs
    return out


class TestMultiTenantE2E:
    def test_eight_tenants_identical_to_solo_and_batched(
            self, tmp_path):
        telemetry.reset()
        # 7 valid + 1 seeded-anomaly run; fins synchronized so the
        # finals land in shared launches
        hists = {f"t{i}": seeded_hist(500 + i, 240, corrupt=(i == 3))
                 for i in range(8)}
        sched = fsched.Scheduler(window_s=0.4)
        srv = fserver.FleetServer(tmp_path / "fleet",
                                  scheduler=sched).start()
        try:
            barrier = threading.Barrier(8)
            out = _concurrent_runs(srv.addr, hists, barrier=barrier)
            assert set(out) == set(hists)
            for t, h in hists.items():
                assert_verdict_matches_solo(h, out[t]["result"],
                                            solo_verdict(h))
            stats = srv.stats()
            st = stats["scheduler"]
            # continuous batching actually happened ACROSS tenants
            assert st["cross_tenant_launches"] >= 1
            assert st["max_tenants_in_launch"] >= 2
            assert st["final_hists"] == 8
            # launch classes split: the blended hists_per_launch bug
            assert st["slice_launches"] + st["final_launches"] == \
                st["launches"]
            # the flight recorder's acceptance invariants (ISSUE 17):
            # a schema-valid latency block on EVERY verdict...
            for t in hists:
                lat = out[t].get("latency")
                flightrec.validate_latency(lat)
                assert lat["total_ms"] > 0
            # ...a decision log whose reason counts sum to the total
            # launches, per-class occupancy in range...
            fr = stats["flightrec"]
            assert fr["enabled"] is True
            assert sum(fr["decisions"].values()) == fr["launches"] \
                == st["launches"]
            assert fr["verdict_ms"]["n"] == 8
            assert set(fr["tenants"]) == set(hists)
            for cls in ("slice", "final"):
                assert 0.0 <= fr["classes"][cls]["occupancy"] <= 1.0
            # ...schema-valid records and a validating Perfetto
            # fleet-session export with per-tenant + device tracks
            recs = srv.flightrec.records()
            flightrec.validate_records(recs)
            doc = rtrace.fleet_chrome_trace(recs)
            assert rtrace.validate_chrome_trace(doc) > 0
            tracks = {e["args"]["name"]
                      for e in doc["traceEvents"]
                      if e["ph"] == "M"
                      and e["name"] == "thread_name"}
            assert set(hists) <= tracks
            assert "device launches" in tracks
            # ...and scrape-parseable tenant-labeled /metrics samples
            prom = fserver.prometheus_from_stats(stats)
            assert flightrec.validate_prometheus(prom) > 0
            assert 'tenant="t3"' in prom
        finally:
            srv.stop()


class TestChaosFleet:
    def test_chaos_transport_runs_identical_to_solo(self, tmp_path):
        """Satellite 1's tier-1 invariant: N concurrent seeded runs
        through ONE chaos-wrapped server — frames dropped, duplicated,
        reordered, torn — still yield verdicts + certificates
        identical to solo runs."""
        hists = {f"t{i}": seeded_hist(700 + i, 200,
                                      corrupt=(i == 1))
                 for i in range(4)}
        transports = {t: chaos.ChaosFleetTransport(seed=SEED + i)
                      for i, t in enumerate(hists)}
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            out = _concurrent_runs(srv.addr, hists,
                                   transports=transports, chunk=40)
            for t, h in hists.items():
                assert_verdict_matches_solo(h, out[t]["result"],
                                            solo_verdict(h))
            # the schedule actually injected faults
            total = sum(sum(tr.tally.values())
                        for tr in transports.values())
            assert total > 0, "chaos rates injected nothing"
        finally:
            srv.stop()

    def test_chaos_plus_midstream_kill(self, tmp_path):
        """The full acceptance schedule: chaos framing AND a
        mid-stream SIGKILL + restart, concurrently."""
        hists = {f"t{i}": seeded_hist(800 + i, 200)
                 for i in range(3)}
        transports = {t: chaos.ChaosFleetTransport(seed=9000 + i)
                      for i, t in enumerate(hists)}
        base = tmp_path / "fleet"
        srv_box = [fserver.FleetServer(base).start()]
        port = srv_box[0].addr[1]

        def killer():
            time.sleep(1.0)
            srv_box[0].kill()
            srv_box[0] = fserver.FleetServer(base, port=port).start()

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        try:
            out = _concurrent_runs(srv_box[0].addr, hists,
                                   transports=transports, chunk=30)
            kt.join(timeout=30)
            for t, h in hists.items():
                assert_verdict_matches_solo(h, out[t]["result"],
                                            solo_verdict(h))
        finally:
            srv_box[0].stop()


# ---------------------------------------------------------------------------
# the flight recorder in the fleet (ISSUE 17)
# ---------------------------------------------------------------------------

class TestFlightRecorderFleet:
    def test_verdict_files_byte_identical_with_recorder_off(
            self, tmp_path):
        """The latency block rides NEXT to the verdict, never inside
        it: the verdict file's bytes must not change with the
        recorder on vs disabled."""
        h = seeded_hist(SEED, 200)
        envs = {}
        for name, on in (("on", True), ("off", False)):
            base = tmp_path / name
            srv = fserver.FleetServer(base, flightrec=on).start()
            try:
                envs[name] = stream_run(srv.addr, "t", "r", h)
            finally:
                srv.stop()
        on_b = fwal.verdict_path(tmp_path / "on", "t", "r").read_bytes()
        off_b = fwal.verdict_path(tmp_path / "off", "t",
                                  "r").read_bytes()
        assert on_b == off_b
        # the wire envelope differs exactly by the latency sibling
        flightrec.validate_latency(envs["on"]["latency"])
        assert "latency" not in envs["off"]
        assert envs["on"]["result"] == envs["off"]["result"]

    def test_chaos_frames_never_orphan_or_double_count_spans(
            self, tmp_path):
        """Chaos parity: dropped/duplicated/reordered frames may
        retransmit forever, but every journaled (tenant, run, seq)
        records EXACTLY one chunk span — no orphans for dropped
        frames, no double counts for duplicated ones."""
        hists = {f"t{i}": seeded_hist(1300 + i, 150)
                 for i in range(3)}
        transports = {t: chaos.ChaosFleetTransport(seed=SEED + 7 * i)
                      for i, t in enumerate(hists)}
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            out = _concurrent_runs(srv.addr, hists,
                                   transports=transports, chunk=30)
            assert sum(sum(tr.tally.values())
                       for tr in transports.values()) > 0
            recs = srv.flightrec.records()
            # validate_records raises on duplicate (tenant, run, seq)
            flightrec.validate_records(recs)
            chunk_spans = {(r["tenant"], r["seq"]) for r in recs
                           if r["kind"] == "chunk"}
            # Per tenant: seqs form a gapless 1..max run. A dropped
            # frame that orphaned a span would leave a gap; a
            # duplicated frame that double-counted would have tripped
            # validate_records above. (The exact count is schedule-
            # dependent — a chaos-failed send can resume the staged
            # chunk or stage a fresh seq — so contiguity, not count,
            # is the invariant.)
            for t in hists:
                seqs = {s for (tt, s) in chunk_spans if tt == t}
                assert seqs, f"{t}: no chunk spans journaled"
                assert seqs == set(range(1, max(seqs) + 1)), (
                    f"{t}: gap in journaled seqs {sorted(seqs)}")
            for t in hists:
                flightrec.validate_latency(out[t]["latency"])
        finally:
            srv.stop()

    def test_sigkill_replayed_verdicts_carry_replay_blocks(
            self, tmp_path):
        """A SIGKILL'd server's replayed verdicts still carry a
        complete latency block — replay-annotated, with the
        ingest-side slices honestly zero (they died with the old
        process)."""
        h = seeded_hist(SEED, 300)
        ops = list(h)
        base = tmp_path / "fleet"
        sched = fsched.Scheduler()
        srv = fserver.FleetServer(base, scheduler=sched).start()
        c = fclient.FleetClient(srv.addr, "t1", "r1", io_timeout_s=1)
        for i in range(0, len(ops), 50):
            c.send_chunk(ops[i:i + 50])
        sched._stop.set()  # freeze: the fin's final check never runs
        time.sleep(0.4)
        with pytest.raises(fclient.FleetError):
            c.finish(timeout_s=2)
        srv.kill()
        # restart: recovery re-submits the fin-without-verdict run
        srv2 = fserver.FleetServer(base).start()
        try:
            env = fclient.FleetClient(srv2.addr, "t1", "r1",
                                      io_timeout_s=3).claim()
            lat = env["latency"]
            flightrec.validate_latency(lat)
            assert lat["replay"] is True
            assert lat["ingest_wait"] == 0.0
            assert lat["wal_fsync"] == 0.0

            # the verdict-file-served path (no recompute) also
            # carries a complete replay block after ANOTHER restart
            srv2.stop()
            srv3 = fserver.FleetServer(base).start()
            env = fclient.FleetClient(srv3.addr, "t1", "r1",
                                      io_timeout_s=3).claim()
            flightrec.validate_latency(env["latency"])
            assert env["latency"]["replay"] is True
            srv3.stop()
        finally:
            pass

    def test_graceful_stop_drains_with_drain_reason(self, tmp_path):
        """stop() flushes queued work as `drain` launches; every
        launch still lands in the decision log."""
        sched = fsched.Scheduler(window_s=30.0)  # never times out
        srv = fserver.FleetServer(tmp_path / "fleet",
                                  scheduler=sched).start()
        h = seeded_hist(SEED, 120)
        c = fclient.FleetClient(srv.addr, "t", "r", io_timeout_s=3)
        for i in range(0, 120, 40):
            c.send_chunk(list(h)[i:i + 40])

        def fin():
            try:
                c.finish(timeout_s=30)
            except fclient.FleetError:
                pass

        ft = threading.Thread(target=fin, daemon=True)
        ft.start()
        time.sleep(0.5)  # the final sits in the 30s batching window
        srv.stop()
        ft.join(timeout=10)
        snap = srv.flightrec.snapshot()
        assert snap["decisions"]["drain"] >= 1
        assert sum(snap["decisions"].values()) == snap["launches"]

    def test_snapshot_survives_sigkill_and_folds(self, tmp_path):
        """flightrec.json persists per verdict; a restarted server
        folds its predecessor's SLO history back in."""
        base = tmp_path / "fleet"
        srv = fserver.FleetServer(base).start()
        stream_run(srv.addr, "t", "r1", seeded_hist(SEED, 150))
        before = srv.flightrec.snapshot()["verdict_ms"]["n"]
        assert before >= 1
        srv.kill()
        srv2 = fserver.FleetServer(base).start()
        try:
            s = srv2.flightrec.snapshot()
            assert s["verdict_ms"]["n"] == before
            assert s["tenants"]["t"]["verdict_ms"]["n"] == before
        finally:
            srv2.stop()

    def test_client_ack_histogram_rides_result_summary(self):
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            srv = fserver.FleetServer(td).start()
            try:
                c = fclient.FleetClient(srv.addr, "t", "r",
                                        io_timeout_s=3)
                ops = list(seeded_hist(SEED, 100))
                for i in range(0, 100, 25):
                    c.send_chunk(ops[i:i + 25])
                assert c.ack_ms.n == 4
                streamer = fclient.FleetStreamer(None, c)
                out = streamer.result_summary(timeout_s=60)
                assert out["ack_ms"]["n"] == 4
                assert out["ack_ms"]["p99"] >= out["ack_ms"]["p50"] \
                    >= 0
                flightrec.validate_latency(out["verdict"]["latency"])
            finally:
                srv.stop()

    def test_quarantine_events_in_recorder_and_metrics(
            self, tmp_path, monkeypatch):
        """Poison-run quarantine is observable end to end: the flight
        recorder journals a schema-valid quarantine record, /metrics
        exports quarantined_runs plus per-action event counters, and
        the host-lane launch lands in the decision log under its own
        "quarantine" reason — while a healthy neighbor keeps its
        device-batched verdict."""
        MARK = 888888  # wire round-trips rebuild ops, so a sentinel
        poison = []    # value tags the poison history, not identity
        for f, v in [("write", MARK), ("read", MARK)] * 10:
            poison.append(make_op(
                index=len(poison), time=len(poison), type="invoke",
                process=0, f=f, value=v if f == "write" else None))
            poison.append(make_op(
                index=len(poison), time=len(poison), type="ok",
                process=0, f=f, value=v))
        real = wgl.analysis_batch_streamed

        def selective(model, hists, **kw):
            if any(any(o.f == "write" and o.value == MARK for o in h)
                   for h in hists):
                raise RuntimeError("injected poison launch death")
            return real(model, hists, **kw)

        monkeypatch.setattr(wgl, "analysis_batch_streamed", selective)
        h = seeded_hist(61, 200)
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            cp = fclient.FleetClient(srv.addr, "tbad", "rbad",
                                     model="cas-register")
            cp.send_chunk(poison)
            envp = cp.finish(timeout_s=120)
            cp.close()
            env = stream_run(srv.addr, "tgood", "r", h)
            # host lane: slower, never wrong — and never starved
            assert envp["result"]["valid?"] is True
            assert_verdict_matches_solo(h, env["result"],
                                        solo_verdict(h))
            recs = srv.flightrec.records()
            flightrec.validate_records(recs)
            q = [r for r in recs if r["kind"] == "quarantine"]
            assert [(r["tenant"], r["run"], r["action"])
                    for r in q] == [("tbad", "rbad", "quarantined")]
            stats = srv.stats()
            assert [x["run"] for x
                    in stats["scheduler"]["quarantine"]] == ["rbad"]
            prom = fserver.prometheus_from_stats(stats)
            assert flightrec.validate_prometheus(prom) > 0
            assert "jepsen_fleet_quarantined_runs 1" in prom
            assert ('jepsen_fleet_quarantine_events_total'
                    '{action="quarantined"} 1') in prom
            assert "jepsen_fleet_wal_sheds 0" in prom
            fr = stats["flightrec"]
            assert fr["quarantine"].get("quarantined") == 1
            assert fr["decisions"].get("quarantine", 0) >= 1
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# interpreter hook (core.run integration)
# ---------------------------------------------------------------------------

class TestInterpreterHook:
    def _test_map(self, tmp_path, name, addr):
        state = testing.AtomState()
        test = testing.noop_test()
        import random as _random

        rng = _random.Random(5)

        def one():
            if rng.random() < 0.5:
                return {"f": "read"}
            return {"f": "write", "value": rng.randrange(5)}

        test.update(
            name=name, store_base=str(tmp_path / "store"),
            nodes=["n1", "n2"], concurrency=2,
            client=testing.AtomClient(state, latency_s=0.0002),
            generator=gen.clients(gen.limit(120, one)),
            fleet={"addr": addr, "tenant": "hook",
                   "model": "cas-register", "chunk_ops": 32})
        return test

    def test_live_run_streams_and_attaches_verdict(self, tmp_path):
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            host, port = srv.addr
            t = core.run(self._test_map(tmp_path, "fleet-hook",
                                        f"{host}:{port}"))
            fl = t["results"]["fleet"]
            assert "verdict" in fl, fl
            assert fl["verdict"]["result"]["valid?"] is True
            certify.validate(t["history"],
                             fl["verdict"]["result"]["certificate"])
            assert srv.stats()["tenants"]["hook"]["ops"] == len(
                t["history"])
        finally:
            srv.stop()

    def test_unreachable_fleet_falls_back_honestly(self, tmp_path):
        # a port nothing listens on: the run must complete locally
        # with an honest unavailable marker
        t = core.run(self._test_map(tmp_path, "fleet-fallback",
                                    "127.0.0.1:9"))
        assert t["results"]["valid?"] is not None
        fl = t["results"]["fleet"]
        assert "unavailable" in fl


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestCli:
    def test_submit_and_status(self, tmp_path, capsys):
        from jepsen_tpu import cli as jcli
        from jepsen_tpu.store import format as sformat

        h = seeded_hist(31, 150)
        run_dir = tmp_path / "some-run"
        sformat.write_history(run_dir / "history.jlog", list(h))
        srv = fserver.FleetServer(tmp_path / "fleet").start()
        try:
            host, port = srv.addr
            spec = jcli.fleet_cmd()["fleet"]
            import argparse

            p = spec["parser_fn"](argparse.ArgumentParser())
            opts = p.parse_args(
                ["submit", str(run_dir), "--addr", f"{host}:{port}",
                 "--tenant", "cli-t", "--chunk-ops", "40"])
            assert spec["run"](opts) == 0
            out = json.loads(capsys.readouterr().out)
            assert out["result"]["valid?"] is True
            opts = p.parse_args(
                ["status", "--addr", f"{host}:{port}"])
            assert spec["run"](opts) == 0
            st = json.loads(capsys.readouterr().out)
            assert st["tenants"]["cli-t"]["verdicts"] == 1
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# satellite 2: shared-ledger concurrent-append safety
# ---------------------------------------------------------------------------

class TestSharedLedgerAppends:
    def test_two_writer_ledger_stress(self, tmp_path):
        path = tmp_path / "bench_ledger.jsonl"
        n_per = 200
        errs = []

        def writer(wid):
            try:
                for i in range(n_per):
                    ledger.atomic_append_line(
                        path, json.dumps({"w": wid, "i": i,
                                          "pad": "x" * 200}))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=writer, args=(w,))
              for w in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        # every line parses whole — lines interleave, bytes never do
        lines = path.read_text().splitlines()
        assert len(lines) == 2 * n_per
        seen = {(0, -1), (1, -1)}
        for ln in lines:
            d = json.loads(ln)  # no spliced lines
            assert len(d["pad"]) == 200
        by = {}
        for ln in lines:
            d = json.loads(ln)
            by.setdefault(d["w"], []).append(d["i"])
        for w, idxs in by.items():
            assert idxs == sorted(idxs)  # per-writer order preserved

    def test_two_writer_atlas_stress(self, tmp_path):
        from jepsen_tpu import coverage

        base = tmp_path
        errs = []

        def writer(wid):
            try:
                for i in range(60):
                    entry = {"run": f"r{wid}-{i}", "ts": 1.0,
                             "workload": "register",
                             "digest": f"d{wid}-{i}",
                             "faults": {}, "anomalies": {}}
                    coverage._append_if_new(
                        base / coverage.ATLAS_FILE, {}, entry)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=writer, args=(w,))
              for w in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        entries = coverage.read_atlas(base / coverage.ATLAS_FILE)
        assert len(entries) == 120  # nothing lost, nothing spliced
        assert len(coverage.dedup_entries(entries)) == 120

    def test_ledger_append_entry_single_write(self, tmp_path):
        p = tmp_path / "l.jsonl"
        e = ledger.append_entry(p, {"round": 1, "headline":
                                    {"value": 1.0}, "kernels": {}})
        got = ledger.read_entries(p)
        assert got == [e]


# ---------------------------------------------------------------------------
# satellite 3: lint coverage of the fleet
# ---------------------------------------------------------------------------

class TestFleetLint:
    def test_fleet_modules_concurrency_clean(self):
        from jepsen_tpu import chaos as chaos_mod
        from jepsen_tpu.analysis import concurrency
        from jepsen_tpu.fleet import client as c
        from jepsen_tpu.fleet import scheduler as s
        from jepsen_tpu.fleet import server as srv
        from jepsen_tpu.tpu import ckpt as ckpt_mod
        from jepsen_tpu.tpu import elle as elle_mod

        fs = []
        for mod in (s, srv, c, chaos_mod, flightrec, ckpt_mod,
                    elle_mod):
            fs.extend(concurrency.scan_module(mod))
        assert [(f.rule, f.kernel, f.site) for f in fs] == []

    def test_fleet_modules_in_driver_list(self):
        from jepsen_tpu.analysis import driver

        names = driver.CONCURRENCY_MODULE_NAMES
        assert "jepsen_tpu.fleet.scheduler" in names
        assert "jepsen_tpu.fleet.server" in names
        assert "jepsen_tpu.fleet.flightrec" in names
        assert "jepsen_tpu.tpu.ckpt" in names
        assert "jepsen_tpu.tpu.elle" in names

    def test_wgl_slices_registered_and_traces(self):
        from jepsen_tpu.analysis import registry

        entry = {e.name: e for e in registry.entries()}["wgl-slices"]
        tr = entry.trace(entry.buckets[0])
        assert tr.name == "wgl-slices"
        assert tr.jaxpr is not None
        # R3's donation source: the packed segment tensors stay
        # donated through the fleet entry point's shared jit factory
        donated = {a.name for a in tr.args if a.donated}
        assert "inv_t" in donated
