"""Filesystem-fault layer tests: lazyfs durability faults, charybdefs
EIO injection, faketime clock-rate wrappers — command emission via the
dummy remote (mirror lazyfs.clj, charybdefs.clj, faketime.clj)."""

import pytest

from jepsen_tpu import charybdefs, control, faketime, lazyfs, testing
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import op as mkop


def make_test(responder=None, nodes=("n1", "n2")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return t


def cmds(test, node):
    return [a.cmd for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestLazyfs:
    def test_map_normalization(self):
        lz = lazyfs.lazyfs("/var/lib/db/data")
        assert lz["dir"] == "/var/lib/db/data"
        assert lz["data-dir"] == "/var/lib/db/data.data"
        assert lz["fifo"].endswith(".lazyfs/fifo")
        assert "lazyfs.log" in lz["log-file"]

    def test_config_includes_fifo_and_log(self):
        lz = lazyfs.lazyfs("/data")
        cfg = lazyfs.config(lz)
        assert 'fifo_path="/data.lazyfs/fifo"' in cfg
        assert 'logfile="/data.lazyfs/lazyfs.log"' in cfg

    def test_mount_and_fault_commands(self):
        test = make_test()
        lz = lazyfs.lazyfs("/data")
        with control.with_session(test, "n1"):
            lazyfs.mount(lz)
            lazyfs.lose_unfsynced_writes(lz)
            lazyfs.checkpoint(lz)
            lazyfs.umount(lz)
        got = " ; ".join(cmds(test, "n1"))
        assert "--config-path /data.lazyfs/lazyfs.conf" in got
        assert "subdir=/data.data" in got
        assert "lazyfs::clear-cache > /data.lazyfs/fifo" in got
        assert "lazyfs::cache-checkpoint" in got
        assert "fusermount -u /data" in got

    def test_db_wrapper_kill_loses_unfsynced(self):
        test = make_test()

        class Inner(testing.AtomDB):
            supports_kill = True

            def __init__(self):
                super().__init__(testing.AtomState())
                self.killed = 0

            def kill(self, t, node):
                self.killed += 1
                return "killed"

        inner = Inner()
        db = lazyfs.LazyFSDB("/data", inner)
        assert db.supports_kill
        with control.with_session(test, "n1"):
            out = db.kill(test, "n1")
        assert inner.killed == 1
        got = " ; ".join(cmds(test, "n1"))
        assert "lazyfs::clear-cache" in got

    def test_nemesis_op(self):
        test = make_test()
        nem = lazyfs.nemesis("/data")
        done = nem.invoke(test, mkop(
            type="info", f="lose-unfsynced-writes", value=["n1"]))
        assert done.value == {"n1": "done"}
        assert any("clear-cache" in c for c in cmds(test, "n1"))
        assert not any("clear-cache" in c for c in cmds(test, "n2"))
        assert nem.fs() == {"lose-unfsynced-writes"}


class TestFileCorruptionPackageLazyfs:
    def test_lose_unfsynced_writes_fault(self):
        from jepsen_tpu.nemesis import combined

        test = make_test()
        lz = lazyfs.lazyfs("/data")
        pkg = combined.file_corruption_package({
            "db": testing.AtomDB(testing.AtomState()),
            "faults": {"file-corruption"},
            "file_corruption": {
                "targets": ["all"], "lazyfs": lz,
                "corruptions": [{"type": "lose-unfsynced-writes"}]}})
        assert "lose-unfsynced-writes" in pkg["nemesis"].fs()
        nem = pkg["nemesis"].setup(test)
        done = nem.invoke(test, mkop(
            type="info", f="lose-unfsynced-writes",
            value=["all", None]))
        assert set(done.value) == {"n1", "n2"}
        assert any("clear-cache" in c for c in cmds(test, "n1"))

    def test_requires_lazyfs_map(self):
        from jepsen_tpu.nemesis import combined

        with pytest.raises(ValueError, match="lazyfs"):
            combined.file_corruption_package({
                "db": testing.AtomDB(testing.AtomState()),
                "faults": {"file-corruption"},
                "file_corruption": {
                    "targets": ["all"],
                    "corruptions": [
                        {"type": "lose-unfsynced-writes"}]}})


class TestCharybdefs:
    def test_fault_commands(self):
        test = make_test()
        with control.with_session(test, "n1"):
            charybdefs.break_all()
            charybdefs.break_one_percent()
            charybdefs.clear()
        got = cmds(test, "n1")
        assert any("./recipes --io-error" in c for c in got)
        assert any("./recipes --probability" in c for c in got)
        assert any("./recipes --clear" in c for c in got)

    def test_nemesis(self):
        test = make_test()
        nem = charybdefs.nemesis()
        done = nem.invoke(test, mkop(type="info", f="break-all",
                                     value=None))
        assert set(done.value) == {"n1", "n2"}
        for n in ("n1", "n2"):
            assert any("--io-error" in c for c in cmds(test, n))
        nem.teardown(test)
        assert any("--clear" in c for c in cmds(test, "n1"))
        assert nem.fs() == {"break-all", "break-one-percent",
                            "clear-faults"}


class TestFaketime:
    def test_script(self):
        s = faketime.script("/opt/db/bin.no-faketime", 5, 1.25)
        assert 'faketime -m -f "+5s x1.25"' in s
        assert s.startswith("#!/bin/bash")
        s = faketime.script("/x", -3, 0.5)
        assert '"-3s x0.5"' in s

    def test_wrap_and_unwrap(self):
        state = {"wrapped": False}

        def responder(node, action):
            if action.cmd.startswith("stat "):
                # .no-faketime exists only after wrap
                ok = state["wrapped"] and ".no-faketime" in action.cmd
                return Result(exit=0 if ok else 1, out="", err="",
                              cmd=action.cmd)
            return None

        test = make_test(responder)
        with control.with_session(test, "n1"):
            faketime.wrap("/opt/db/bin", 2, 1.5)
            state["wrapped"] = True
            faketime.unwrap("/opt/db/bin")
        got = cmds(test, "n1")
        assert any(c.startswith("mv /opt/db/bin /opt/db/bin.no-faketime")
                   for c in got)
        assert any("chmod a+x /opt/db/bin" in c for c in got)
        assert any(c.startswith("mv /opt/db/bin.no-faketime /opt/db/bin")
                   for c in got)

    def test_rand_factor_bounds(self):
        import random

        rng = random.Random(3)
        rates = [faketime.rand_factor(2.5, rng) for _ in range(200)]
        assert max(rates) <= 2.5 * min(rates) + 1e-9
        assert all(0 < r < 2 for r in rates)


class TestReviewRegressions:
    def test_package_accepts_bare_dir(self):
        """A bare dir (or partial map) must normalize like every other
        lazyfs entry point (round-3 review finding)."""
        from jepsen_tpu.nemesis import combined

        test = make_test()
        pkg = combined.file_corruption_package({
            "db": testing.AtomDB(testing.AtomState()),
            "faults": {"file-corruption"},
            "file_corruption": {
                "targets": ["all"], "lazyfs": "/data",
                "corruptions": [{"type": "lose-unfsynced-writes"}]}})
        nem = pkg["nemesis"].setup(test)
        done = nem.invoke(test, mkop(
            type="info", f="lose-unfsynced-writes",
            value=["all", None]))
        assert set(done.value) == {"n1", "n2"}
