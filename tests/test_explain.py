"""Explanation artifacts: elle anomaly files + cycle plots and the
linearizability counterexample SVG (reference behavior:
append.clj:17-27 passes :directory to elle; checker.clj:222-229 calls
knossos.linear.report/render-analysis!)."""

from jepsen_tpu import checker
from jepsen_tpu.checker import cycle as cyc, models
from jepsen_tpu.history import History, op
from jepsen_tpu.reports import explain
from jepsen_tpu.tpu import elle


def T(*events):
    return History([op(type=t, process=p, f="txn", value=m)
                    for t, p, m in events])


def _g0_history():
    return T(("invoke", 0, [["append", "x", 1], ["append", "y", 1]]),
             ("invoke", 1, [["append", "x", 2], ["append", "y", 2]]),
             ("ok", 0, [["append", "x", 1], ["append", "y", 1]]),
             ("ok", 1, [["append", "x", 2], ["append", "y", 2]]),
             ("invoke", 2, [["r", "x", None], ["r", "y", None]]),
             ("ok", 2, [["r", "x", [1, 2]], ["r", "y", [2, 1]]]))


def _bad_register_history():
    return History([
        op(type="invoke", process=0, f="write", value=1),
        op(type="ok", process=0, f="write", value=1),
        op(type="invoke", process=1, f="read", value=None),
        op(type="ok", process=1, f="read", value=2),
    ])


class TestElleArtifacts:
    def test_write_artifacts(self, tmp_path):
        res = elle.check_list_append(_g0_history())
        assert res["valid?"] is False
        paths = explain.write_elle_artifacts(tmp_path, res)
        assert paths
        elle_dir = tmp_path / "elle"
        txts = list(elle_dir.glob("*.txt"))
        assert any(p.stem.startswith("G0-") for p in txts), txts
        # cycle plot + dot text for the G0 cycle
        svgs = list(elle_dir.glob("cycle-*.svg"))
        assert svgs
        assert "<svg" in svgs[0].read_text()
        dot = next(iter(elle_dir.glob("cycles-*.dot"))).read_text()
        assert "->" in dot and "digraph" in dot

    def test_valid_result_writes_nothing(self, tmp_path):
        paths = explain.write_elle_artifacts(
            tmp_path, {"valid?": True, "anomalies": {}})
        assert paths == []
        assert not (tmp_path / "elle").exists()

    def test_checker_integration(self, tmp_path):
        c = cyc.append_checker()
        test = {"store_dir": str(tmp_path)}
        res = c.check(test, _g0_history())
        assert res["valid?"] is False
        assert res.get("artifacts")
        assert (tmp_path / "elle").is_dir()
        assert list((tmp_path / "elle").glob("*.txt"))


class TestLinearCounterexample:
    def test_render_svg(self, tmp_path):
        c = checker.linearizable({"model": models.cas_register()})
        res = c.check({}, _bad_register_history())
        assert res["valid?"] is False
        p = explain.render_linear_svg(res, tmp_path / "ce.svg")
        assert p is not None
        body = (tmp_path / "ce.svg").read_text()
        assert "<svg" in body and "unlinearizable" in body

    def test_valid_renders_nothing(self, tmp_path):
        assert explain.render_linear_svg(
            {"valid?": True}, tmp_path / "x.svg") is None
        assert not (tmp_path / "x.svg").exists()

    def test_checker_integration(self, tmp_path):
        c = checker.linearizable({"model": models.cas_register()})
        test = {"store_dir": str(tmp_path)}
        res = c.check(test, _bad_register_history())
        assert res["valid?"] is False
        assert res.get("counterexample-svg")
        svgs = list(tmp_path.glob("linear-counterexample-*.svg"))
        assert svgs and res["counterexample-svg"] == str(svgs[0])
