"""Live-monitor subsystem tests: the streaming log-bucket histogram
(quantile accuracy vs numpy, merge associativity), the sampler's
time-series artifact, the online safety watchdog (seeded violations,
verdict non-interference, early abort), the Chrome-trace exporter, the
/live/ SSE endpoint against an in-progress run, and the hot-loop
throughput floor with the monitor enabled."""

import json
import random
import threading
import time
import urllib.request

import numpy as np
import pytest

from jepsen_tpu import checker as jchecker
from jepsen_tpu import cli, client as jclient, core, interpreter
from jepsen_tpu import generator as gen
from jepsen_tpu import monitor as jmonitor
from jepsen_tpu import store as jstore
from jepsen_tpu import telemetry, testing, util, watchdog
from jepsen_tpu.history import Op
from jepsen_tpu.monitor import LogHistogram
from jepsen_tpu.workloads import register as register_wl


# ---------------------------------------------------------------------------
# LogHistogram
# ---------------------------------------------------------------------------

class TestLogHistogram:
    @pytest.mark.parametrize("name,values", [
        ("uniform", np.random.RandomState(7).uniform(
            1e3, 1e8, 5000)),
        ("lognormal", np.exp(np.random.RandomState(7).normal(
            14, 2, 5000))),
        # adversarial: huge dynamic range, ties, bucket-edge values
        ("adversarial", np.array(
            [1.0] * 500 + [2.0 ** (k / 8) for k in range(0, 400)] * 5
            + [1e12] * 100 + [3.0] * 1000)),
    ])
    def test_quantiles_within_one_bucket_of_numpy(self, name, values):
        h = LogHistogram()
        for v in values:
            h.add(float(v))
        for q in (0.1, 0.5, 0.9, 0.95, 0.99):
            est = h.quantile(q)
            true = float(np.quantile(values, q, method="lower"))
            # "within one bucket": the estimate's bucket is adjacent
            # to (or equal to) the true quantile's bucket
            assert abs(LogHistogram.bucket_of(est)
                       - LogHistogram.bucket_of(true)) <= 1, \
                (name, q, est, true)

    def test_merge_associative_and_commutative(self):
        """Histograms built by concurrent workers must combine to the
        same result regardless of merge order."""
        rng = random.Random(3)
        chunks = [[rng.lognormvariate(12, 3) for _ in range(500)]
                  for _ in range(4)]
        hs = []
        for chunk in chunks:
            h = LogHistogram()
            for v in chunk:
                h.add(v)
            hs.append(h)
        left = hs[0].merge(hs[1]).merge(hs[2]).merge(hs[3])
        right = hs[0].merge(hs[1].merge(hs[2].merge(hs[3])))
        swapped = hs[3].merge(hs[2]).merge(hs[1].merge(hs[0]))
        assert left.counts == right.counts == swapped.counts
        assert left.n == right.n == swapped.n == 2000
        for q in (0.5, 0.99):
            assert left.quantile(q) == right.quantile(q) \
                == swapped.quantile(q)
        # and the merged histogram equals one built from all the data
        whole = LogHistogram()
        for chunk in chunks:
            for v in chunk:
                whole.add(v)
        assert whole.counts == left.counts

    def test_empty_zero_and_edge(self):
        h = LogHistogram()
        assert h.quantile(0.5) is None
        h.add(0)
        h.add(-5)
        assert h.quantile(0.5) == 0.0
        h2 = LogHistogram()
        h2.add(1e6, n=3)
        q = h2.quantile(0.5)
        assert 1e6 / LogHistogram.GROWTH <= q <= 1e6 * LogHistogram.GROWTH

    def test_dict_round_trip_preserves_quantiles(self):
        """to_dict -> JSON -> from_dict is lossless: buckets, zeros,
        and every quantile survive, and n is re-derived from the
        buckets rather than trusted (the flight recorder's
        persistence contract)."""
        rng = random.Random(11)
        h = LogHistogram()
        for _ in range(2000):
            h.add(rng.lognormvariate(10, 3))
        h.add(0, n=7)
        d = json.loads(json.dumps(h.to_dict()))
        h2 = LogHistogram.from_dict(d)
        assert h2.counts == h.counts
        assert h2.zeros == h.zeros == 7
        assert h2.n == h.n == h2.zeros + sum(h2.counts.values())
        for q in (0.1, 0.5, 0.9, 0.95, 0.99):
            assert h2.quantile(q) == h.quantile(q)

    def test_from_dict_tolerates_junk(self):
        """A torn/corrupt snapshot folds as EMPTY, never raises —
        the crash-tolerance contract of flightrec.json."""
        for junk in (None, [], "x", {"counts": "nope"},
                     {"counts": {"a": "b"}}, {"zeros": "many"},
                     {"counts": {"3": -2, "4": 0}}):
            h = LogHistogram.from_dict(junk)
            assert (h.n, h.zeros, h.counts) == (0, 0, {})
        # negative/zero bucket counts are dropped, positives kept
        h = LogHistogram.from_dict({"counts": {"3": 2}, "zeros": -1})
        assert h.zeros == 0 and h.n == 2

    def test_merge_dicts_matches_pairwise_merge(self):
        """merge_dicts folds serialized histograms to the same result
        as pairwise merge in any order — the cross-process fold a
        restarted fleet server (or an external observer) does."""
        rng = random.Random(5)
        hs = []
        for _ in range(4):
            h = LogHistogram()
            for _ in range(300):
                h.add(rng.lognormvariate(12, 2))
            hs.append(h)
        dicts = [h.to_dict() for h in hs]
        folded = LogHistogram.merge_dicts(dicts)
        folded_rev = LogHistogram.merge_dicts(reversed(dicts))
        pair = hs[0].merge(hs[1]).merge(hs[2]).merge(hs[3])
        assert folded.counts == folded_rev.counts == pair.counts
        assert folded.n == folded_rev.n == pair.n == 1200
        for q in (0.5, 0.99):
            assert folded.quantile(q) == pair.quantile(q)

    def test_quantiles_vs_numpy_after_round_trip(self):
        """Serialization cannot cost accuracy: the round-tripped
        histogram stays within one bucket of numpy, same bound as
        the live one."""
        values = np.exp(np.random.RandomState(9).normal(13, 2, 3000))
        h = LogHistogram()
        for v in values:
            h.add(float(v))
        h2 = LogHistogram.from_dict(
            json.loads(json.dumps(h.to_dict())))
        for q in (0.5, 0.95, 0.99):
            est = h2.quantile(q)
            true = float(np.quantile(values, q, method="lower"))
            assert abs(LogHistogram.bucket_of(est)
                       - LogHistogram.bucket_of(true)) <= 1, (q, est)


# ---------------------------------------------------------------------------
# Monitor unit behavior
# ---------------------------------------------------------------------------

class TestMonitor:
    def test_hooks_and_sample_fields(self):
        util.init_relative_time()
        m = jmonitor.Monitor({}, interval_s=99)
        now = util.relative_time_nanos()
        inv = Op(type="invoke", process=0, f="w", time=now)
        m.on_dispatch(inv, 0, now)
        p = m.sample()
        assert p["dispatched"] == 1 and p["completed"] == 0
        assert list(p["inflight"]) == ["0"]
        m.on_complete(inv.copy(type="ok"), 0, now + 2_000_000)
        m.on_stall()
        p2 = m.sample()
        assert p2["completed"] == 1 and p2["inflight"] == {}
        assert p2["ops_s"] is not None and p2["stall_rate"] > 0
        assert p2["latency_ms"]["p50"] == pytest.approx(2.0, rel=0.2)

    def test_nemesis_activity_tracking(self):
        util.init_relative_time()
        m = jmonitor.Monitor({}, interval_s=99)
        t = util.relative_time_nanos()
        inv = Op(type="invoke", process="nemesis", f="start", time=t)
        m.on_dispatch(inv, "nemesis", t)
        start = Op(type="info", process="nemesis", f="start", time=t)
        m.on_complete(start, "nemesis", t + 5_000_000_000)
        p = m.sample()
        assert p["nemesis"] == ["nemesis"]
        # a 5s fault activation is nemesis state, NOT client latency
        # or throughput
        assert p["completed"] == 0 and p["dispatched"] == 0
        assert p["latency_ms"]["p50"] is None
        stop = Op(type="info", process="nemesis", f="stop", time=t)
        m.on_complete(stop, "nemesis", t)
        assert m.sample()["nemesis"] == []

    def test_probe_gauges_flow_into_points(self):
        util.init_relative_time()
        seen = []

        def probe_factory():
            def probe(op, monitor):
                seen.append(op.f)
                monitor.probe_gauge("lag", 42)
            return probe

        m = jmonitor.Monitor({"monitor_probes": [probe_factory]},
                             interval_s=99)
        t = util.relative_time_nanos()
        m.on_complete(Op(type="ok", process=0, f="poll", time=t), 0, t)
        assert seen == ["poll"]
        assert m.sample()["probes"] == {"lag": 42}

    def test_sampler_thread_writes_jsonl(self, tmp_path):
        util.init_relative_time()
        m = jmonitor.Monitor({}, interval_s=0.02)
        out = tmp_path / "timeseries.jsonl"
        m.start(out)
        time.sleep(0.1)
        m.stop()
        pts = list(jmonitor.read_points(out))
        assert len(pts) >= 2
        assert all("t" in p for p in pts)
        # torn trailing line is dropped, like telemetry.read_events
        with open(out, "a") as f:
            f.write('{"t": 12')
        assert len(list(jmonitor.read_points(out))) == len(pts)

    def test_open_spans_visible_in_sample(self):
        util.init_relative_time()
        telemetry.reset()
        m = jmonitor.Monitor({}, interval_s=99)
        with telemetry.span("outer"):
            with telemetry.span("inner"):
                p = m.sample()
        assert p["open_spans"] == ["outer", "inner"]
        assert "open_spans" not in m.sample()


# ---------------------------------------------------------------------------
# Watchdog adapters
# ---------------------------------------------------------------------------

def _ops(*specs):
    """Op stream from (type, f, value) tuples."""
    return [Op(index=i, time=i, type=t, process=0, f=f, value=v)
            for i, (t, f, v) in enumerate(specs)]


class TestWatchdogAdapters:
    def test_register_impossible_read(self):
        wd = watchdog.from_test({"watchdog": ["register"]})
        for op in _ops(("invoke", "write", 1), ("ok", "write", 1),
                       ("invoke", "read", None), ("ok", "read", 1)):
            wd.observe(op)
        assert not wd.tripped
        wd.observe(Op(index=9, time=9, type="ok", process=0,
                      f="read", value=777))
        assert wd.tripped
        assert wd.violations[0]["type"] == "impossible-read"

    def test_register_independent_tuples_and_cas_from(self):
        wd = watchdog.from_test({"watchdog": ["register"]})
        for op in _ops(("invoke", "write", ("k1", 5)),
                       ("ok", "write", ("k1", 5)),
                       ("ok", "read", ("k1", 5)),
                       ("ok", "read", ("k2", None))):
            wd.observe(op)
        assert not wd.tripped
        # a cas claiming to have seen a value nobody attempted on k2
        wd.observe(Op(index=8, time=8, type="ok", process=0, f="cas",
                      value=("k2", [123, 5])))
        assert wd.tripped
        assert wd.violations[0]["type"] == "impossible-cas-from"

    def test_counter_bounds_and_arming(self):
        wd = watchdog.from_test({"watchdog": ["counter"]})
        # unarmed: numeric reads from some other workload are ignored
        wd.observe(Op(type="ok", process=0, f="read", value=50))
        assert not wd.tripped
        for op in _ops(("invoke", "add", 5), ("ok", "add", 5),
                       ("invoke", "add", -2), ("ok", "add", -2),
                       ("ok", "read", 3), ("ok", "read", -2),
                       ("ok", "read", 5)):
            wd.observe(op)
        assert not wd.tripped
        wd.observe(Op(type="ok", process=0, f="read", value=6))
        assert wd.tripped
        assert wd.violations[0]["type"] == "counter-out-of-bounds"

    def test_set_dirty_and_phantom_reads(self):
        wd = watchdog.from_test({"watchdog": ["set"]})
        wd.observe(Op(type="ok", process=0, f="read", value=[9]))
        assert not wd.tripped  # unarmed: no adds seen yet
        for op in _ops(("invoke", "add", 1), ("ok", "add", 1),
                       ("invoke", "add", 2), ("fail", "add", 2),
                       ("ok", "read", [1])):
            wd.observe(op)
        assert not wd.tripped
        wd.observe(Op(type="ok", process=0, f="read", value=[1, 2]))
        assert wd.tripped
        assert wd.violations[0]["type"] == "dirty-read"
        wd2 = watchdog.from_test({"watchdog": ["set"]})
        wd2.observe(Op(type="invoke", process=0, f="add", value=1))
        wd2.observe(Op(type="ok", process=0, f="read", value=[77]))
        assert wd2.violations[0]["type"] == "phantom-read"

    def test_set_retry_interleaving_is_not_dirty(self):
        """A failed add with a retry in flight may legitimately show
        up in a read (the retry applied server-side before its
        completion arrived) — flagging it would be unsound."""
        wd = watchdog.from_test({"watchdog": ["set"]})
        for op in _ops(("invoke", "add", 5), ("fail", "add", 5),
                       ("invoke", "add", 5),  # retry outstanding
                       ("ok", "read", [5])):
            wd.observe(op)
        assert not wd.tripped, wd.violations
        # once the retry also fails, the element's presence IS dirty
        wd.observe(Op(type="fail", process=0, f="add", value=5))
        wd.observe(Op(type="ok", process=0, f="read", value=[5]))
        assert wd.tripped
        assert wd.violations[0]["type"] == "dirty-read"
        # an indeterminate (:info) attempt legitimizes forever
        wd2 = watchdog.from_test({"watchdog": ["set"]})
        for op in _ops(("invoke", "add", 9), ("info", "add", 9),
                       ("ok", "read", [9])):
            wd2.observe(op)
        assert not wd2.tripped

    def test_no_cross_flagging_with_all_adapters(self):
        """A register stream through ALL adapters must stay quiet —
        arming keeps foreign adapters out of ambiguous reads."""
        wd = watchdog.from_test({"watchdog": True})
        for op in _ops(("invoke", "write", 3), ("ok", "write", 3),
                       ("invoke", "read", None), ("ok", "read", 3),
                       ("invoke", "cas", [3, 1]), ("ok", "cas", [3, 1]),
                       ("ok", "read", 1)):
            wd.observe(op)
        assert not wd.tripped, wd.violations

    def test_from_test_spec_shapes(self):
        assert watchdog.from_test({}) is None
        assert watchdog.from_test({"watchdog": False}) is None
        wd = watchdog.from_test({"watchdog": True})
        assert {a.name for a in wd.adapters} == {"register", "counter",
                                                "set"}
        wd = watchdog.from_test({"watchdog": {"adapters": ["set"],
                                              "early_abort": True}})
        assert wd.early_abort and len(wd.adapters) == 1
        with pytest.raises(ValueError):
            watchdog.from_test({"watchdog": ["nope"]})

    def test_violation_raises_telemetry_span_and_counter(self):
        telemetry.reset()
        wd = watchdog.from_test({"watchdog": ["register"]})
        wd.observe(Op(index=0, time=0, type="invoke", process=0,
                      f="write", value=1))
        wd.observe(Op(index=1, time=1, type="ok", process=0,
                      f="read", value=2))
        assert telemetry.get().counters()["watchdog.violations"] == 1
        names = [e["name"] for e in telemetry.get().events()]
        assert "watchdog" in names


# ---------------------------------------------------------------------------
# Pipeline: monitor + watchdog through core.run
# ---------------------------------------------------------------------------

class SeededViolationClient(jclient.Client):
    """Wraps AtomClient, corrupting the Nth read completion to return
    a value no write ever attempted — the seeded mid-run violation."""

    def __init__(self, state, bad_at=10):
        self.inner = testing.AtomClient(state)
        self.bad_at = bad_at
        self.reads = [0]

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        out = self.inner.invoke(test, op)
        if op.f == "read" and out.type == "ok":
            self.reads[0] += 1
            if self.reads[0] == self.bad_at:
                return out.copy(value=999_999)
        return out


def _register_test(tmp_path, name, n=60, **kw):
    state = testing.AtomState()
    rng = random.Random(7)
    t = testing.noop_test()
    t.update(
        name=name, store_base=str(tmp_path), nodes=["n1", "n2"],
        concurrency=4, monitor_interval_s=0.02,
        client=testing.AtomClient(state),
        checker=jchecker.stats(),
        generator=gen.clients(gen.limit(
            n, lambda: register_wl.cas_op_mix(rng, n_values=3))))
    t.update(kw)
    return t


class TestPipeline:
    def test_run_writes_timeseries_artifact(self, tmp_path):
        test = core.run(_register_test(tmp_path, "mon-e2e"))
        assert test["results"]["valid?"] is True
        d = jstore.path(test)
        pts = jstore.load_timeseries(d)
        assert len(pts) >= 1
        last = pts[-1]
        assert last["completed"] == 60 and last["dispatched"] == 60
        assert last["latency_ms"]["p50"] is not None

    def test_watchdog_flags_seeded_violation_without_changing_verdict(
            self, tmp_path):
        state = testing.AtomState()
        test = _register_test(tmp_path, "wd-e2e", n=80,
                              watchdog=["register"])
        test["client"] = SeededViolationClient(state, bad_at=10)
        test = core.run(test)
        res = test["results"]
        # the checkers' verdict is untouched (stats says valid)...
        assert res["valid?"] is True
        # ...while the watchdog reports the seeded violation alongside
        wd = res["watchdog"]
        assert wd["valid?"] is False and wd["count"] >= 1
        assert wd["violations"][0]["type"] == "impossible-read"
        assert wd["violations"][0]["value"] == 999_999
        assert not test.get("aborted")
        # full history: nothing was cut short
        assert len(test["history"]) == 160
        # the violation is in the saved telemetry + final point
        assert test["results"]["telemetry"]["counters"][
            "watchdog.violations"] >= 1

    def test_watchdog_early_abort_stops_the_run(self, tmp_path):
        state = testing.AtomState()
        test = _register_test(tmp_path, "wd-abort", n=2000,
                              watchdog=["register"],
                              early_abort=True)
        test["client"] = SeededViolationClient(state, bad_at=5)
        test = core.run(test)
        assert test["aborted"] == "watchdog"
        # aborted well before the 2000-op budget
        assert len(test["history"]) < 2000
        wd = test["results"]["watchdog"]
        assert wd["tripped"] and wd["aborted"] == "watchdog"

    def test_monitor_graph_rendered_by_perf_checker(self, tmp_path):
        test = _register_test(tmp_path, "mon-graph")
        test["checker"] = jchecker.compose({
            "stats": jchecker.stats(), "perf": jchecker.perf()})
        test = core.run(test)
        assert test["results"]["valid?"] is True
        d = jstore.path(test)
        assert (d / "monitor.png").exists()

    def test_monitor_graph_survives_zero_interval_samples(self, tmp_path):
        """A run that finishes inside the sampler's first interval must
        still render: the case→analyze boundary flush guarantees one
        real-rate point (this was a load-dependent flake before)."""
        test = _register_test(tmp_path, "mon-graph-slow",
                              monitor_interval_s=99)
        test["checker"] = jchecker.compose({
            "stats": jchecker.stats(), "perf": jchecker.perf()})
        test = core.run(test)
        assert test["results"]["valid?"] is True
        d = jstore.path(test)
        pts = jstore.load_timeseries(d)
        assert any(p.get("ops_s") is not None for p in pts)
        assert (d / "monitor.png").exists()

    def test_interpreter_floor_with_monitor_enabled(self):
        """ISSUE-3 acceptance: the hot loop keeps its throughput with
        monitor + watchdog attached. The bound is RELATIVE to a bare
        run measured back-to-back (the CI box throttles by shares, so
        an absolute floor alone flakes when the whole suite is hot —
        both configurations degrade together, the ratio doesn't),
        plus a loose absolute sanity floor."""
        n = 2000

        def one(monitored: bool) -> float:
            t = testing.noop_test()
            t.update(concurrency=10, client=jclient.noop,
                     generator=gen.clients(gen.limit(
                         n, gen.repeat({"f": "write", "value": 1}))))
            if monitored:
                t["monitor"] = jmonitor.Monitor(t, interval_s=0.25)
                t["watchdog"] = watchdog.from_test({"watchdog": True})
                t["monitor"].start()
            util.init_relative_time()
            t0 = time.monotonic()
            t = interpreter.run(dict(t))
            dt = time.monotonic() - t0
            if monitored:
                t["monitor"].stop()
                assert not t["watchdog"].tripped
            assert len(t["history"]) == 2 * n
            return n / dt

        one(True)  # warm
        bare = max(one(False) for _ in range(3))
        rates = []
        for _attempt in range(3):
            rates.append(one(True))
            if rates[-1] > 0.5 * bare:
                break
        best = max(rates)
        assert best > 0.5 * bare and best > 500, \
            (f"monitored {[f'{r:.0f}' for r in rates]} ops/s "
             f"vs bare {bare:.0f}")


# ---------------------------------------------------------------------------
# Chrome-trace export
# ---------------------------------------------------------------------------

class InfoNemesis(testing.jnemesis.Nemesis):
    def invoke(self, test, op):
        return op.copy(type="info")


class TestTraceExport:
    def test_cli_trace_produces_valid_chrome_trace(self, tmp_path,
                                                   capsys):
        test = _register_test(tmp_path, "trace-e2e", n=30)
        test["nemesis"] = InfoNemesis()
        test["generator"] = gen.phases(
            gen.nemesis(gen.limit(2, [{"f": "start"}, {"f": "stop"}])),
            test["generator"])
        test = core.run(test)
        d = jstore.path(test)
        with pytest.raises(SystemExit) as e:
            cli.run_cli(cli.trace_cmd(), ["trace", str(d)])
        assert e.value.code == 0
        out = capsys.readouterr().out
        assert "trace.json" in out
        with open(d / "trace.json") as f:
            doc = json.load(f)  # valid JSON, by construction of load
        assert set(doc) >= {"traceEvents", "displayTimeUnit"}
        evs = doc["traceEvents"]
        for e2 in evs:
            assert {"name", "ph", "pid", "tid"} <= set(e2)
            assert e2["ph"] in ("X", "M")
            if e2["ph"] == "X":
                assert "ts" in e2 and "dur" in e2 and e2["dur"] > 0
        cats = {e2.get("cat") for e2 in evs}
        assert {"span", "op", "nemesis"} <= cats
        # one op slice per client invocation, on per-process tracks
        ops = [e2 for e2 in evs if e2.get("cat") == "op"]
        invokes = [o for o in test["history"] if o.type == "invoke"]
        assert len(ops) == len(invokes)
        assert len({e2["tid"] for e2 in ops}) >= 2  # >1 process track
        # nemesis window: start..stop became one slice
        nem = [e2 for e2 in evs if e2.get("cat") == "nemesis"]
        assert len(nem) == 1
        # spans include the run lifecycle
        span_names = {e2["name"] for e2 in evs
                      if e2.get("cat") == "span"}
        assert {"run", "case", "analyze"} <= span_names

    def test_trace_cmd_missing_run(self, tmp_path, capsys):
        with pytest.raises(SystemExit) as e:
            cli.run_cli(cli.trace_cmd(),
                        ["trace", str(tmp_path / "nope"),
                         "--store", str(tmp_path)])
        assert e.value.code == 254


# ---------------------------------------------------------------------------
# /live/ SSE endpoint
# ---------------------------------------------------------------------------

class TestLiveEndpoint:
    def test_sse_streams_during_in_progress_run(self, tmp_path,
                                                monkeypatch):
        """ISSUE-3 acceptance: /live/ streams ≥1 SSE event while a
        dummy-remote run is still executing."""
        from jepsen_tpu import web

        monkeypatch.setattr(web, "SSE_POLL_S", 0.05)
        server = web.serve("127.0.0.1", 0, base=tmp_path)
        port = server.server_address[1]
        test = _register_test(tmp_path, "live-e2e", n=400)
        # pace the run to ~2s so the client catches it mid-flight
        test["generator"] = gen.clients(gen.time_limit(
            2.0, gen.stagger(0.01, gen.repeat({"f": "read"}))))
        box = {}
        th = threading.Thread(
            target=lambda: box.update(t=core.run(test)), daemon=True)
        try:
            th.start()
            deadline = time.time() + 10
            resp = None
            while resp is None and time.time() < deadline:
                try:
                    resp = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/live/?events=1",
                        timeout=10)
                except urllib.error.HTTPError:
                    time.sleep(0.05)  # run (current link) not up yet
            assert resp is not None, "no /live/ run appeared"
            events = []
            while len(events) < 2:
                line = resp.readline().decode()
                assert line, "SSE stream ended before any event"
                if line.startswith("data: "):
                    events.append(json.loads(line[len("data: "):]))
            resp.close()
            assert th.is_alive() or events  # streamed while running
            assert all("t" in p for p in events)
            # the live page embeds the EventSource wiring
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/live/",
                timeout=5).read().decode()
            assert "EventSource" in page and "ops/s" in page
        finally:
            th.join(timeout=30)
            server.shutdown()
        assert box["t"]["results"]["valid?"] is True

    def test_sse_replays_finished_run_then_ends(self, tmp_path):
        from jepsen_tpu import web

        test = core.run(_register_test(tmp_path, "live-replay"))
        d = jstore.path(test)
        rel = f"live-replay/{d.name}"
        server = web.serve("127.0.0.1", 0, base=tmp_path)
        port = server.server_address[1]
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/live/{rel}?events=1",
                timeout=10)
            n = 0
            saw_end = False
            deadline = time.time() + 10
            while time.time() < deadline:
                line = resp.readline().decode()
                if line.startswith("data: "):
                    n += 1
                if line.startswith("event: end"):
                    saw_end = True
                    break
            assert n >= 1 and saw_end
            # run dirs link their rendered views
            listing = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/files/{rel}/",
                timeout=5).read().decode()
            assert f"/live/{rel}" in listing
            assert f"/telemetry/{rel}" in listing
        finally:
            server.shutdown()

    def test_live_404_on_unknown_run(self, tmp_path):
        import urllib.error

        from jepsen_tpu import web

        server = web.serve("127.0.0.1", 0, base=tmp_path)
        port = server.server_address[1]
        try:
            with pytest.raises(urllib.error.HTTPError) as he:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/live/nope/run",
                    timeout=5)
            assert he.value.code == 404
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# Kafka realtime lag
# ---------------------------------------------------------------------------

class TestKafkaLag:
    def test_checker_emits_lag_stats_tail(self):
        from jepsen_tpu.workloads import kafka

        ms = 1_000_000
        ops = [
            # p0 sends v1..v3 to key 0 at t=1,2,3
            dict(index=0, time=0 * ms, type="invoke", process=0,
                 f="send", value=[["send", 0, 1]]),
            dict(index=1, time=1 * ms, type="ok", process=0,
                 f="send", value=[["send", 0, [0, 1]]]),
            dict(index=2, time=1 * ms, type="invoke", process=0,
                 f="send", value=[["send", 0, 2]]),
            dict(index=3, time=2 * ms, type="ok", process=0,
                 f="send", value=[["send", 0, [1, 2]]]),
            # p1 polls only v1 at t=50 -> lagging behind v2 (acked t=2)
            dict(index=4, time=3 * ms, type="invoke", process=1,
                 f="poll", value=[["poll"]]),
            dict(index=5, time=50 * ms, type="ok", process=1,
                 f="poll", value=[["poll", {0: [[0, 1]]}]]),
            # then catches up at t=60
            dict(index=6, time=51 * ms, type="invoke", process=1,
                 f="poll", value=[["poll"]]),
            dict(index=7, time=60 * ms, type="ok", process=1,
                 f="poll", value=[["poll", {0: [[1, 2]]}]]),
        ]
        res = kafka.check(ops)
        lag = res["realtime-lag"]
        # at t=50 the oldest unpolled acked message (v2, acked t=2)
        # was 48ms old; after the catch-up poll the lag is 0
        assert lag["max-lag-ms"] == pytest.approx(48.0)
        assert lag["worst-realtime-lag"]["process"] == 1
        assert lag["worst-realtime-lag"]["key"] == 0
        assert lag["final-lags-ms"] == {"1:0": 0.0}
        assert lag["unseen-at-end"] == {}

    def test_unseen_at_end_reported(self):
        from jepsen_tpu.workloads import kafka

        ops = [
            dict(index=0, time=0, type="invoke", process=0, f="send",
                 value=[["send", 0, 1]]),
            dict(index=1, time=1, type="ok", process=0, f="send",
                 value=[["send", 0, [0, 1]]]),
        ]
        res = kafka.check(ops)
        assert res["realtime-lag"]["unseen-at-end"] == {0: 1}
        assert res["realtime-lag"]["max-lag-ms"] == 0.0

    def test_lag_probe_streams_into_monitor(self):
        from jepsen_tpu.workloads import kafka

        util.init_relative_time()
        m = jmonitor.Monitor({"monitor_probes": [kafka.lag_probe]},
                             interval_s=99)
        ms = 1_000_000
        send = Op(type="ok", process=0, f="send", time=2 * ms,
                  value=[["send", 0, [0, "a"]], ["send", 0, [1, "b"]]])
        m.on_complete(send, 0, 2 * ms)
        poll = Op(type="ok", process=1, f="poll", time=30 * ms,
                  value=[["poll", {0: [[0, "a"]]}]])
        m.on_complete(poll, 1, 30 * ms)
        p = m.sample()
        # offset 1 ("b", acked t=2ms) still unpolled at t=30ms
        assert p["probes"]["kafka.realtime-lag-ms"] == pytest.approx(
            28.0)
        caught_up = Op(type="ok", process=1, f="poll", time=40 * ms,
                       value=[["poll", {0: [[1, "b"]]}]])
        m.on_complete(caught_up, 1, 40 * ms)
        assert m.sample()["probes"]["kafka.realtime-lag-ms"] == 0.0

    def test_kafka_workload_declares_probe(self):
        from jepsen_tpu.workloads import kafka

        w = kafka.workload({"ops": 10})
        assert w["monitor_probes"] == [kafka.lag_probe]
