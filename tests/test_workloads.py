"""Independent key-space machinery + workload bundles, end to end:
every workload runs through the full lifecycle against an in-memory
client and must validate; broken clients must be caught.

Mirrors the reference's approach: generator semantics via deterministic
simulation (generator/test.clj), checker verdicts via real runs against
atom-backed stores (core_test.clj)."""

import pytest

from jepsen_tpu import checker as chk
from jepsen_tpu import core, independent, testing, workloads
from jepsen_tpu import generator as gen
from jepsen_tpu.checker import models
from jepsen_tpu.generator import test_support as sim
from jepsen_tpu.history import History, op


def run_clusterless(client, workload, concurrency=6, nodes=1):
    test = testing.noop_test()
    test.update(nodes=[f"n{i}" for i in range(nodes or 1)],
                concurrency=concurrency, client=client,
                checker=workload["checker"],
                generator=gen.clients(workload["generator"]))
    for k, v in workload.items():
        if k not in ("generator", "checker"):
            test[k] = v
    return core.run(test)


class TestIndependent:
    def test_tuple_helpers(self):
        t = independent.ktuple("x", 5)
        assert independent.key_(t) == "x"
        assert independent.value_(t) == 5
        assert independent.value_(7) == 7

    def test_sequential_generator_simulation(self):
        g = independent.sequential_generator(
            ["a", "b"], lambda k: gen.limit(4, lambda: {"f": "read"}))
        ops = sim.quick(gen.clients(g), sim.n_plus_nemesis_context(2))
        invokes = [o for o in ops if o.type == "invoke"]
        assert len(invokes) == 8
        keys = [o.value[0] for o in invokes]
        assert keys == ["a"] * 4 + ["b"] * 4

    def test_concurrent_generator_simulation(self):
        g = independent.concurrent_generator(
            2, list(range(4)),
            lambda k: gen.limit(6, lambda: {"f": "read"}))
        ops = sim.quick(gen.clients(g), sim.n_plus_nemesis_context(4))
        invokes = [o for o in ops if o.type == "invoke"]
        assert len(invokes) == 24
        # every key gets exactly its 6 ops
        from collections import Counter
        counts = Counter(o.value[0] for o in invokes)
        assert counts == {0: 6, 1: 6, 2: 6, 3: 6}

    def test_subhistories(self):
        hist = History([
            op(type="invoke", process=0, f="read", value=("a", None)),
            op(type="ok", process=0, f="read", value=("a", 1)),
            op(type="invoke", process=1, f="write", value=("b", 2)),
            op(type="ok", process=1, f="write", value=("b", 2))])
        subs = independent.subhistories(hist)
        assert set(subs) == {"a", "b"}
        assert subs["a"][1].value == 1

    def test_independent_checker_batched(self):
        """Per-key histories checked in one device launch via
        Linearizable.check_batch."""
        hist = History([
            op(type="invoke", process=0, f="write", value=("k1", 1)),
            op(type="ok", process=0, f="write", value=("k1", 1)),
            op(type="invoke", process=1, f="read", value=("k1", None)),
            op(type="ok", process=1, f="read", value=("k1", 1)),
            op(type="invoke", process=2, f="write", value=("k2", 3)),
            op(type="ok", process=2, f="write", value=("k2", 3)),
            op(type="invoke", process=3, f="read", value=("k2", None)),
            op(type="ok", process=3, f="read", value=("k2", 9))])  # bad
        c = independent.checker(chk.linearizable(
            {"model": models.cas_register()}))
        res = c.check({}, hist)
        assert res["valid?"] is False
        assert res["failures"] == ["k2"]
        assert res["results"]["k1"]["valid?"] is True


class TestWorkloadsEndToEnd:
    def test_register(self):
        w = workloads.register.workload(
            {"keys": [0, 1], "group_size": 3, "ops_per_key": 40,
             "seed": 5})
        t = run_clusterless(testing.KVClient(testing.KVState()), w,
                            concurrency=6)
        assert t["results"]["valid?"] is True, t["results"]

    def test_bank_valid(self):
        w = workloads.bank.workload({"seed": 1, "ops": 120})
        state = testing.BankState(w["accounts"], initial=10)
        t = run_clusterless(testing.BankClient(state), w)
        assert t["results"]["valid?"] is True, t["results"]["bank"]

    def test_bank_catches_total_violation(self):
        w = workloads.bank.workload({"seed": 2, "ops": 120})
        state = testing.BankState(w["accounts"], initial=11)  # wrong total
        t = run_clusterless(testing.BankClient(state), w)
        assert t["results"]["valid?"] is False

    def test_set_valid_and_lossy(self):
        w = workloads.sets.workload({"ops": 60})
        t = run_clusterless(testing.SetClient(), w)
        assert t["results"]["valid?"] is True, t["results"]

        w = workloads.sets.workload({"ops": 60})
        t = run_clusterless(testing.SetClient(drop_every=10), w)
        assert t["results"]["valid?"] is False
        assert t["results"]["lost-count"] > 0

    def test_set_full(self):
        w = workloads.sets.full_workload({"ops": 80})
        t = run_clusterless(testing.SetClient(), w)
        assert t["results"]["valid?"] in (True, "unknown")

    def test_queue_valid_and_lossy(self):
        w = workloads.queue.workload({"ops": 60})
        t = run_clusterless(testing.QueueClient(), w)
        assert t["results"]["valid?"] is True, t["results"]

        w = workloads.queue.workload({"ops": 60})
        t = run_clusterless(testing.QueueClient(drop_every=7), w)
        assert t["results"]["valid?"] is False

    def test_counter(self):
        w = workloads.counter.workload({"ops": 80, "seed": 3})
        t = run_clusterless(testing.CounterClient(), w)
        assert t["results"]["valid?"] is True, t["results"]

    def test_unique_ids_valid_and_dup(self):
        w = workloads.unique_ids.workload({"ops": 50})
        t = run_clusterless(testing.UniqueIdsClient(), w)
        assert t["results"]["valid?"] is True

        w = workloads.unique_ids.workload({"ops": 50})
        t = run_clusterless(testing.UniqueIdsClient(dup_every=9), w)
        assert t["results"]["valid?"] is False

    def test_long_fork_valid(self):
        w = workloads.long_fork.workload({"ops": 120})
        t = run_clusterless(testing.TxnClient(), w)
        assert t["results"]["valid?"] is True, t["results"]

    def test_txn_append(self):
        w = workloads.txn_append.workload({"ops": 150, "seed": 9})
        t = run_clusterless(testing.TxnClient(), w)
        assert t["results"]["valid?"] is True, t["results"]

    def test_txn_wr(self):
        w = workloads.txn_wr.workload({"ops": 150, "seed": 9})
        t = run_clusterless(testing.TxnClient(), w)
        assert t["results"]["valid?"] is True, t["results"]

    def test_registry_complete(self):
        # Core workload families must stay registered; new families may be
        # added freely (assert subset, not equality, so registrations don't
        # silently break the suite).
        core = {
            "adya-g2", "bank", "causal", "causal-reverse", "counter", "dirty-read",
            "kafka", "long-fork", "monotonic", "sequential", "queue", "register", "set",
            "set-full", "append", "wr", "unique-ids",
            "lock", "fenced-lock", "owner-lock", "reentrant-lock", "semaphore",
            "upsert", "run-coverage", "pages", "multimonotonic", "lost-updates",
            "version-divergence"}
        assert core <= set(workloads.REGISTRY), core - set(workloads.REGISTRY)
        # Every registered workload must build a test map with a generator
        # and a checker from default-ish opts.
        for name, fn in workloads.REGISTRY.items():
            w = fn({"ops": 10})
            assert "generator" in w and "checker" in w, name


class TestBankCheckFast:
    def _hist(self, rows, f="read"):
        from jepsen_tpu.history import History, op
        evs = []
        for i, r in enumerate(rows):
            evs.append(op(type="invoke", process=0, f=f, value=None))
            evs.append(op(type="ok", process=0, f=f, value=r))
        return History(evs)

    def test_fold_path_valid_and_anomalies(self):
        from jepsen_tpu.workloads import bank
        h = self._hist([{0: 5, 1: 5}, {0: 4, 1: 6}])
        assert bank.check_fast(h, 10)["valid?"] is True
        bad = self._hist([{0: 5, 1: 5}, {0: 4, 1: 4}])
        res = bank.check_fast(bad, 10)
        assert res["valid?"] is False
        assert res["first-error"]["type"] == "wrong-total"
        neg = self._hist([{0: -2, 1: 12}])
        res = bank.check_fast(neg, 10)
        assert res["valid?"] is False
        assert res["first-error"]["type"] == "negative-value"
        assert bank.check_fast(neg, 10, negative_ok=True)["valid?"] is True

    def test_matrix_path_matches_fold(self):
        from jepsen_tpu.workloads import bank
        import random
        rng = random.Random(5)
        n_acc = 16  # wide: takes the matrix path
        rows = []
        for _ in range(50):
            vals = [10] * n_acc
            for _ in range(8):
                a, b = rng.sample(range(n_acc), 2)
                amt = rng.randint(1, 5)
                vals[a] -= amt
                vals[b] += amt
            rows.append(dict(enumerate(vals)))
        h = self._hist(rows)
        res = bank.check_fast(h, n_acc * 10, device=False)
        assert res["valid?"] is False  # negatives occur
        assert bank.check_fast(h, n_acc * 10, negative_ok=True,
                               device=False)["valid?"] is True

    def test_empty_is_unknown(self):
        from jepsen_tpu.workloads import bank
        from jepsen_tpu.history import History
        assert bank.check_fast(History([]), 10)["valid?"] == "unknown"


class TestSynthGenerators:
    def test_list_append_history_valid(self):
        from jepsen_tpu.tpu import elle, synth
        h = synth.list_append_history(800, seed=5)
        for engine in ("host", "device"):
            res = elle.check_list_append(h, {"engine": engine})
            assert res["valid?"] is True, (engine, res["anomaly-types"])

    def test_bank_history_valid(self):
        from jepsen_tpu.tpu import synth
        from jepsen_tpu.workloads import bank
        h = synth.bank_history(800, seed=5)
        assert bank.check_fast(h, 80)["valid?"] is True

    def test_register_history_with_crashes_valid(self):
        from jepsen_tpu.checker import models
        from jepsen_tpu.tpu import synth, wgl
        h = synth.register_history(150, n_procs=4, seed=9, crash_p=0.15)
        a = wgl.analysis(models.cas_register(), h, algorithm="wgl")
        assert a["valid?"] is True, a


class TestSetFullVectorized:
    """The array path must agree with the object path exactly
    (VERDICT r2 weak #6: O(reads x elements) Python loops)."""

    @staticmethod
    def _hist(n_adds, n_reads, lose=(), dup_read=False, seed=0,
              str_values=False):
        import random

        from jepsen_tpu.history import History, op

        rng = random.Random(seed)
        evs = []
        present = []
        idx = 0
        t = 0
        rp = sorted(rng.sample(range(1, n_adds),
                               min(n_reads, n_adds - 1)))

        def val(i):
            return f"e{i}" if str_values else i

        for i in range(n_adds):
            t += 10
            evs.append(op(index=idx, time=t, type="invoke",
                          process=i % 5, f="add", value=val(i)))
            idx += 1
            ok = rng.random() < 0.95
            t += 5
            evs.append(op(index=idx, time=t,
                          type="ok" if ok else "fail",
                          process=i % 5, f="add", value=val(i)))
            idx += 1
            if ok and i not in lose:
                present.append(val(i))
            if rp and i == rp[0]:
                rp.pop(0)
                t += 3
                evs.append(op(index=idx, time=t, type="invoke",
                              process=9, f="read", value=None))
                idx += 1
                t += 3
                vals = list(present)
                if dup_read and vals:
                    vals.append(vals[0])
                evs.append(op(index=idx, time=t, type="ok", process=9,
                              f="read", value=vals))
                idx += 1
        t += 3
        evs.append(op(index=idx, time=t, type="invoke", process=9,
                      f="read", value=None))
        idx += 1
        t += 3
        evs.append(op(index=idx, time=t, type="ok", process=9,
                      f="read", value=list(present)))
        idx += 1
        return History(evs, assign_indices=False)

    def _differential(self, hist):
        from jepsen_tpu import checker as chk

        fast = chk._set_full_results_fast(hist)
        assert fast is not None
        f_rs, f_dups = fast
        s_rs, s_dups = chk._set_full_results_slow(hist)
        assert f_dups == s_dups
        assert len(f_rs) == len(s_rs)
        for a, b in zip(f_rs, s_rs):
            for k in ("element", "outcome", "stable-latency",
                      "lost-latency"):
                assert a[k] == b[k], (a, b)

    def test_clean(self):
        self._differential(self._hist(200, 10, seed=1))

    def test_lost_elements(self):
        self._differential(self._hist(200, 10, lose={50, 51}, seed=2))

    def test_duplicates(self):
        self._differential(self._hist(100, 5, dup_read=True, seed=3))

    def test_no_reads(self):
        self._differential(self._hist(50, 0, seed=4))

    def test_non_int_values_fall_back(self):
        from jepsen_tpu import checker as chk

        hist = self._hist(30, 3, seed=5, str_values=True)
        assert chk._set_full_results_fast(hist) is None
        out = chk.check(chk.set_full(), {}, hist)  # slow path still works
        assert out["valid?"] is True, out

    def test_scale_smoke(self):
        """200k-op history checks in well under the old quadratic
        regime (the 1M-op target is ~5s, measured out-of-band)."""
        import time

        from jepsen_tpu import checker as chk

        hist = self._hist(100_000, 40, lose={777}, seed=6)
        t0 = time.time()
        out = chk.check(chk.set_full(), {}, hist)
        dt = time.time() - t0
        assert out["valid?"] is False
        assert out["lost"] == [777]
        assert dt < 20, f"set-full took {dt:.1f}s on 200k ops"


class TestSetFullEdgeCases:
    def test_adds_but_no_reads_at_all(self):
        """E>0, R==0 must report never-read, not crash (round-3 review
        finding)."""
        from jepsen_tpu import checker as chk
        from jepsen_tpu.history import History, op

        hist = History([
            op(index=0, time=1, type="invoke", process=0, f="add",
               value=1),
            op(index=1, time=2, type="ok", process=0, f="add",
               value=1)], assign_indices=False)
        fast = chk._set_full_results_fast(hist)
        assert fast is not None
        rs, dups = fast
        assert [r["outcome"] for r in rs] == ["never-read"]
        out = chk.check(chk.set_full(), {}, hist)
        assert out["valid?"] == "unknown"

    def test_known_and_last_absent_are_ops(self):
        """Row fields carry the same Op objects as the object path:
        known by read completion when the add never ok'd, last-absent
        as the read invocation (round-3 review finding)."""
        from jepsen_tpu import checker as chk
        from jepsen_tpu.history import History, op

        evs = [
            op(index=0, time=1, type="invoke", process=0, f="add",
               value=7),
            op(index=1, time=2, type="info", process=0, f="add",
               value=7),                                  # never ok'd
            op(index=2, time=3, type="invoke", process=1, f="read",
               value=None),
            op(index=3, time=4, type="ok", process=1, f="read",
               value=[7]),                                # ...but seen
            op(index=4, time=5, type="invoke", process=1, f="read",
               value=None),
            op(index=5, time=6, type="ok", process=1, f="read",
               value=[]),                                 # then gone
        ]
        hist = History(evs, assign_indices=False)
        f_rs, _ = chk._set_full_results_fast(hist)
        s_rs, _ = chk._set_full_results_slow(hist)
        for a, b in zip(f_rs, s_rs):
            assert a["outcome"] == b["outcome"] == "lost"
            assert a["known"] is b["known"]          # the read's ok op
            assert a["last-absent"] is b["last-absent"]


class TestMonotonic:
    """cockroach monotonic.clj equivalents."""

    def _run(self, client, ops=120, concurrency=4):
        from jepsen_tpu import workloads

        w = workloads.monotonic.workload({"ops": ops})
        test = testing.noop_test()
        test.update(nodes=["n1", "n2"], concurrency=concurrency,
                    client=client, checker=w["checker"],
                    generator=gen.clients(gen.phases(
                        gen.stagger(0.0003, w["generator"]),
                        w["final_generator"])))
        return core.run(test)

    def test_healthy_run_valid(self):
        test = self._run(testing.MonotonicClient())
        res = test["results"]
        assert res["valid?"] is True
        assert res["add-count"] > 50 and res["read-count"] > 50
        assert not res["lost"] and not res["duplicates"]

    def test_clock_skew_detected(self):
        # reads sort by sts, so a backwards clock can never violate the
        # (non-strict, ties legal) sts order; it surfaces as values out
        # of order relative to timestamps — monotonic.clj semantics
        test = self._run(testing.MonotonicClient(skew_every=10))
        res = test["results"]
        assert res["valid?"] is False
        assert res["value-reorders"]
        assert not res["order-by-errors"]

    def test_duplicate_insert_detected(self):
        test = self._run(testing.MonotonicClient(dup_every=15))
        res = test["results"]
        assert res["valid?"] is False
        assert res["duplicates"]

    def test_never_read_is_unknown(self):
        from jepsen_tpu import workloads

        w = workloads.monotonic.workload({"ops": 20})
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=2,
                    client=testing.MonotonicClient(),
                    checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0003, w["generator"])))
        test = core.run(test)
        assert test["results"]["valid?"] == "unknown"


class TestSequential:
    """cockroach sequential.clj equivalents."""

    def _run(self, client, ops=200, concurrency=6):
        from jepsen_tpu import workloads

        w = workloads.sequential.workload({"ops": ops, "writers": 3,
                                           "seed": 11})
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=concurrency,
                    client=client, key_count=w["key_count"],
                    checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0003, w["generator"])))
        return core.run(test)

    def test_healthy_run_valid(self):
        test = self._run(testing.SequentialClient())
        res = test["results"]
        assert res["valid?"] is True
        assert res["bad-count"] == 0
        assert res["all-count"] + res["some-count"] + \
            res["none-count"] > 0
        reads = [op for op in test["history"]
                 if op.type == "ok" and op.f == "read"]
        assert reads

    def test_trailing_none_detected(self):
        """Writers that skip a key's first subkey leave later subkeys
        visible without it: sequential consistency violation."""
        test = self._run(
            testing.SequentialClient(hide_first_every=2), ops=300)
        res = test["results"]
        assert res["valid?"] is False
        assert res["bad-count"] > 0

    def test_subkeys_order(self):
        from jepsen_tpu.workloads import sequential as seq

        assert seq.subkeys(3, 7) == ["7_0", "7_1", "7_2"]
        assert seq._trailing_none(["7_2", None]) is True
        assert seq._trailing_none([None, "7_1"]) is False
        assert seq._trailing_none([None, None]) is False

    def test_store_roundtrip_preserves_reads(self, tmp_path,
                                             monkeypatch):
        """A NAMED test round-trips its history through the JSON store
        log (tuples become lists); the checker must still see the
        reads (regression: valid? was 'unknown' from the CLI)."""
        import jepsen_tpu.store as store_mod
        from jepsen_tpu import workloads

        monkeypatch.setattr(store_mod, "BASE", tmp_path / "store")
        w = workloads.sequential.workload({"ops": 100, "writers": 2,
                                           "seed": 3})
        test = testing.noop_test()
        test.update(name="seq-store", nodes=["n1"], concurrency=4,
                    client=testing.SequentialClient(),
                    key_count=w["key_count"], checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0003, w["generator"])))
        test = core.run(test)
        res = test["results"]
        assert res["valid?"] is True
        assert res["all-count"] + res["some-count"] + \
            res["none-count"] > 0


class TestDirtyRead:
    """elasticsearch dirty_read.clj equivalents."""

    def _run(self, client, ops=200, concurrency=6):
        from jepsen_tpu import workloads

        w = workloads.dirty_read.workload(
            {"ops": ops, "concurrency": concurrency, "seed": 5})
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"], concurrency=concurrency,
                    client=client, checker=w["checker"],
                    generator=gen.clients(gen.phases(
                        gen.stagger(0.0003, w["generator"]),
                        w["final_generator"])))
        return core.run(test)

    def test_healthy_run_valid(self):
        test = self._run(testing.DirtyReadClient())
        res = test["results"]
        assert res["valid?"] is True
        assert res["read-count"] > 0
        assert res["strong-read-count"] == 6
        assert res["dirty-count"] == 0 and res["lost-count"] == 0

    def test_dirty_read_detected(self):
        """Visible-but-never-committed writes observed by readers must
        surface as dirty."""
        test = self._run(testing.DirtyReadClient(dirty_every=3),
                         ops=400)
        res = test["results"]
        assert res["valid?"] is False
        assert res["dirty-count"] > 0

    def test_lost_write_detected(self):
        test = self._run(testing.DirtyReadClient(lose_every=4),
                         ops=300)
        res = test["results"]
        assert res["valid?"] is False
        assert res["lost-count"] > 0

    def test_no_strong_reads_is_unknown(self):
        from jepsen_tpu import workloads

        w = workloads.dirty_read.workload({"ops": 30,
                                           "concurrency": 3})
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=3,
                    client=testing.DirtyReadClient(),
                    checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0003, w["generator"])))
        test = core.run(test)
        assert test["results"]["valid?"] == "unknown"
