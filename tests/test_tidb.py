"""TiDB suite tests: pd/tikv/tidb bootstrap command emission via the
dummy remote, an in-memory tidb speaking the suite's SQL batches, and
clusterless end-to-end append/bank/long-fork runs (mirrors
tidb/src/tidb/*.clj)."""

import re
import threading

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.suites import tidb as td


def responder(node, action):
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "tidb-v7.5.1-linux-amd64"
    return None


def make_test(nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return core.prepare_test(t)


class TestDB:
    def test_daemon_stack_and_schema(self):
        test = make_test()
        db = td.TidbDB()
        control.on_nodes(test, lambda t, n: db.setup(t, n))
        got1 = " ; ".join(a.cmd for a in test["sessions"]["n1"].log
                          if isinstance(a, Action))
        got2 = " ; ".join(a.cmd for a in test["sessions"]["n2"].log
                          if isinstance(a, Action))
        for got in (got1, got2):
            assert "pd-server" in got and "tikv-server" in got \
                and "tidb-server" in got
            assert ("--initial-cluster pd-n1=http://n1:2380,"
                    "pd-n2=http://n2:2380") in got
            assert "--pd n1:2379,n2:2379,n3:2379" in got
            assert "--store tikv" in got
            assert "mariadb-client" in got
        # pd starts before tikv, tikv before tidb
        assert got1.index("pd-server") < got1.index("tikv-server") \
            < got1.index("tidb-server")
        # schema once, on the primary
        assert "CREATE DATABASE IF NOT EXISTS jepsen" in got1
        assert "CREATE DATABASE" not in got2


class FakeTidb:
    """In-memory store executing the suite's SQL batches atomically —
    a perfectly serializable 'tidb'."""

    def __init__(self):
        self.lock = threading.Lock()
        self.tables = {f"txn{i}": {} for i in range(td.TABLE_COUNT)}
        self.lf: dict = {}
        self.accounts = {i: 10 for i in range(8)}

    def run(self, sql: str) -> str:
        with self.lock:
            out = []
            for stmt in filter(None,
                               (s.strip() for s in sql.split(";"))):
                line = self._stmt(stmt)
                if line is not None:
                    out.append(line)
            return "\n".join(out)

    def _stmt(self, s):
        if s in ("BEGIN", "COMMIT"):
            return None
        m = re.match(r"SELECT CONCAT\('m(\d+)=', COALESCE\("
                     r"\(SELECT val FROM (txn\d+|lf) WHERE "
                     r"(?:id|k) = (\d+)\), '~'\)\)", s)
        if m:
            i, t, k = m.group(1), m.group(2), int(m.group(3))
            store = self.lf if t == "lf" else self.tables[t]
            v = store.get(k)
            return f"m{i}=" + ("~" if v is None else str(v))
        m = re.match(r"INSERT INTO (txn\d+) \(id, val\) VALUES "
                     r"\((\d+), '(\d+)'\) ON DUPLICATE KEY", s)
        if m:
            t, k, v = m.group(1), int(m.group(2)), m.group(3)
            cur = self.tables[t].get(k)
            self.tables[t][k] = v if cur is None else f"{cur},{v}"
            return None
        m = re.match(r"INSERT INTO lf \(k, val\) VALUES "
                     r"\((\d+), (\d+)\)", s)
        if m:
            self.lf[int(m.group(1))] = int(m.group(2))
            return None
        if "CONCAT('b='" in s:
            return "b=" + ",".join(f"{i}:{b}" for i, b in
                                   sorted(self.accounts.items()))
        raise AssertionError(f"fake tidb can't parse: {s!r}")


class FakeSqlFactory:
    def __init__(self, state=None):
        self.state = state or FakeTidb()

    def __call__(self, test, node, timeout=10.0):
        factory = self

        class _S:
            def run(self, sql):
                return factory.state.run(sql)

            def close(self):
                pass

        return _S()


def run_workload(workload_fn, opts, factory, extra_test=None):
    w = workload_fn(opts)
    w["client"].sql_factory = factory
    main = gen.clients(
        gen.stagger(0.0004, gen.limit(
            opts.get("gen_ops", 250), w["generator"])))
    final = w.get("final_generator")
    g = main if final is None else gen.phases(
        main, gen.clients(final))
    test = testing.noop_test()
    test.update(nodes=["n1", "n2"],
                concurrency=opts.get("concurrency", 6),
                client=w["client"], checker=w["checker"],
                generator=g)
    if w.get("lf-table"):
        test["lf-table"] = True
    test.update(extra_test or {})
    return core.run(test)


class TestEndToEnd:
    def test_append_valid(self):
        test = run_workload(td.append_workload,
                            {"ops": 250, "keys": 5, "seed": 3},
                            FakeSqlFactory())
        assert test["results"]["valid?"] is True

    def test_append_detects_reversed_read(self):
        class Corrupt(FakeTidb):
            def __init__(self):
                super().__init__()
                self.n = 0

            def _stmt(self, s):
                out = super()._stmt(s)
                if out and out.startswith("m") and "," in out:
                    self.n += 1
                    if self.n % 2:
                        tag, raw = out.split("=", 1)
                        out = tag + "=" + ",".join(
                            reversed(raw.split(",")))
                return out

        test = run_workload(td.append_workload,
                            {"ops": 300, "keys": 2, "seed": 13},
                            FakeSqlFactory(Corrupt()))
        assert test["results"]["valid?"] is False

    def test_long_fork_valid(self):
        test = run_workload(td.long_fork_workload,
                            {"ops": 300}, FakeSqlFactory())
        assert test["results"]["valid?"] is True
        # reads actually observed written values
        seen = [m[2] for op in test["history"]
                if op.type == "ok" and op.f == "txn"
                for m in op.value if m[0] == "r" and m[2] is not None]
        assert seen and all(v == 1 for v in seen)


class TestBank:
    def _factory(self):
        class BankFake(FakeTidb):
            def __init__(self):
                super().__init__()
                self._applied = False

            def _stmt(self, s):
                if s.startswith("SELECT balance INTO @b1"):
                    self._b1_from = int(
                        re.search(r"id = (\d+)", s).group(1))
                    self._b1 = self.accounts[self._b1_from]
                    return None
                m = re.match(r"UPDATE accounts SET balance = balance "
                             r"([-+]) (\d+) WHERE id = (\d+)", s)
                if m:
                    sign, a, acct = (m.group(1), int(m.group(2)),
                                     int(m.group(3)))
                    self._applied = self._b1 >= a
                    if self._applied:
                        self.accounts[acct] += a if sign == "+" else -a
                    return None
                if "applied=" in s:
                    return ("applied=1" if self._applied
                            else "applied=0")
                return super()._stmt(s)

        return FakeSqlFactory(BankFake())

    def test_bank_valid(self):
        test = run_workload(td.bank_workload,
                            {"seed": 5, "gen_ops": 200},
                            self._factory())
        assert test["results"]["valid?"] is True
        reads = [op for op in test["history"]
                 if op.type == "ok" and op.f == "read"]
        assert reads and all(sum(op.value.values()) == 80
                             for op in reads)


class TestCli:
    def test_map_and_sweep(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                "ssh": {"dummy": True}, "time_limit": 5}
        test = td.tidb_test(opts)
        assert test["name"] == "tidb-append"
        tests = list(td.all_tests(opts))
        # every workload x the three fault options
        assert len(tests) == len(td.WORKLOADS) * 3
        lf = td.tidb_test({**opts, "workload": "long-fork"})
        assert lf["lf-table"] is True

    def test_kill_fault_wires_db_package(self):
        opts = {"nodes": ["n1"], "concurrency": 2,
                "ssh": {"dummy": True}, "faults": ["kill"],
                "time_limit": 5}
        test = td.tidb_test(opts)
        # the composed package nemesis, not the bare partitioner
        bare = td.tidb_test({**opts, "faults": None})
        assert type(test["nemesis"]) is not type(bare["nemesis"])


class FakeTidbFull(FakeTidb):
    """FakeTidb extended with the round-5 workload statement shapes:
    registers, sets (plain + CAS blob), sequential subkeys, monotonic
    rows, and DDL'd tN tables. broken='mono-reorder' hands out
    timestamps that run backwards; broken='ghost-table' acks
    create-table but doesn't create every 3rd table."""

    def __init__(self, broken=None):
        super().__init__()
        self.broken = broken
        self.banks = {i: 10 for i in range(8)}
        self.registers: dict = {}
        self.sets: list = []
        self.setcas = ""
        self.seq: set = set()
        self.mono: list = []
        self.ts = 100
        self.created: set = set()
        self.creates = 0
        self.vars: dict = {}

    def _stmt(self, s):
        m = re.match(r"SELECT CONCAT\('v=', COALESCE\(\(SELECT val "
                     r"FROM registers WHERE id = (\d+)\), '~'\)\)", s)
        if m:
            v = self.registers.get(int(m.group(1)))
            return "v=" + ("~" if v is None else str(v))
        m = re.match(r"INSERT INTO registers \(id, val\) VALUES "
                     r"\((\d+), (\d+)\) ON DUPLICATE KEY", s)
        if m:
            self.registers[int(m.group(1))] = int(m.group(2))
            return None
        m = re.match(r"UPDATE registers SET val = (\d+) WHERE "
                     r"id = (\d+) AND val = (\d+)", s)
        if m:
            new, k, old = (int(m.group(1)), int(m.group(2)),
                           int(m.group(3)))
            hit = self.registers.get(k) == old
            if hit:
                self.registers[k] = new
            self.vars["rowcount"] = 1 if hit else 0
            return None
        if re.match(r"SELECT CONCAT\('n=', ROW_COUNT\(\)\)", s):
            return f"n={self.vars.get('rowcount', 0)}"
        m = re.match(r"INSERT INTO sets \(val\) VALUES \((\d+)\)", s)
        if m:
            self.sets.append(int(m.group(1)))
            return None
        if s == "SELECT val FROM sets":
            return "\n".join(str(x) for x in self.sets)
        m = re.match(r"SELECT val INTO @v FROM setcas", s)
        if m:
            self.vars["v"] = self.setcas
            return None
        m = re.match(r"UPDATE setcas SET val = CONCAT\(@v, ',', "
                     r"'(\d+)'\)", s)
        if m:
            self.setcas = f"{self.vars['v']},{m.group(1)}"
            return None
        m = re.match(r"SELECT CONCAT\('s=', val\) FROM setcas", s)
        if m:
            return f"s={self.setcas}"
        m = re.match(r"INSERT IGNORE INTO seq \(sk\) VALUES "
                     r"'?\('([\w]+)'\)", s)
        if m:
            self.seq.add(m.group(1))
            return None
        m = re.match(r"SELECT CONCAT\('x=', COUNT\(\*\)\) FROM seq "
                     r"WHERE sk = '([\w]+)'", s)
        if m:
            return f"x={1 if m.group(1) in self.seq else 0}"
        if re.match(r"SELECT COALESCE\(MAX\(val\), 0\) \+ 1, "
                    r"@@tidb_current_ts INTO @v, @ts FROM mono", s):
            mx = max((r["val"] for r in self.mono), default=0)
            self.vars["v"] = mx + 1
            self.ts += 1
            ts = self.ts
            if self.broken == "mono-reorder" and mx % 5 == 4:
                ts -= 3  # commit timestamp runs backwards
            self.vars["ts"] = ts
            return None
        m = re.match(r"INSERT INTO mono \(val, sts, node, process, "
                     r"tb\) VALUES \(@v, @ts, '([\w.-]+)', (\d+), "
                     r"(\d+)\)", s)
        if m:
            self.mono.append({"val": self.vars["v"],
                              "sts": self.vars["ts"],
                              "node": m.group(1),
                              "process": int(m.group(2)),
                              "tb": int(m.group(3))})
            return None
        if re.match(r"SELECT CONCAT\('row=', @v, ':', @ts\)", s):
            return f"row={self.vars['v']}:{self.vars['ts']}"
        if s.startswith("SELECT CONCAT('r=', val"):
            rows = sorted(self.mono,
                          key=lambda r: (r["sts"], r["val"]))
            return "\n".join(
                f"r={r['val']}:{r['sts']}:{r['node']}:"
                f"{r['process']}:{r['tb']}" for r in rows)
        m = re.match(r"SELECT balance INTO @b1 FROM bank(\d+) "
                     r"WHERE id = 0 FOR UPDATE", s)
        if m:
            self.vars["b1"] = self.banks[int(m.group(1))]
            return None
        m = re.match(r"UPDATE bank(\d+) SET balance = balance "
                     r"([+-]) (\d+) WHERE id = 0 AND @b1 >= (\d+)",
                     s)
        if m:
            if self.vars.get("b1", 0) >= int(m.group(4)):
                d = int(m.group(3))
                i = int(m.group(1))
                self.banks[i] += d if m.group(2) == "+" else -d
            return None
        m = re.match(r"SELECT CONCAT\('applied=', IF\(@b1 >= "
                     r"(\d+), 1, 0\)\)", s)
        if m:
            ok = 1 if self.vars.get("b1", 0) >= int(m.group(1)) else 0
            return f"applied={ok}"
        if s.startswith("SELECT CONCAT('b=', GROUP_CONCAT"):
            return "b=" + ",".join(
                f"{i}:{b}" for i, b in sorted(self.banks.items()))
        m = re.match(r"CREATE TABLE IF NOT EXISTS t(\d+) ", s)
        if m:
            self.creates += 1
            if not (self.broken == "ghost-table"
                    and self.creates % 3 == 0):
                self.created.add(int(m.group(1)))
            return None
        m = re.match(r"INSERT INTO t(\d+) \(id\) VALUES \((\d+)\)", s)
        if m:
            t = int(m.group(1))
            if t not in self.created:
                raise _FakeSqlError(f"Table 'jepsen.t{t}' "
                                    "doesn't exist")
            return None
        return super()._stmt(s)


class _FakeSqlError(Exception):
    pass


class FakeFullFactory(FakeSqlFactory):
    def __init__(self, state=None, broken=None):
        self.state = state or FakeTidbFull(broken)

    def __call__(self, test, node, timeout=10.0):
        factory = self

        class _S:
            def run(self, sql):
                try:
                    return factory.state.run(sql)
                except _FakeSqlError as e:
                    from jepsen_tpu.control.core import RemoteError

                    raise RemoteError("mysql failed", exit=1, out="",
                                      err=str(e), cmd="mysql",
                                      node=node)

            def close(self):
                pass

        return _S()


class TestNewWorkloads:
    def test_register_linearizable(self):
        t = run_workload(td.register_workload,
                         {"keys": [0, 1], "ops_per_key": 40,
                          "group_size": 3, "seed": 7,
                          "gen_ops": 200},
                         FakeFullFactory())
        assert t["results"]["valid?"] is True, t["results"]

    def test_set_and_set_cas(self):
        for fn in (td.set_workload, td.set_cas_workload):
            t = run_workload(fn, {"ops": 120, "gen_ops": 150},
                             FakeFullFactory())
            assert t["results"]["valid?"] is True, t["results"]

    def test_sequential(self):
        t = run_workload(td.sequential_workload,
                         {"ops": 80, "gen_ops": 120},
                         FakeFullFactory())
        assert t["results"]["valid?"] in (True, "unknown"), \
            t["results"]

    def test_monotonic_healthy_and_reordered(self):
        t = run_workload(td.monotonic_workload,
                         {"ops": 60, "gen_ops": 80},
                         FakeFullFactory())
        assert t["results"]["valid?"] is True, t["results"]
        t = run_workload(td.monotonic_workload,
                         {"ops": 60, "gen_ops": 80},
                         FakeFullFactory(broken="mono-reorder"))
        assert t["results"]["valid?"] is False

    def test_txn_cycle(self):
        t = run_workload(td.txn_cycle_workload,
                         {"ops": 150, "seed": 5, "gen_ops": 200},
                         FakeSqlFactory())
        assert t["results"]["valid?"] is True, t["results"]

    def test_table_healthy_and_ghost(self):
        t = run_workload(td.table_workload,
                         {"ops": 80, "seed": 2, "gen_ops": 100},
                         FakeFullFactory())
        assert t["results"]["valid?"] is True, t["results"]
        t = run_workload(td.table_workload,
                         {"ops": 120, "seed": 2, "gen_ops": 150},
                         FakeFullFactory(broken="ghost-table"))
        assert t["results"]["valid?"] is False

    def test_bank_multitable(self):
        t = run_workload(td.bank_multitable_workload,
                         {"ops": 80, "gen_ops": 100},
                         FakeFullFactory())
        assert t["results"]["valid?"] is True, t["results"]

    def test_menu_matches_reference(self):
        # tidb/core.clj:32-60 workload names
        assert set(td.WORKLOADS) == {
            "bank", "bank-multitable", "long-fork", "monotonic",
            "txn-cycle", "append", "register", "set", "set-cas",
            "sequential", "table"}
