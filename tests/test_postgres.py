"""Postgres suite tests: DB command emission via the dummy remote, a
scripted in-memory "postgres" speaking the suite's SQL shapes, and
clusterless end-to-end append (elle) + bank runs (mirrors
stolon/src/jepsen/stolon/{append,ledger,client}.clj)."""

import re
import threading

import pytest

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, RemoteError
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.suites import postgres as pg


def make_test(responder=None, nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return t


def cmds(test, node):
    return [a for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_primary_gets_server_and_schema(self):
        test = make_test(lambda node, a:
                         "/etc/postgresql/15/main/pg_hba.conf"
                         if a.cmd.startswith("psql") and
                         "SHOW hba_file" in a.cmd else None)
        db = pg.PostgresDB()
        with control.with_session(test, "n1"):
            db.setup(test, "n1")
        acts = cmds(test, "n1")
        got = " ; ".join(a.cmd for a in acts)
        assert "postgresql" in got
        assert "listen_addresses" in got
        assert "pg_hba.conf" in got and "trust" in got
        assert "CREATE TABLE txn0" in got
        assert "CREATE TABLE accounts" in got
        assert "CHECK (balance >= 0)" in got
        assert "service postgresql restart" in got
        # schema statements run as the postgres superuser
        create = next(a for a in acts if "CREATE TABLE txn0" in a.cmd)
        assert create.sudo == "postgres"

    def test_secondaries_get_client_only(self):
        test = make_test()
        db = pg.PostgresDB()
        with control.with_session(test, "n2"):
            db.setup(test, "n2")
        got = " ; ".join(a.cmd for a in cmds(test, "n2"))
        assert "postgresql-client" in got
        assert "CREATE TABLE" not in got

    def test_teardown_drops_db(self):
        test = make_test()
        db = pg.PostgresDB()
        with control.with_session(test, "n1"):
            db.teardown(test, "n1")
        got = " ; ".join(a.cmd for a in cmds(test, "n1"))
        assert "DROP DATABASE IF EXISTS jepsen" in got
        assert "service postgresql stop" in got


class FakePostgres:
    """In-memory store executing exactly the SQL shapes the suite
    emits, one whole psql invocation at a time under a lock — i.e. a
    perfectly serializable single-node 'postgres'."""

    def __init__(self, accounts=8, balance=10):
        self.lock = threading.Lock()
        self.tables = {f"txn{i}": {} for i in range(pg.TABLE_COUNT)}
        self.accounts = {i: balance for i in range(accounts)}
        self.statements: list = []

    # -- statement interpreters -----------------------------------------

    def _read_mop(self, m):
        t, k = m.group(2), int(m.group(3))
        val = self.tables[t].get(k)
        return f"m{m.group(1)}=" + ("~" if val is None else val)

    def _append_mop(self, m):
        t, k, v = m.group(1), int(m.group(2)), m.group(3)
        cur = self.tables[t].get(k)
        self.tables[t][k] = v if cur is None else f"{cur},{v}"
        return None

    def _bank_read(self, _m):
        return "b=" + ",".join(f"{i}:{b}" for i, b in
                               sorted(self.accounts.items()))

    def _transfer(self, m):
        amt, acct = int(m.group(1)), int(m.group(2))
        sign = -1 if m.group(0).count("- ") else 1
        nxt = self.accounts[acct] + sign * amt
        if nxt < 0:
            raise _PgError(
                'new row for relation "accounts" violates check '
                'constraint "accounts_balance_check"')
        self.accounts[acct] = nxt

    PATTERNS = [
        (re.compile(r"SELECT 'm(\d+)=' \|\| COALESCE\("
                    r"\(SELECT val FROM (txn\d+) WHERE id = (\d+)\), "
                    r"'~'\)"), "_read_mop"),
        (re.compile(r"INSERT INTO (txn\d+) AS t \(id, val\) "
                    r"VALUES \((\d+), '(\d+)'\) ON CONFLICT"),
         "_append_mop"),
        (re.compile(r"SELECT 'b=' \|\| COALESCE\(string_agg"),
         "_bank_read"),
        (re.compile(r"UPDATE accounts SET balance = balance "
                    r"[-+] (\d+) WHERE id = (\d+)"), "_transfer"),
        (re.compile(r"(BEGIN ISOLATION LEVEL \w+|COMMIT)"), None),
    ]

    def execute(self, sql: str) -> str:
        """Executes one psql -c payload atomically; returns stdout."""
        with self.lock:
            out = []
            backup = ({t: dict(kv) for t, kv in self.tables.items()},
                      dict(self.accounts))
            try:
                for stmt in filter(None,
                                   (s.strip() for s in sql.split(";"))):
                    self.statements.append(stmt)
                    for pat, meth in self.PATTERNS:
                        m = pat.search(stmt)
                        if m:
                            if meth:
                                line = getattr(self, meth)(m)
                                if line is not None:
                                    out.append(line)
                            break
                    else:
                        raise AssertionError(
                            f"fake postgres can't parse: {stmt!r}")
            except _PgError:
                self.tables, self.accounts = backup  # txn rollback
                raise
            return "\n".join(out) + ("\n" if out else "")


class _PgError(Exception):
    pass


class FakePsqlFactory:
    """Builds Psql objects whose run() hits the fake instead of a
    node; RemoteErrors carry the fake's stderr like real psql."""

    def __init__(self, state=None):
        self.state = state or FakePostgres()

    def __call__(self, test, node, host, timeout=10.0):
        factory = self

        class _FakePsql:
            def run(self, sql):
                try:
                    return factory.state.execute(sql)
                except _PgError as e:
                    raise RemoteError("psql failed", exit=1, out="",
                                      err=f"ERROR: {e}", cmd="psql",
                                      node=node)

            def close(self):
                pass

        return _FakePsql()


class TestAppendClient:
    def _client(self, state=None):
        f = FakePsqlFactory(state)
        c = pg.PgAppendClient(psql_factory=f).open(
            {"nodes": ["n1"]}, "n1")
        return c, f.state

    def _invoke(self, c, mops):
        from jepsen_tpu.history import Op

        return c.invoke({}, Op(type="invoke", process=0, f="txn",
                               value=mops))

    def test_append_then_read(self):
        c, _ = self._client()
        r1 = self._invoke(c, [["append", 1, 10]])
        assert r1.type == "ok"
        r2 = self._invoke(c, [["r", 1, None]])
        assert r2.value == [["r", 1, [10]]]

    def test_read_missing_key_is_none(self):
        c, _ = self._client()
        r = self._invoke(c, [["r", 9, None]])
        assert r.value == [["r", 9, None]]

    def test_multi_mop_txn_reads_own_writes(self):
        c, state = self._client()
        r = self._invoke(c, [["append", 2, 7], ["r", 2, None],
                             ["append", 2, 8], ["r", 2, None]])
        assert r.type == "ok"
        assert r.value == [["append", 2, 7], ["r", 2, [7]],
                           ["append", 2, 8], ["r", 2, [7, 8]]]
        # and it all went through one serializable block
        assert any("BEGIN ISOLATION LEVEL SERIALIZABLE" in s
                   for s in state.statements)

    def test_serialization_failure_is_definite_fail(self):
        c, state = self._client()

        real = state.execute
        state.execute = lambda sql: (_ for _ in ()).throw(
            _PgError("could not serialize access due to concurrent "
                     "update"))
        r = self._invoke(c, [["append", 1, 1], ["r", 1, None]])
        assert r.type == "fail"
        assert "serialize" in r.error
        state.execute = real

    def test_tables_partition_keyspace(self):
        c, state = self._client()
        self._invoke(c, [["append", 0, 1]])
        self._invoke(c, [["append", 1, 1]])
        self._invoke(c, [["append", 5, 1]])
        assert state.tables["txn0"] == {0: "1"}
        assert state.tables["txn1"] == {1: "1"}
        assert state.tables["txn2"] == {5: "1"}


class TestEndToEnd:
    def _run(self, workload_fn, opts, factory):
        w = workload_fn(opts)
        w["client"].psql_factory = factory
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"],
                    concurrency=opts.get("concurrency", 6),
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0005, gen.limit(
                            opts.get("ops", 300), w["generator"]))))
        return core.run(test)

    def test_append_workload_valid(self):
        test = self._run(pg.append_workload,
                         {"ops": 300, "keys": 5, "seed": 11,
                          "concurrency": 6},
                         FakePsqlFactory())
        assert test["results"]["valid?"] is True
        oks = [op for op in test["history"]
               if op.type == "ok" and op.f == "txn"]
        assert len(oks) > 50

    def test_append_detects_incompatible_order(self):
        """A fake that serves one key's list REVERSED to half the
        reads yields incompatible version orders -> invalid."""

        class Corrupt(FakePostgres):
            def __init__(self):
                super().__init__()
                self.n = 0

            def _read_mop(self, m):
                t, k = m.group(2), int(m.group(3))
                val = self.tables[t].get(k)
                self.n += 1
                if val is not None and "," in val and self.n % 2:
                    val = ",".join(reversed(val.split(",")))
                return f"m{m.group(1)}=" + ("~" if val is None
                                            else val)

        test = self._run(pg.append_workload,
                         {"ops": 400, "keys": 2, "seed": 13,
                          "concurrency": 6},
                         FakePsqlFactory(Corrupt()))
        assert test["results"]["valid?"] is False

    def test_bank_workload_valid(self):
        test = self._run(pg.bank_workload,
                         {"ops": 300, "seed": 17, "concurrency": 6},
                         FakePsqlFactory())
        assert test["results"]["valid?"] is True
        reads = [op for op in test["history"]
                 if op.type == "ok" and op.f == "read"]
        assert reads and all(sum(op.value.values()) == 80
                             for op in reads)

    def test_bank_detects_lost_debit(self):
        """A fake that drops the debit half of transfers inflates the
        total -> wrong-total error."""

        class Lossy(FakePostgres):
            def _transfer(self, m):
                if "- " in m.group(0):
                    return  # lose every debit
                super()._transfer(m)

        test = self._run(pg.bank_workload,
                         {"ops": 200, "seed": 19, "concurrency": 4},
                         FakePsqlFactory(Lossy()))
        assert test["results"]["valid?"] is False

    def test_overdraft_aborts_whole_txn(self):
        """CHECK constraint: a transfer bigger than the balance
        definitively fails and mutates nothing."""
        state = FakePostgres(accounts=2, balance=3)
        f = FakePsqlFactory(state)
        c = pg.PgBankClient(psql_factory=f).open(
            {"nodes": ["n1"]}, "n1")
        from jepsen_tpu.history import Op

        r = c.invoke({}, Op(type="invoke", process=0, f="transfer",
                            value={"from": 0, "to": 1, "amount": 99}))
        assert r.type == "fail"
        assert state.accounts == {0: 3, 1: 3}


class TestCli:
    def test_test_map_shape(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                "ssh": {"dummy": True}, "workload": "bank",
                "time_limit": 5}
        test = pg.postgres_test(opts)
        assert test["name"] == "postgres-bank"
        assert isinstance(test["db"], pg.PostgresDB)

    def test_isolation_threads_to_client(self):
        opts = {"nodes": ["n1"], "concurrency": 2,
                "ssh": {"dummy": True}, "workload": "append",
                "isolation": "REPEATABLE READ"}
        test = pg.postgres_test(opts)
        assert test["client"].isolation == "REPEATABLE READ"
