"""Nemesis grudge math + composition tests (nemesis.clj semantics)."""

import random

from jepsen_tpu import nemesis as nem
from jepsen_tpu.history import History, Op, op


def test_bisect_and_split_one():
    assert nem.bisect([1, 2, 3, 4, 5]) == [[1, 2], [3, 4, 5]]
    assert nem.split_one(2, [1, 2, 3]) == [[2], [1, 3]]


def test_complete_grudge():
    g = nem.complete_grudge([[1, 2], [3, 4, 5]])
    assert g[1] == {3, 4, 5}
    assert g[3] == {1, 2}


def test_bridge():
    g = nem.bridge([1, 2, 3, 4, 5])
    assert g[3] == set()          # the bridge sees everyone
    assert g[1] == {4, 5}
    assert g[5] == {1, 2}


def test_majorities_ring_cuts_links_and_preserves_majorities():
    nodes = [f"n{i}" for i in range(1, 6)]
    g = nem.majorities_ring(nodes, rng=random.Random(7))
    # Some links must actually be cut (regression: k formula produced an
    # empty grudge for odd n).
    assert any(v for v in g.values())
    for node in nodes:
        visible = set(nodes) - g[node]
        assert node in visible
        assert len(visible) == 3  # bare majority of 5
    # No two nodes see the same majority.
    majorities = [frozenset(set(nodes) - g[n]) for n in nodes]
    assert len(set(majorities)) == len(nodes)


class Recorder(nem.Nemesis):
    def __init__(self):
        self.seen = []

    def invoke(self, test, o):
        self.seen.append(o.f)
        return o

    def fs(self):
        return {"go"}


def test_compose_routes_by_fs():
    a, b = Recorder(), Recorder()
    c = nem.compose([({"a-go"}, nem.f_map({"a-go": "go"}, a)),
                     ({"b-go"}, nem.f_map({"b-go": "go"}, b))])
    c = c.setup({})
    out = c.invoke({}, op(type="info", process="nemesis", f="a-go"))
    assert out.f == "a-go"  # outer name restored
    assert a.seen == ["go"]
    assert b.seen == []
    assert c.fs() == {"a-go", "b-go"}


def test_compose_dict_mapping_rewrites_f():
    a = Recorder()
    c = nem.compose([({"kill-primary": "go"}, a)])
    c.invoke({}, op(type="info", process="nemesis", f="kill-primary"))
    assert a.seen == ["go"]
    assert c.fs() == {"kill-primary"}


def test_history_pairing_survives_filtering():
    hist = History([
        dict(type="invoke", process="nemesis", f="start", time=0),
        dict(type="invoke", process=0, f="w", value=1, time=1),
        dict(type="info", process="nemesis", f="start", time=2),
        dict(type="ok", process=0, f="w", value=1, time=3),
    ])
    clients = hist.client_ops()
    inv = clients[0]
    comp = clients.completion(inv)
    assert comp.type == "ok" and comp.process == 0
    assert clients.invocation(comp).index == inv.index
    sliced = hist[1:]
    assert sliced.completion(sliced[0]).f == "w"
