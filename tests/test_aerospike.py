"""Aerospike suite tests: DB command emission via the dummy remote, a
scripted aql, reply classification, and a clusterless end-to-end CAS
register run (mirrors aphyr/jepsen aerospike/src/aerospike/core.clj)."""

import threading

from jepsen_tpu import control, core, suites, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, RemoteError, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import op
from jepsen_tpu.suites import aerospike as ae


def responder(node, action):
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "aerospike-server-community-3.5.4-debian8"
    return None


class TestRegistry:
    def test_aerospike_registered(self):
        assert "aerospike" in suites.SUITES
        assert suites.load("aerospike") is ae


class TestDB:
    def test_setup_commands(self):
        remote = DummyRemote(responder)
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"], remote=remote,
                    sessions={n: remote.connect({"host": n})
                              for n in ["n1", "n2", "n3"]})
        db = ae.AerospikeDB("3.5.4")
        with control.with_session(test, "n2"):
            db.setup(test, "n2")
        got = " ; ".join(a.cmd for a in test["sessions"]["n2"].log
                         if isinstance(a, Action))
        assert "aerospike-server-community-3.5.4-debian8.tgz" in got
        assert "dpkg -i" in got
        assert "service aerospike restart" in got
        assert "REGISTER MODULE" in got
        # mesh seeds name every OTHER node, never the node itself
        stdins = " ; ".join(str(a.stdin) for a in
                            test["sessions"]["n2"].log
                            if isinstance(a, Action) and a.stdin)
        assert "mesh-seed-address-port n1 3002" in stdins
        assert "mesh-seed-address-port n3 3002" in stdins
        assert "mesh-seed-address-port n2 3002" not in stdins
        # the conf replicates across the whole cluster
        assert "replication-factor 3" in stdins


class TestReplyParsing:
    TABLE = ("+---+\n| v |\n+---+\n| 5 |\n+---+\n"
             "1 row in set (0.000 secs)\n")

    def test_parse_value_cell(self):
        assert ae.parse_cells(self.TABLE) == [5]

    def test_parse_empty(self):
        assert ae.parse_cells("0 rows in set (0.000 secs)\n") == []

    def test_error_raises(self):
        import pytest

        with pytest.raises(ae._ErrReply):
            ae.parse_cells("Error: (11) AEROSPIKE_ERR_CLUSTER\n")

    def test_timeout_write_is_info(self):
        o = op(index=0, time=0, type="invoke", process=0, f="write",
               value=3)
        e = RemoteError("timed out", exit=-1, out="", err="timeout",
                        cmd="aql", node="n1")
        assert ae._classify(o, e).type == "info"

    def test_definite_error_is_fail(self):
        o = op(index=0, time=0, type="invoke", process=0, f="write",
               value=3)
        got = ae._classify(o, ae._ErrReply(
            "Error: (11) AEROSPIKE_ERR_CLUSTER unavailable"))
        assert got.type == "fail"

    def test_read_error_is_always_fail(self):
        o = op(index=0, time=0, type="invoke", process=0, f="read",
               value=None)
        e = RemoteError("timed out", exit=-1, out="", err="timeout",
                        cmd="aql", node="n1")
        assert ae._classify(o, e).type == "fail"


class FakeAerospike:
    """In-memory register speaking aql table replies; cas runs
    atomically under the lock like the record UDF does server-side."""

    def __init__(self):
        self.lock = threading.Lock()
        self.value = None

    @staticmethod
    def _table(v) -> str:
        return f"+---+\n| v |\n+---+\n| {v} |\n+---+\n1 row in set\n"

    def run(self, statement: str):
        import re

        with self.lock:
            if statement.startswith("SELECT"):
                if self.value is None:
                    return "0 rows in set (0.000 secs)\n"
                return self._table(self.value)
            m = re.match(r"EXECUTE jepsen\.put\((-?\d+)\)", statement)
            if m:
                self.value = int(m.group(1))
                return self._table(1)
            m = re.match(r"EXECUTE jepsen\.cas\((-?\d+), (-?\d+)\)",
                         statement)
            if m:
                old, new = int(m.group(1)), int(m.group(2))
                if self.value == old:
                    self.value = new
                    return self._table(1)
                return self._table(0)
            raise AssertionError(f"unexpected statement {statement!r}")


class FakeCliFactory:
    def __init__(self, state=None):
        self.state = state or FakeAerospike()

    def __call__(self, test, node, timeout=5.0):
        factory = self

        class _C:
            def run(self, statement):
                return factory.state.run(statement)

            def close(self):
                pass

        return _C()


class TestEndToEnd:
    def _run(self, ops=160):
        w = ae.register_workload({"ops": ops, "seed": 7})
        w["client"].cli_factory = FakeCliFactory()
        test = testing.noop_test()
        test.update(nodes=["n1", "n2"], concurrency=4,
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0004, w["generator"])))
        return core.run(test)

    def test_register_run_is_linearizable(self):
        t = self._run()
        res = t["results"]
        assert res["valid?"] is True
        # the atomic fake register really exercised cas both ways
        types = {(o.f, o.type) for o in t["history"]}
        assert ("cas", "ok") in types
        assert ("cas", "fail") in types

    def test_run_carries_validated_certificate(self):
        """Suite verdicts ride the same proof plane as everything
        else: the linearizable checker's result carries a certificate
        that core.analyze stamped `certified` (VERDICT L11 parity AND
        ISSUE-10 in one run)."""
        t = self._run(ops=80)
        res = t["results"]
        cert = res.get("certificate")
        assert isinstance(cert, dict)
        assert "absent" not in cert, cert
        assert res.get("certified") is True
