"""Differential tests: the C flattener (native/elleflat.c) against the
Python Flat/RwFlat reference, field by field, plus end-to-end
equivalence of the native path vs the forced-Python fallback. These
pin the 'semantically identical' contract both files claim."""

import random

import numpy as np
import pytest

from jepsen_tpu import native
from jepsen_tpu.history import History, op
from jepsen_tpu.tpu import elle, elle_device, synth

pytestmark = pytest.mark.skipif(
    native.elleflat() is None,
    reason="native elleflat unavailable (no C toolchain)")

APPEND_FIELDS = ("t_type", "t_inv", "t_comp", "t_opidx",
                 "ap_txn", "ap_key", "ap_val",
                 "rd_txn", "rd_key", "rd_len", "re_vals")
RW_FIELDS = ("t_type", "t_inv", "t_comp", "t_opidx",
             "wr_txn", "wr_key", "wr_val", "wr_nonfinal",
             "rd_txn", "rd_key", "rd_val",
             "fr_txn", "fr_key", "fr_prev", "fr_new",
             "er_txn", "er_key", "er_val")


def _rw_history(n_txns, seed):
    """Random rw-register txns incl. fails/infos and None reads."""
    rng = random.Random(seed)
    events = []
    open_t = {}
    t = 0
    while t < n_txns or open_t:
        if t < n_txns and len(open_t) < 4 and (rng.random() < 0.6
                                               or not open_t):
            p = rng.choice([q for q in range(5) if q not in open_t])
            mops = []
            for _ in range(rng.randint(1, 4)):
                k = rng.randrange(4)
                if rng.random() < 0.5:
                    mops.append(["w", k, rng.randrange(100)])
                else:
                    mops.append(["r", k, None])
            events.append(("invoke", p, mops))
            open_t[p] = mops
            t += 1
        else:
            p = rng.choice(list(open_t))
            mops = open_t.pop(p)
            r = rng.random()
            if r < 0.1:
                events.append(("info", p, mops))
            elif r < 0.2:
                events.append(("fail", p, mops))
            else:
                done = [[f, k, rng.randrange(100) if f == "r" else v]
                        for f, k, v in mops]
                events.append(("ok", p, done))
    return History([op(type=ty, process=p, f="txn", value=m)
                    for ty, p, m in events])


class TestDifferential:
    def test_append_fields_identical(self):
        for seed in range(8):
            hist = synth.list_append_history(400, seed=seed)
            ops = list(hist)
            arrs, keys = native.elle_flatten(ops, 0)
            txns = elle.collect(hist)
            ref = elle_device.Flat(txns)
            for f in APPEND_FIELDS:
                got = arrs[f]
                want = getattr(ref, f, None)
                if want is None:  # t_opidx has no python analog field
                    continue
                assert (np.asarray(got) == np.asarray(want)).all(), \
                    (seed, f)
            assert keys == ref.key_names
            # dense first-seen proc codes must match the python intern
            flat = elle_device.Flat.from_native(ops, arrs, keys)
            assert (flat.t_proc == ref.t_proc).all(), seed

    def test_rw_fields_identical(self):
        for seed in range(8):
            hist = _rw_history(300, seed)
            ops = list(hist)
            arrs, keys = native.elle_flatten(ops, 1)
            txns = elle.collect(hist)
            ref = elle_device.RwFlat(txns)
            for f in RW_FIELDS:
                got = np.asarray(arrs[f])
                want = getattr(ref, f, None)
                if want is None:
                    continue
                want = np.asarray(want)
                if f == "wr_nonfinal":
                    # C emits a non-final row at the NEXT same-key
                    # write, python per-txn at txn end — same set;
                    # the only consumer (inter_txn) is a scatter-max,
                    # order-independent
                    got, want = np.sort(got), np.sort(want)
                assert (got == want).all(), (seed, f)
            assert keys == ref.key_names
            flat = elle_device.RwFlat.from_native(ops, arrs, keys)
            assert (flat.t_proc == ref.t_proc).all(), seed
            # internal anomaly records carry the same (key, expected,
            # read) triples
            assert ([(r["key"], r["expected"], r["read"])
                     for r in flat.internal_bad]
                    == [(r["key"], r["expected"], r["read"])
                        for r in ref.internal_bad]), seed

    def test_native_vs_fallback_end_to_end(self, monkeypatch):
        def boom(*a, **kw):
            raise RuntimeError("forced fallback")

        for seed in (3, 9):
            hist = synth.list_append_history(600, seed=seed)
            want = elle_device.check_list_append_device(hist,
                                                       device=False)
            with monkeypatch.context() as m:
                m.setattr(native, "elle_flatten", boom)
                got = elle_device.check_list_append_device(
                    hist, device=False)
            assert got["valid?"] == want["valid?"]
            assert got["anomaly-types"] == want["anomaly-types"]
            assert got["edge-count"] == want["edge-count"]

        hist = _rw_history(400, 5)
        want = elle_device.check_rw_register_device(hist, device=False)
        with monkeypatch.context() as m:
            m.setattr(native, "elle_flatten", boom)
            got = elle_device.check_rw_register_device(hist,
                                                       device=False)
        assert got["valid?"] == want["valid?"]
        assert got["anomaly-types"] == want["anomaly-types"]
        assert got["edge-count"] == want["edge-count"]

    def test_unvectorizable_values_raise(self):
        hist = History([
            op(type="invoke", process=0, f="txn",
               value=[["append", "x", "str"]]),
            op(type="ok", process=0, f="txn",
               value=[["append", "x", "str"]]),
        ])
        with pytest.raises(native.NotVectorizable):
            native.elle_flatten(list(hist), 0)

    def test_unknown_mop_types_intern_keys_like_python(self):
        """Key-intern parity (round-5 advisor finding): the Python
        flattener assigns a key id to EVERY mop before dispatching on
        f, so an unknown mop type must still claim its intern slot in
        the C pass — here 'zed' must intern before 'a'."""
        mops1 = [["x", "zed", 0], ["append", "a", 1]]
        hist = History([
            op(type="invoke", process=0, f="txn", value=mops1),
            op(type="ok", process=0, f="txn", value=mops1),
            op(type="invoke", process=1, f="txn",
               value=[["append", "zed", 2], ["r", "a", None]]),
            op(type="ok", process=1, f="txn",
               value=[["append", "zed", 2], ["r", "a", [1]]]),
        ])
        ops = list(hist)
        arrs, keys = native.elle_flatten(ops, 0)
        ref = elle_device.Flat(elle.collect(hist))
        assert keys == ref.key_names == ["zed", "a"]
        for f in APPEND_FIELDS:
            want = getattr(ref, f, None)
            if want is None:
                continue
            assert (np.asarray(arrs[f]) == np.asarray(want)).all(), f

    def test_unknown_mop_types_intern_keys_rw(self):
        mops1 = [["cas", "q", 7], ["w", "p", 1]]
        hist = History([
            op(type="invoke", process=0, f="txn", value=mops1),
            op(type="ok", process=0, f="txn", value=mops1),
            op(type="invoke", process=1, f="txn",
               value=[["w", "q", 2], ["r", "p", None]]),
            op(type="ok", process=1, f="txn",
               value=[["w", "q", 2], ["r", "p", 1]]),
        ])
        ops = list(hist)
        arrs, keys = native.elle_flatten(ops, 1)
        ref = elle_device.RwFlat(elle.collect(hist))
        assert keys == ref.key_names == ["q", "p"]
        for f in RW_FIELDS:
            want = getattr(ref, f, None)
            if want is None:
                continue
            assert (np.asarray(arrs[f]) == np.asarray(want)).all(), f

    def test_non_string_op_type_skipped(self):
        """An op with a non-string :type must be skipped cleanly by
        the C pass — the host path ignores it, and an unguarded
        PyUnicode compare on it is undefined behavior (round-5
        advisor finding)."""
        ops = [
            op(type="invoke", process=0, f="txn",
               value=[["append", "k", 1]]),
            op(type=7, process=0, f="txn",
               value=[["append", "k", 2]]),
            op(type=None, process=1, f="txn",
               value=[["append", "k", 3]]),
            op(type="ok", process=0, f="txn",
               value=[["append", "k", 1]]),
        ]
        arrs, keys = native.elle_flatten(ops, 0)
        assert len(arrs["t_type"]) == 1  # only the paired ok txn
        assert list(arrs["ap_val"]) == [1]
        assert keys == ["k"]
