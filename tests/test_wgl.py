"""Linearizability engine tests: host WGL vs object-model search vs the
batched device kernel, on hand-written and randomly generated histories.

Mirrors the reference's approach of checker unit tests over literal
histories (jepsen/test/jepsen/checker_test.clj) plus differential golden
checks; random histories are valid-by-construction (effects applied at a
random point inside each op's invoke/complete window) and corrupted
variants exercise the invalid path.
"""

import random

import pytest

from jepsen_tpu import checker
from jepsen_tpu.checker import models as model
from jepsen_tpu.history import History, op
from jepsen_tpu.tpu import wgl
from jepsen_tpu.tpu.encode import encode


def H(*specs):
    """history from (type, process, f, value) tuples."""
    return History([op(type=t, process=p, f=f, value=v)
                    for t, p, f, v in specs])


# ---------------------------------------------------------------------------
# Hand-written cases
# ---------------------------------------------------------------------------

VALID_CASES = {
    "empty": H(),
    "write-read": H(("invoke", 0, "write", 1), ("ok", 0, "write", 1),
                    ("invoke", 1, "read", None), ("ok", 1, "read", 1)),
    "concurrent-read-either": H(
        ("invoke", 0, "write", 1),
        ("invoke", 1, "read", None),
        ("ok", 1, "read", None),   # read sees initial nil: w not yet applied
        ("ok", 0, "write", 1)),
    "cas": H(("invoke", 0, "write", 1), ("ok", 0, "write", 1),
             ("invoke", 1, "cas", [1, 2]), ("ok", 1, "cas", [1, 2]),
             ("invoke", 0, "read", None), ("ok", 0, "read", 2)),
    "crashed-write-observed": H(
        ("invoke", 0, "write", 7), ("info", 0, "write", 7),
        ("invoke", 1, "read", None), ("ok", 1, "read", 7)),
    "crashed-write-unobserved": H(
        ("invoke", 0, "write", 7), ("info", 0, "write", 7),
        ("invoke", 1, "read", None), ("ok", 1, "read", None)),
    "failed-write-ignored": H(
        ("invoke", 0, "write", 3), ("fail", 0, "write", 3),
        ("invoke", 1, "read", None), ("ok", 1, "read", None)),
    "overlap-chain": H(
        ("invoke", 0, "write", 1),
        ("invoke", 1, "write", 2),
        ("ok", 0, "write", 1),
        ("invoke", 2, "read", None),
        ("ok", 2, "read", 2),
        ("ok", 1, "write", 2)),
}

INVALID_CASES = {
    # NB: an ok read with value None is "observed nothing" and always
    # passes (knossos cas-register convention) — so stale reads must
    # observe a concrete superseded value to be anomalies.
    "wrong-read": H(("invoke", 0, "write", 1), ("ok", 0, "write", 1),
                    ("invoke", 1, "read", None), ("ok", 1, "read", 2)),
    "cas-from-missing": H(
        ("invoke", 0, "write", 1), ("ok", 0, "write", 1),
        ("invoke", 1, "cas", [2, 3]), ("ok", 1, "cas", [2, 3])),
    "failed-write-observed": H(
        ("invoke", 0, "write", 3), ("fail", 0, "write", 3),
        ("invoke", 1, "read", None), ("ok", 1, "read", 3)),
    "ordered-writes-stale-read": H(
        ("invoke", 0, "write", 1), ("ok", 0, "write", 1),
        ("invoke", 0, "write", 2), ("ok", 0, "write", 2),
        ("invoke", 1, "read", None), ("ok", 1, "read", 1)),
}


@pytest.mark.parametrize("name", sorted(VALID_CASES))
def test_valid_cases_all_algorithms(name):
    hist = VALID_CASES[name]
    for alg in ("tpu", "wgl", "model"):
        a = wgl.analysis(model.cas_register(), hist, algorithm=alg)
        assert a["valid?"] is True, (name, alg, a)


@pytest.mark.parametrize("name", sorted(INVALID_CASES))
def test_invalid_cases_all_algorithms(name):
    hist = INVALID_CASES[name]
    for alg in ("tpu", "wgl", "model"):
        a = wgl.analysis(model.cas_register(), hist, algorithm=alg)
        assert a["valid?"] is False, (name, alg, a)
    a = wgl.analysis(model.cas_register(), hist)
    assert a.get("op") is not None  # witness


# ---------------------------------------------------------------------------
# Random differential histories
# ---------------------------------------------------------------------------

def random_register_history(rng, n_procs=4, n_ops=40, crash_p=0.08):
    """Concurrent CAS-register history, valid by construction: each op's
    effect lands at a random instant inside its window."""
    value = None
    events = []
    open_ops = {}  # process -> (f, v, applied?, result)
    budget = n_ops
    procs = list(range(n_procs))
    while budget > 0 or open_ops:
        actions = []
        idle = [p for p in procs if p not in open_ops]
        if budget > 0 and idle:
            actions.append("invoke")
        unapplied = [p for p, o in open_ops.items() if not o[2]]
        if unapplied:
            actions.append("apply")
        applied = [p for p, o in open_ops.items() if o[2]]
        if applied:
            actions.append("complete")
            actions.append("crash")
        act = rng.choice(actions)
        if act == "invoke":
            p = rng.choice(idle)
            f = rng.choice(["read", "write", "cas"])
            if f == "read":
                v = None
            elif f == "write":
                v = rng.randint(0, 4)
            else:
                v = [rng.randint(0, 4), rng.randint(0, 4)]
            open_ops[p] = (f, v, False, None)
            events.append(("invoke", p, f, v))
            budget -= 1
        elif act == "apply":
            p = rng.choice(unapplied)
            f, v, _, _ = open_ops[p]
            if f == "read":
                open_ops[p] = (f, v, True, value)
            elif f == "write":
                value = v
                open_ops[p] = (f, v, True, None)
            else:
                cur, new = v
                if cur == value:
                    value = new
                    open_ops[p] = (f, v, True, "ok")
                else:
                    open_ops[p] = (f, v, True, "fail")
        elif act == "complete":
            p = rng.choice(applied)
            f, v, _, result = open_ops.pop(p)
            if f == "read":
                events.append(("ok", p, f, result))
            elif f == "write":
                events.append(("ok", p, f, v))
            else:
                events.append((("ok" if result == "ok" else "fail"),
                               p, f, v))
        else:  # crash: effect stands (if applied) but completion is lost
            p = rng.choice(applied)
            if rng.random() < crash_p:
                f, v, _, _ = open_ops.pop(p)
                events.append(("info", p, f, v))
    return H(*events)


def corrupt(rng, hist):
    """Flip one ok-read's value; may or may not remain linearizable."""
    ops = list(hist)
    reads = [i for i, o in enumerate(ops)
             if o.type == "ok" and o.f == "read"]
    if not reads:
        return hist
    i = rng.choice(reads)
    bad = (ops[i].value or 0) + rng.randint(1, 3)
    ops[i] = ops[i].copy(value=bad)
    return History(ops, assign_indices=False)


def test_random_valid_histories_differential():
    rng = random.Random(7)
    hists = [random_register_history(rng, n_procs=rng.randint(2, 5),
                                     n_ops=rng.randint(10, 60))
             for _ in range(40)]
    m = model.cas_register()
    batch = wgl.analysis_batch(m, hists)
    for i, hist in enumerate(hists):
        host = wgl.search_host(encode(m, hist))
        obj = wgl.search_host_model(m, hist)
        assert host["valid?"] is True, f"history {i} host-invalid?"
        assert obj["valid?"] is True
        assert batch[i]["valid?"] is True, (i, batch[i])


def test_random_corrupted_histories_differential():
    rng = random.Random(21)
    hists = [corrupt(rng, random_register_history(
        rng, n_procs=rng.randint(2, 4), n_ops=rng.randint(10, 40)))
        for _ in range(40)]
    m = model.cas_register()
    batch = wgl.analysis_batch(m, hists)
    for i, hist in enumerate(hists):
        host = wgl.search_host(encode(m, hist), witness=True)
        obj = wgl.search_host_model(m, hist)
        assert host["valid?"] == obj["valid?"], i
        assert batch[i]["valid?"] == host["valid?"], (i, batch[i], host)


def test_mixed_batch_sizes():
    rng = random.Random(3)
    hists = [VALID_CASES["cas"], INVALID_CASES["wrong-read"], H(),
             random_register_history(rng, n_ops=25)]
    m = model.cas_register()
    out = wgl.analysis_batch(m, hists)
    assert [o["valid?"] for o in out[:3]] == [True, False, True]


def test_small_window_falls_back_to_host():
    """W=2 forces window overflows on concurrent histories; results must
    still be correct via host fallback."""
    rng = random.Random(11)
    m = model.cas_register()
    for _ in range(10):
        hist = random_register_history(rng, n_procs=5, n_ops=30)
        a = wgl.analysis(m, hist, W=2, F=4)
        assert a["valid?"] is True, a


def test_checker_integration():
    c = checker.linearizable({"model": model.cas_register()})
    res = checker.check(c, {}, VALID_CASES["write-read"])
    assert res["valid?"] is True
    res = checker.check(c, {}, INVALID_CASES["wrong-read"])
    assert res["valid?"] is False


def test_queue_model_analysis():
    hist = H(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
             ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1))
    a = wgl.analysis(model.unordered_queue(), hist)
    assert a["valid?"] is True
    hist = H(("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 9))
    a = wgl.analysis(model.unordered_queue(), hist)
    assert a["valid?"] is False


# ---------------------------------------------------------------------------
# Reach mode + segment-parallel long histories
# ---------------------------------------------------------------------------

def test_reach_mode_matches_host():
    rng = random.Random(5)
    m = model.cas_register()
    hists = [random_register_history(rng, n_procs=3, n_ops=30, crash_p=0)
             for _ in range(16)]
    encs = [encode(m, hh) for hh in hists]
    out, unk = wgl.check_batch_reach(encs)
    for i, e in enumerate(encs):
        if unk[i]:
            continue
        assert int(out[i]) == wgl.search_host_reach(e), i


def test_segment_cuts_are_sound():
    rng = random.Random(9)
    hist = random_register_history(rng, n_procs=4, n_ops=400, crash_p=0)
    enc = encode(model.cas_register(), hist)
    cuts = wgl.segment_cuts(enc, target_len=32)
    assert cuts[0] == 0 and cuts[-1] == enc.m
    for c in cuts[1:-1]:
        assert max(enc.ret_t[:c]) < enc.inv_t[c]


def test_segmented_valid_long_history():
    rng = random.Random(13)
    hist = random_register_history(rng, n_procs=4, n_ops=3000, crash_p=0)
    enc = encode(model.cas_register(), hist)
    res = wgl.check_segmented(enc, target_len=128)
    assert res is not None and res["valid?"] is True
    assert res["segments"] > 2


def test_segmented_invalid_long_history():
    rng = random.Random(17)
    hist = random_register_history(rng, n_procs=4, n_ops=3000, crash_p=0)
    bad = corrupt(rng, hist)
    m = model.cas_register()
    enc = encode(m, bad)
    seg = wgl.check_segmented(enc, target_len=128, witness=True)
    host = wgl.search_host(enc)
    if seg is not None:
        assert seg["valid?"] == host["valid?"], (seg, host)


def test_segmented_with_crashes_degrades_but_correct():
    rng = random.Random(23)
    hist = random_register_history(rng, n_procs=4, n_ops=1500,
                                   crash_p=0.03)
    m = model.cas_register()
    enc = encode(m, hist)
    seg = wgl.check_segmented(enc, target_len=64)
    if seg is not None:
        assert seg["valid?"] is True


def test_non_tabulable_model_uses_object_search():
    class ProcessMutex(model.Model):
        """Only the acquiring process may release — step() consults
        op.process, so it must opt out of tabulation."""
        tabulable = False

        def __init__(self, holder=None):
            self.holder = holder

        def step(self, o):
            if o.f == "acquire":
                if self.holder is not None:
                    return model.inconsistent("held")
                return ProcessMutex(o.process)
            if o.f == "release":
                if self.holder != o.process:
                    return model.inconsistent("not holder")
                return ProcessMutex(None)
            return model.inconsistent("unknown f")

    hist = H(("invoke", 0, "acquire", None), ("ok", 0, "acquire", None),
             ("invoke", 1, "release", None), ("ok", 1, "release", None))
    a = wgl.analysis(ProcessMutex(), hist)
    assert a["analyzer"] == "model"
    assert a["valid?"] is False  # p1 releasing p0's lock


# ---------------------------------------------------------------------------
# Round-3 advisor regressions
# ---------------------------------------------------------------------------

def test_unhashable_op_values_check_cleanly():
    """A list written into a register must not blow up state hashing and
    degrade the whole result to unknown (round-2 advisor finding)."""
    hist = H(("invoke", 0, "write", [1, 2]), ("ok", 0, "write", [1, 2]),
             ("invoke", 1, "read", None), ("ok", 1, "read", [1, 2]))
    for alg in ("tpu", "wgl", "model"):
        a = wgl.analysis(model.register(), hist, algorithm=alg)
        assert a["valid?"] is True, (alg, a)
    bad = H(("invoke", 0, "write", [1, 2]), ("ok", 0, "write", [1, 2]),
            ("invoke", 1, "read", None), ("ok", 1, "read", [9]))
    for alg in ("tpu", "wgl", "model"):
        a = wgl.analysis(model.register(), bad, algorithm=alg)
        assert a["valid?"] is False, (alg, a)


def test_witness_pending_reaches_past_mask_span():
    """All in-flight ops at the stuck point belong in the witness
    pending list, not just offsets inside the linearized-mask span
    (round-2 advisor finding: the scan stopped at bit_length()+1)."""
    hist = H(
        ("invoke", 0, "read", 5), ("invoke", 1, "read", 6),
        ("invoke", 2, "read", 7),
        ("ok", 0, "read", 5), ("ok", 1, "read", 6), ("ok", 2, "read", 7))
    a = wgl.analysis(model.cas_register(), hist, algorithm="wgl")
    assert a["valid?"] is False
    pend = a["configs"][0]["pending"]
    assert len(pend) == 3, a["configs"]


def test_segmented_with_crashes_matches_host():
    """Crashed ops forbid later cuts; the segmented path must not hand
    the giant trailing segment to the exponential host search (round-3
    review finding) and must stay correct."""
    import random

    from jepsen_tpu.tpu import synth

    hist = synth.register_history(3000, n_procs=4, seed=13, crash_p=0.02)
    enc = encode(model.cas_register(), hist)
    res = wgl.check_segmented(enc, target_len=256)
    if res is not None:  # may not segment at all under heavy crashes
        assert res["valid?"] is True, res
    a = wgl.analysis(model.cas_register(), hist)
    assert a["valid?"] is True, a


def test_segmented_prefix_screen_equivalent():
    """Screen on/off must agree (the screen only refutes soundly)."""
    from jepsen_tpu.tpu import synth

    hist = synth.register_history(6000, n_procs=5, seed=21)
    enc = encode(model.cas_register(), hist)
    r1 = wgl.check_segmented(enc, target_len=512, prefix_screen=96)
    r2 = wgl.check_segmented(enc, target_len=512, prefix_screen=0)
    assert r1["valid?"] == r2["valid?"] is True


def test_segmented_checkpoint_resume(tmp_path):
    """A crashed long check resumes from the checkpoint: the second
    run launches no device rows for already-resolved segments
    (SURVEY §5 checker-state checkpointing)."""
    from jepsen_tpu.tpu import synth

    hist = synth.register_history(1500, n_procs=4, seed=31)
    enc = encode(model.cas_register(), hist)
    ck = tmp_path / "frontier.jlog"
    r1 = wgl.check_segmented(enc, target_len=256, checkpoint_path=ck)
    assert r1 is not None and r1["valid?"] is True
    assert ck.exists()

    launched = []
    real = wgl._launch

    def spy(pb, rows, W, F, reach):
        launched.append(len(rows))
        return real(pb, rows, W, F, reach)

    wgl._launch = spy
    try:
        r2 = wgl.check_segmented(enc, target_len=256,
                                 checkpoint_path=ck)
    finally:
        wgl._launch = real
    assert r2["valid?"] is True
    assert launched in ([], [0]) or sum(launched) == 0, launched


def test_segmented_checkpoint_ignores_stale(tmp_path):
    from jepsen_tpu.tpu import synth

    h1 = synth.register_history(1500, n_procs=4, seed=32)
    h2 = synth.register_history(1500, n_procs=4, seed=33)
    ck = tmp_path / "frontier.jlog"
    e1 = encode(model.cas_register(), h1)
    e2 = encode(model.cas_register(), h2)
    wgl.check_segmented(e1, target_len=256, checkpoint_path=ck)
    # a different history must not reuse the checkpoint
    r = wgl.check_segmented(e2, target_len=256, checkpoint_path=ck)
    assert r["valid?"] is True


def test_segmented_checkpoint_model_mismatch_ignored(tmp_path):
    """The fingerprint covers the transition tables, so a checkpoint
    for one model never feeds another (round-3 review finding)."""
    from jepsen_tpu.tpu import synth

    hist = synth.register_history(1500, n_procs=4, seed=34)
    ck = tmp_path / "frontier.jlog"
    e1 = encode(model.cas_register(), hist)
    wgl.check_segmented(e1, target_len=256, checkpoint_path=ck)
    e2 = encode(model.register(), hist)  # different model, same history
    c1 = wgl._SegmentCheckpoint(ck, e1,
                                wgl.segment_cuts(e1, 256))
    c2 = wgl._SegmentCheckpoint(ck, e2,
                                wgl.segment_cuts(e2, 256))
    assert c1.fingerprint != c2.fingerprint
    assert c2.load() == {}


def test_segmented_checkpoint_survives_torn_tail(tmp_path):
    """Appends after a crash must stay reachable: torn tails truncate
    before the next write (round-3 review finding)."""
    from jepsen_tpu.tpu import synth

    hist = synth.register_history(1500, n_procs=4, seed=35)
    enc = encode(model.cas_register(), hist)
    ck = tmp_path / "frontier.jlog"
    wgl.check_segmented(enc, target_len=256, checkpoint_path=ck)
    n_before = len(wgl._SegmentCheckpoint(
        ck, enc, wgl.segment_cuts(enc, 256)).load())
    with open(ck, "r+b") as f:  # crash mid-record
        f.truncate(ck.stat().st_size - 3)
    c = wgl._SegmentCheckpoint(ck, enc, wgl.segment_cuts(enc, 256))
    got = c.load()
    assert len(got) == n_before - 1
    c.save_one(999, 0, 5)  # post-crash append
    c2 = wgl._SegmentCheckpoint(ck, enc, wgl.segment_cuts(enc, 256))
    got2 = c2.load()
    assert got2[(999, 0)] == 5  # reachable, not hidden by the tear
    assert len(got2) == n_before


def test_segmented_checkpoint_stale_file_resets(tmp_path):
    from jepsen_tpu.tpu import synth

    h1 = synth.register_history(1500, n_procs=4, seed=36)
    h2 = synth.register_history(1500, n_procs=4, seed=37)
    ck = tmp_path / "frontier.jlog"
    e1 = encode(model.cas_register(), h1)
    e2 = encode(model.cas_register(), h2)
    wgl.check_segmented(e1, target_len=256, checkpoint_path=ck)
    wgl.check_segmented(e2, target_len=256, checkpoint_path=ck)
    # the file was restarted for h2: its checkpoint now loads fully
    c = wgl._SegmentCheckpoint(ck, e2, wgl.segment_cuts(e2, 256))
    assert len(c.load()) > 0


def test_linearizable_checker_checkpoints_via_test_map(tmp_path):
    from jepsen_tpu import checker as chk
    from jepsen_tpu.tpu import synth

    hist = synth.register_history(6000, n_procs=4, seed=38)
    c = chk.linearizable({"model": model.cas_register()})
    test = {"checkpoint?": True, "store_dir": str(tmp_path)}
    out = c.check(test, hist)
    assert out["valid?"] is True
    files = list((tmp_path / "checker-frontier").glob("frontier-*.jlog"))
    assert files, "per-fingerprint checkpoint file expected"
    # a second, different-keyed check gets its OWN file (no collision)
    hist2 = synth.register_history(6000, n_procs=4, seed=39)
    out2 = c.check(test, hist2)
    assert out2["valid?"] is True
    files2 = list((tmp_path / "checker-frontier").glob(
        "frontier-*.jlog"))
    assert len(files2) == 2, files2


# ---------------------------------------------------------------------------
# Bounded anomaly path (time-to-first-anomaly)
# ---------------------------------------------------------------------------

def test_anomaly_path_localized_and_bounded():
    """An invalid long history must be explained by segment-localized
    witness extraction, not a whole-history host re-search: the check
    stays within ~2x the valid-check time (VERDICT r4 item 1; the
    reference's knossos pays unbounded search here, checker.clj:202-233).
    """
    import time as _t

    from jepsen_tpu.tpu import synth

    hist = synth.register_history(20_000, n_procs=5, seed=42)
    m = model.cas_register()
    wgl.analysis(m, hist)  # warm: XLA compiles out of the timed region
    t0 = _t.time()
    res_v = wgl.analysis(m, hist)
    tv = _t.time() - t0
    assert res_v["valid?"] is True
    assert res_v["analyzer"] == "tpu-segmented"

    bad, idx = synth.corrupt_register_history(hist, at_frac=0.85)
    t0 = _t.time()
    res_i = wgl.analysis(m, bad)
    ti = _t.time() - t0
    assert res_i["valid?"] is False
    assert res_i["analyzer"] == "tpu-segmented"
    lo, hi = res_i["segment-range"]
    # localized deep in the history (the corrupted read invokes past
    # ~60% of entries), not a from-the-start exhaustive search
    assert lo > 0.4 * 20_000, (lo, hi)
    # bounded: only ONE segment is host-searched for the witness
    # (generous slack: the box shows ~30% run-to-run noise)
    assert ti < 2.5 * tv + 10.0, (ti, tv)


def test_batch_invalid_member_localized():
    """A long invalid member of a batched check goes through segmented
    witness localization, not the whole-history host fallback."""
    from jepsen_tpu.tpu import synth

    good = synth.register_history(600, n_procs=4, seed=3)
    big = synth.register_history(6000, n_procs=5, seed=4)
    bad, _ = synth.corrupt_register_history(big, at_frac=0.8)
    res = wgl.analysis_batch(model.cas_register(), [good, bad])
    assert res[0]["valid?"] is True
    assert res[1]["valid?"] is False
    assert res[1]["witness-extraction"] == "segmented"
    assert "failed-segment" in res[1]


def _wide_register_history(n_values=40, bad_read=False):
    """Sequential write(i)/read(i) pairs over n_values distinct values:
    a register state space of n_values + 1 (initial None), which
    overflows the segmented checker's 32-bit reach masks."""
    evs = []
    for i in range(n_values):
        evs += [("invoke", 0, "write", i), ("ok", 0, "write", i),
                ("invoke", 1, "read", None), ("ok", 1, "read", i)]
    if bad_read:
        evs += [("invoke", 1, "read", None),
                ("ok", 1, "read", n_values + 7)]  # never written
    return H(*evs)


def test_segmented_fallback_over_32_states_is_loud(caplog):
    """ISSUE-4 satellite (VERDICT weak #6): the n_states > 32 bail in
    check_segmented emits a telemetry counter + warning naming the
    model instead of silently returning None."""
    import logging

    from jepsen_tpu import telemetry

    enc = encode(model.register(), _wide_register_history(40))
    assert enc.n_states > 32
    before = telemetry.get().counters().get(
        "wgl.segmented.fallback-states", 0)
    with caplog.at_level(logging.WARNING, logger="jepsen_tpu.tpu.wgl"):
        assert wgl.check_segmented(enc) is None
    after = telemetry.get().counters()["wgl.segmented.fallback-states"]
    assert after == before + 1
    warnings = [r.getMessage() for r in caplog.records]
    assert any("Register" in w and "32" in w for w in warnings), \
        warnings


def test_over_32_state_model_still_verdicts_via_fallback(monkeypatch):
    """A >32-state model must come back with a correct verdict through
    the whole-history fallback, on valid AND invalid histories, even
    when the history is long enough that analysis() tries the
    segmented path first."""
    monkeypatch.setattr(wgl, "SEGMENT_MIN_M", 8)
    m = model.register()
    good = wgl.analysis(m, _wide_register_history(40))
    assert good["valid?"] is True, good
    bad = wgl.analysis(m, _wide_register_history(40, bad_read=True))
    assert bad["valid?"] is False, bad
    # witness extraction still names the impossible read
    assert bad["op"] is not None and bad["op"].f == "read"


def test_corrupt_register_history_seeds_one_bad_read():
    from jepsen_tpu.tpu import synth

    hist = synth.register_history(500, n_procs=3, seed=7)
    bad, idx = synth.corrupt_register_history(hist, at_frac=0.5)
    # default bogus: one past the largest value in the write domain
    assert bad[idx].f == "read" and bad[idx].value == 5
    assert len(bad) == len(hist)
    # everything else untouched
    diffs = [i for i in range(len(hist))
             if (hist[i].type, hist[i].f, hist[i].value)
             != (bad[i].type, bad[i].f, bad[i].value)]
    assert diffs == [idx]
