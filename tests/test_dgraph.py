"""Dgraph suite tests: daemon orchestration via the dummy remote, an
in-memory dgraph (upsert blocks + snapshot txns with first-committer-
wins conflicts), and clusterless e2e runs of the workload menu —
healthy and with seeded upsert/index bugs (mirrors
dgraph/src/jepsen/dgraph/{support,client,upsert,delete}.clj)."""

import itertools
import threading

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import dgraph as dg


def make_test(responder=None, nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return t


def cmds(test, node):
    return [a for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_zero_peers_follow_node1(self):
        test = make_test()
        db = dg.DgraphDB()
        with control.with_session(test, "n2"):
            db._start_zero(test, "n2")
            db._start_alpha(test, "n2")
        got = " ; ".join(a.cmd for a in cmds(test, "n2"))
        assert "zero" in got and "idx=2" in got
        assert f"--peer n1:{dg.ZERO_PORT}" in got
        assert "alpha" in got and f"--zero n1:{dg.ZERO_PORT}" in got

    def test_node1_zero_has_no_peer(self):
        test = make_test()
        db = dg.DgraphDB()
        with control.with_session(test, "n1"):
            db._start_zero(test, "n1")
        got = " ; ".join(a.cmd for a in cmds(test, "n1"))
        assert "--peer" not in got and "idx=1" in got

    def test_kill_greps_binaries(self):
        test = make_test()
        db = dg.DgraphDB()
        with control.with_session(test, "n1"):
            db.kill(test, "n1")
        got = " ; ".join(a.cmd for a in cmds(test, "n1"))
        assert "dgraph" in got


class FakeDgraph:
    """In-memory dgraph: records are uid->predicate dicts; upsert
    blocks are atomic; explicit txns take a snapshot and conflict
    first-committer-wins on written uids. broken='double-upsert'
    defeats the insert-unless-exists condition every 3rd call (the
    duplicate-entity bug upsert.clj exists to catch);
    broken='dirty-index' leaves the index entry behind on delete."""

    def __init__(self, broken=None):
        self.lock = threading.Lock()
        self.broken = broken
        self.uids = itertools.count(1)
        self.ghosts: dict = {}    # (pred, key) -> stale index uids
        self.recs: dict = {}      # uid -> {pred: value}
        self.version = 0          # bumps on every commit
        self.write_log: dict = {} # uid -> version last written
        self.upsert_calls = 0

    # -- semantic interface (DgraphHTTP) --------------------------------

    def alter_schema(self, schema):
        pass

    def _find(self, pred, key):
        return [u for u, r in self.recs.items()
                if str(r.get(pred)) == str(key)]

    def upsert_unless_exists(self, pred, key, extra):
        with self.lock:
            self.upsert_calls += 1
            hit = self._find(pred, key)
            forced = (self.broken == "double-upsert"
                      and self.upsert_calls % 3 == 0)
            if hit and not forced:
                return None
            uid = f"0x{next(self.uids):x}"
            self.recs[uid] = dict(extra, **{pred: key})
            self.version += 1
            self.write_log[uid] = self.version
            return uid

    def delete_where(self, pred, key):
        with self.lock:
            hits = self._find(pred, key)
            for u in hits:
                if self.broken == "dirty-index":
                    # record goes, index entry stays: later reads see
                    # the ghost AND any recreated record (the stale-
                    # index bug delete.clj hunts)
                    self.ghosts.setdefault((pred, str(key)),
                                           []).append(u)
                del self.recs[u]
            self.version += 1
            return len(hits)

    def query_eq(self, pred, key, want=("uid",)):
        with self.lock:
            return self._rows(pred, key, want)

    def _rows(self, pred, key, want):
        out = []
        for u in self._find(pred, key):
            row = {}
            for w in want:
                if w == "uid":
                    row["uid"] = u
                elif w in self.recs[u]:
                    row[w] = self.recs[u][w]
            out.append(row)
        for u in self.ghosts.get((pred, str(key)), []):
            out.append({"uid": u} if "uid" in want else {})
        return out

    def write_value(self, pred, key, vpred, value):
        with self.lock:
            hits = self._find(pred, key)
            if hits:
                u = hits[0]
            else:
                u = f"0x{next(self.uids):x}"
                self.recs[u] = {pred: key}
            self.recs[u][vpred] = value
            self.version += 1
            self.write_log[u] = self.version

    # explicit txns: snapshot + first-committer-wins

    def txn_begin(self):
        with self.lock:
            import copy

            return {"snapshot": copy.deepcopy(self.recs),
                    "start_version": self.version,
                    "writes": [],     # (uid-or-new, pred, value)
                    "read_uids": set()}

    def txn_query(self, txn, pred, key, want=("uid",)):
        # effective view: snapshot + own writes (read-your-writes)
        import copy

        eff = copy.deepcopy(txn["snapshot"])
        for uid, p, v in txn["writes"]:
            rec = eff.setdefault(uid, {})
            try:
                rec[p] = int(v)
            except (TypeError, ValueError):
                rec[p] = v
        rows = []
        for u, r in eff.items():
            if str(r.get(pred)) == str(key):
                row = {}
                for w in want:
                    row[w] = u if w == "uid" else r.get(w)
                rows.append({k: v for k, v in row.items()
                             if v is not None})
                txn["read_uids"].add(u)
        return rows

    def txn_set(self, txn, nquads: str):
        for line in nquads.strip().splitlines():
            parts = line.strip().rstrip(" .").split(maxsplit=2)
            subj = parts[0].strip("<>")
            pred = parts[1].strip("<>")
            val = parts[2].strip('"')
            if subj.startswith("_:"):
                subj = f"new:{subj}:{id(txn)}"
            txn["writes"].append((subj, pred, val))

    def txn_commit(self, txn):
        with self.lock:
            written = {u for u, _p, _v in txn["writes"]
                       if not u.startswith("new:")}
            for u in written:
                if self.write_log.get(u, 0) > txn["start_version"]:
                    raise dg.TxnConflict(f"uid {u} written since "
                                         f"ts {txn['start_version']}")
            self.version += 1
            renames = {}
            for u, p, v in txn["writes"]:
                if u.startswith("new:"):
                    u = renames.setdefault(
                        u, f"0x{next(self.uids):x}")
                rec = self.recs.setdefault(u, {})
                try:
                    rec[p] = int(v)
                except (TypeError, ValueError):
                    rec[p] = v
                self.write_log[u] = self.version


class FakeHTTPFactory:
    def __init__(self, state=None):
        self.state = state or FakeDgraph()

    def __call__(self, test, node, timeout=10.0):
        return self.state


def run_clusterless(workload: dict, concurrency=6) -> dict:
    t = testing.noop_test()
    t.update(
        nodes=["n1", "n2", "n3"],
        concurrency=concurrency,
        client=workload["client"],
        checker=workload["checker"],
        generator=gen.clients(workload["generator"]))
    for extra in ("total-amount", "accounts"):
        if extra in workload:
            t[extra] = workload[extra]
    return core.run(t)


def _wl(name, state, **opts):
    w = dg.WORKLOADS[name](dict(opts))
    w["client"].http_factory = FakeHTTPFactory(state)
    w["client"].http = state
    w["client"].setup({})
    return w


class TestWorkloadsEndToEnd:
    def test_upsert_healthy(self):
        t = run_clusterless(_wl("upsert", FakeDgraph(),
                                key_count=4, group_size=3))
        assert t["results"]["valid?"] is True, t["results"]

    def test_upsert_detects_double_create(self):
        t = run_clusterless(_wl("upsert", FakeDgraph("double-upsert"),
                                key_count=4, group_size=3))
        assert t["results"]["valid?"] is False

    def test_delete_healthy(self):
        t = run_clusterless(_wl("delete", FakeDgraph(),
                                key_count=4, seed=5))
        assert t["results"]["valid?"] is True, t["results"]

    def test_delete_detects_dirty_index(self):
        t = run_clusterless(_wl("delete", FakeDgraph("dirty-index"),
                                key_count=3, seed=5,
                                ops_per_key=40))
        # leftover index entries accumulate -> some read sees >1 row
        assert t["results"]["valid?"] is False

    def test_register_linearizable(self):
        t = run_clusterless(_wl("linearizable-register", FakeDgraph(),
                                keys=[0, 1, 2], ops_per_key=40,
                                group_size=3, seed=3))
        assert t["results"]["valid?"] is True, t["results"]

    def test_set_healthy(self):
        t = run_clusterless(_wl("set", FakeDgraph(), ops=60))
        assert t["results"]["valid?"] is True, t["results"]

    def test_sequential(self):
        t = run_clusterless(_wl("sequential", FakeDgraph(), ops=60))
        assert t["results"]["valid?"] in (True, "unknown"), \
            t["results"]

    def test_bank_conserves(self):
        t = run_clusterless(_wl("bank", FakeDgraph(), ops=80))
        assert t["results"]["valid?"] is True, t["results"]

    def test_wr_txns(self):
        t = run_clusterless(_wl("wr", FakeDgraph(), ops=80))
        assert t["results"]["valid?"] is True, t["results"]

    def test_workload_registry_builds(self):
        for name, fn in dg.WORKLOADS.items():
            w = fn({"ops": 5})
            assert {"generator", "checker", "client"} <= set(w), name


class TestTraceClient:
    def test_spans_written(self, tmp_path):
        state = FakeDgraph()
        w = _wl("upsert", state, key_count=2, group_size=2)
        inner = w["client"]
        tc = dg.TraceClient(inner, path=str(tmp_path / "trace.jsonl"))
        c = tc.open({"nodes": ["n1"]}, "n1")
        c.invoke({}, Op(type="invoke", process=0, f="upsert",
                        value=(0, None)))
        c.invoke({}, Op(type="invoke", process=0, f="read",
                        value=(0, None)))
        lines = (tmp_path / "trace.jsonl").read_text().splitlines()
        assert len(lines) == 2
        import json

        span = json.loads(lines[0])
        assert span["f"] == "upsert" and span["node"] == "n1"
        assert span["end"] >= span["start"]
