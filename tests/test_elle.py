"""Elle-equivalent checker tests: hand-crafted anomaly fixtures for each
Adya class plus valid end-to-end histories through the real lifecycle
(mirrors how the reference's append.clj/wr.clj wrap elle and how
core_test.clj runs list-append against an in-memory store)."""

import itertools

from jepsen_tpu import checker, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.checker import cycle as cyc
from jepsen_tpu.history import History, op
from jepsen_tpu.tpu import elle
from jepsen_tpu import txn as txnlib


def T(*events):
    """history of txn ops from (type, process, mops) tuples."""
    return History([op(type=t, process=p, f="txn", value=m)
                    for t, p, m in events])


def H(*events):
    """history of ops from (type, process, f, mops) tuples."""
    return History([op(type=t, process=p, f=f, value=m)
                    for t, p, f, m in events])


def ok_txns(*pairs):
    """Interleave invoke/ok pairs sequentially: each pair is
    (process, invoked_mops, completed_mops)."""
    evs = []
    for p, inv, okv in pairs:
        evs.append(("invoke", p, inv))
        evs.append(("ok", p, okv))
    return T(*evs)


class TestTxnAlgebra:
    def test_ext_reads_writes(self):
        t = [["r", "x", 1], ["w", "x", 2], ["r", "x", 2], ["r", "y", 3]]
        assert txnlib.ext_reads(t) == {"x": 1, "y": 3}
        assert txnlib.ext_writes(t) == {"x": 2}
        assert txnlib.keys(t) == {"x", "y"}


class TestListAppend:
    def test_valid_sequential(self):
        h = ok_txns(
            (0, [["append", "x", 1]], [["append", "x", 1]]),
            (1, [["r", "x", None]], [["r", "x", [1]]]),
            (0, [["append", "x", 2]], [["append", "x", 2]]),
            (1, [["r", "x", None]], [["r", "x", [1, 2]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is True, res

    def test_g0_write_cycle(self):
        # T1 and T2 append to x and y in opposite orders; a reader
        # observes both interleavings -> ww cycle.
        h = T(("invoke", 0, [["append", "x", 1], ["append", "y", 1]]),
              ("invoke", 1, [["append", "x", 2], ["append", "y", 2]]),
              ("ok", 0, [["append", "x", 1], ["append", "y", 1]]),
              ("ok", 1, [["append", "x", 2], ["append", "y", 2]]),
              ("invoke", 2, [["r", "x", None], ["r", "y", None]]),
              ("ok", 2, [["r", "x", [1, 2]], ["r", "y", [2, 1]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "G0" in res["anomaly-types"], res

    def test_g1a_aborted_read(self):
        h = ok_txns(
            (0, [["append", "x", 9]], None),
            (1, [["r", "x", None]], [["r", "x", [9]]]))
        # rebuild: first txn fails
        h = T(("invoke", 0, [["append", "x", 9]]),
              ("fail", 0, [["append", "x", 9]]),
              ("invoke", 1, [["r", "x", None]]),
              ("ok", 1, [["r", "x", [9]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "G1a" in res["anomaly-types"], res

    def test_g1b_intermediate_read(self):
        h = ok_txns(
            (0, [["append", "x", 1], ["append", "x", 2]],
                [["append", "x", 1], ["append", "x", 2]]),
            (1, [["r", "x", None]], [["r", "x", [1]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "G1b" in res["anomaly-types"], res

    def test_g1c_wr_cycle(self):
        # T1 observes T2's write and vice versa.
        h = T(("invoke", 0, [["append", "x", 1], ["r", "y", None]]),
              ("invoke", 1, [["append", "y", 1], ["r", "x", None]]),
              ("ok", 0, [["append", "x", 1], ["r", "y", [1]]]),
              ("ok", 1, [["append", "y", 1], ["r", "x", [1]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "G1c" in res["anomaly-types"], res

    def test_g_single(self):
        # T1 reads x=[] but observes T2's y; T2 wrote x -> one rw edge.
        h = T(("invoke", 0, [["r", "x", None], ["r", "y", None]]),
              ("invoke", 1, [["append", "y", 1], ["append", "x", 1]]),
              ("ok", 1, [["append", "y", 1], ["append", "x", 1]]),
              ("ok", 0, [["r", "x", []], ["r", "y", [1]]]),
              ("invoke", 2, [["r", "x", None]]),
              ("ok", 2, [["r", "x", [1]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "G-single" in res["anomaly-types"], res

    def test_g2_write_skew(self):
        h = T(("invoke", 0, [["r", "x", None], ["append", "y", 1]]),
              ("invoke", 1, [["r", "y", None], ["append", "x", 1]]),
              ("ok", 0, [["r", "x", []], ["append", "y", 1]]),
              ("ok", 1, [["r", "y", []], ["append", "x", 1]]),
              ("invoke", 2, [["r", "x", None], ["r", "y", None]]),
              ("ok", 2, [["r", "x", [1]], ["r", "y", [1]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "G2-item" in res["anomaly-types"], res

    def test_incompatible_order(self):
        h = ok_txns(
            (0, [["r", "x", None]], [["r", "x", [1, 2]]]),
            (1, [["r", "x", None]], [["r", "x", [2, 1, 3]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "incompatible-order" in res["anomaly-types"]

    def test_internal(self):
        h = ok_txns(
            (0, [["append", "x", 5], ["r", "x", None]],
                [["append", "x", 5], ["r", "x", [1]]]),)
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "internal" in res["anomaly-types"]

    def test_duplicate_appends(self):
        h = ok_txns(
            (0, [["append", "x", 1]], [["append", "x", 1]]),
            (1, [["append", "x", 1]], [["append", "x", 1]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "duplicate-appends" in res["anomaly-types"]


class TestRwRegister:
    def test_valid(self):
        h = ok_txns(
            (0, [["w", "x", 1]], [["w", "x", 1]]),
            (1, [["r", "x", None]], [["r", "x", 1]]))
        res = elle.check_rw_register(h)
        assert res["valid?"] is True, res

    def test_g1a(self):
        h = T(("invoke", 0, [["w", "x", 7]]),
              ("fail", 0, [["w", "x", 7]]),
              ("invoke", 1, [["r", "x", None]]),
              ("ok", 1, [["r", "x", 7]]))
        res = elle.check_rw_register(h)
        assert res["valid?"] is False
        assert "G1a" in res["anomaly-types"]

    def test_wr_cycle(self):
        h = T(("invoke", 0, [["w", "x", 1], ["r", "y", None]]),
              ("invoke", 1, [["w", "y", 1], ["r", "x", None]]),
              ("ok", 0, [["w", "x", 1], ["r", "y", 1]]),
              ("ok", 1, [["w", "y", 1], ["r", "x", 1]]))
        res = elle.check_rw_register(h)
        assert res["valid?"] is False
        assert "G1c" in res["anomaly-types"], res


class TestEndToEnd:
    def test_list_append_lifecycle(self):
        """Full run against the in-memory strict-serializable store,
        checked with the elle engine (core_test.clj:69-120)."""
        state = testing.ListAppendState()
        g = cyc.append_gen(seed=7)
        test = testing.noop_test()
        test.update(
            nodes=["n1"], concurrency=5,
            client=testing.ListAppendClient(state),
            checker=cyc.append_checker(),
            generator=gen.clients(gen.limit(
                400, lambda: next(g))))
        test = core.run(test)
        assert test["results"]["valid?"] is True, test["results"]

    def test_scale_smoke(self):
        """A larger sequential history stays valid and fast."""
        g = cyc.append_gen(key_count=5, seed=3)
        state = testing.ListAppendState()
        evs = []
        for i, o in zip(range(3000), g):
            txn = o["value"]
            res = state.apply_txn(txn)
            evs.append(("invoke", i % 7, txn))
            evs.append(("ok", i % 7, res))
        res = elle.check_list_append(T(*evs))
        assert res["valid?"] is True, res["anomaly-types"]
        assert res["txn-count"] == 3000


class TestReviewRegressions:
    def test_unobservable_read_flagged(self):
        h = ok_txns((0, [["r", "x", None]], [["r", "x", [99]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is False
        assert "unobservable-read" in res["anomaly-types"]
        h = ok_txns((0, [["r", "x", None]], [["r", "x", 99]]))
        res = elle.check_rw_register(h)
        assert res["valid?"] is False
        assert "unobservable-read" in res["anomaly-types"]

    def test_retry_after_fail_is_not_duplicate(self):
        h = T(("invoke", 0, [["append", "x", 1]]),
              ("fail", 0, [["append", "x", 1]]),
              ("invoke", 0, [["append", "x", 1]]),
              ("ok", 0, [["append", "x", 1]]),
              ("invoke", 1, [["r", "x", None]]),
              ("ok", 1, [["r", "x", [1]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is True, res

    def test_info_append_observed_is_fine(self):
        h = T(("invoke", 0, [["append", "x", 1]]),
              ("info", 0, [["append", "x", 1]]),
              ("invoke", 1, [["r", "x", None]]),
              ("ok", 1, [["r", "x", [1]]]))
        res = elle.check_list_append(h)
        assert res["valid?"] is True, res


# ---------------------------------------------------------------------------
# Round-2 hardening: G1b/internal for rw-register, full realtime order
# ---------------------------------------------------------------------------

class TestRwHardening:
    def test_g1b_intermediate_read(self):
        """A txn writes x=1 then x=2; another committed txn reads x=1:
        the intermediate version escaped (ADVICE r1, elle G1b)."""
        hist = H(
            ("invoke", 0, "txn", [["w", "x", 1], ["w", "x", 2]]),
            ("ok", 0, "txn", [["w", "x", 1], ["w", "x", 2]]),
            ("invoke", 1, "txn", [["r", "x", None]]),
            ("ok", 1, "txn", [["r", "x", 1]]))
        res = elle.check_rw_register(hist)
        assert res["valid?"] is False
        assert "G1b" in res["anomaly-types"]

    def test_final_read_not_g1b(self):
        hist = H(
            ("invoke", 0, "txn", [["w", "x", 1], ["w", "x", 2]]),
            ("ok", 0, "txn", [["w", "x", 1], ["w", "x", 2]]),
            ("invoke", 1, "txn", [["r", "x", None]]),
            ("ok", 1, "txn", [["r", "x", 2]]))
        res = elle.check_rw_register(hist)
        assert "G1b" not in res["anomaly-types"]

    def test_internal_inconsistency(self):
        """A txn reads a value contradicting its own earlier write."""
        hist = H(
            ("invoke", 0, "txn", [["w", "x", 1], ["r", "x", None]]),
            ("ok", 0, "txn", [["w", "x", 1], ["r", "x", 2]]),
            ("invoke", 1, "txn", [["w", "x", 2]]),
            ("ok", 1, "txn", [["w", "x", 2]]))
        res = elle.check_rw_register(hist)
        assert res["valid?"] is False
        assert "internal" in res["anomaly-types"]

    def test_internal_consistent_ok(self):
        hist = H(
            ("invoke", 0, "txn", [["w", "x", 1], ["r", "x", None]]),
            ("ok", 0, "txn", [["w", "x", 1], ["r", "x", 1]]))
        res = elle.check_rw_register(hist)
        assert "internal" not in res["anomaly-types"]


class TestFullRealtime:
    def test_interval_order_cycle_beyond_last_completion(self):
        """A completes before B invokes, but another txn C completes in
        between with an earlier invocation — the old last-completion
        link (C -> B only) missed the A -> B realtime edge, so this
        G-single-realtime went undetected (VERDICT r1 weak #6)."""
        hist = H(
            ("invoke", 1, "txn", [["append", "z", 1]]),   # C starts
            ("invoke", 0, "txn", [["append", "y", 1]]),   # A starts
            ("ok", 0, "txn", [["append", "y", 1]]),       # A completes
            ("ok", 1, "txn", [["append", "z", 1]]),       # C completes
            ("invoke", 2, "txn", [["r", "y", None]]),     # B starts
            ("ok", 2, "txn", [["r", "y", []]]))           # missed y=1
        res = elle.check_list_append(hist)
        assert res["valid?"] is False
        assert any(t.endswith("-realtime") for t in res["anomaly-types"])

    def test_realtime_edges_complete(self):
        """Every completed-before pair is reachable through RT edges."""
        import itertools
        import random

        rng = random.Random(4)
        for _trial in range(20):
            txns = []
            t = 0
            for i in range(12):
                inv = t + rng.randrange(1, 4)
                comp = inv + rng.randrange(1, 8)
                t = inv
                txns.append(elle.Txn(i, None, "ok", i % 4, inv, comp,
                                     []))
            edges = [(s, d) for s, d, ty in elle._order_edges(txns)
                     if ty == elle.RT]
            adj = {}
            for s, d in edges:
                adj.setdefault(s, set()).add(d)
            # transitive closure
            reach = {i: set(adj.get(i, ())) for i in range(12)}
            for k, i, j in itertools.product(range(12), repeat=3):
                if k in reach[i] and j in reach[k]:
                    reach[i].add(j)
            for a, b in itertools.permutations(txns, 2):
                if a.complete_pos < b.invoke_pos:
                    assert b.i in reach[a.i], (a.i, b.i)


class TestEmptyReadRw:
    def test_empty_read_rw_edge_to_info_writer(self):
        """An :info append later observed by a read is provably
        committed; an empty read of that key must still produce the rw
        anti-dependency (round-3 review finding)."""
        hist = T(
            ("invoke", 0, [["append", "k", 1]]),
            ("info", 0, [["append", "k", 1]]),     # indeterminate...
            ("invoke", 1, [["r", "k", None]]),
            ("ok", 1, [["r", "k", [1]]]),          # ...but observed
            ("invoke", 2, [["r", "k", None]]),
            ("ok", 2, [["r", "k", []]]))           # missed k=1: cycle
        res = elle.check_list_append(hist)
        assert res["valid?"] is False
        assert any(t.endswith("-realtime") for t in res["anomaly-types"]), res

    def test_empty_read_before_writer_is_valid(self):
        hist = T(
            ("invoke", 0, [["r", "k", None]]),
            ("ok", 0, [["r", "k", []]]),
            ("invoke", 1, [["append", "k", 1]]),
            ("ok", 1, [["append", "k", 1]]),
            ("invoke", 2, [["r", "k", None]]),
            ("ok", 2, [["r", "k", [1]]]))
        res = elle.check_list_append(hist)
        assert res["valid?"] is True, res
