"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding
(pjit/shard_map over a Mesh) is exercised without TPU hardware. Must run
before the first `import jax` anywhere in the test session.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# Persistent compilation cache: the WGL/elle kernels compile once per
# shape bucket; cache across test runs. Env vars must be set before the
# `import jax` below — jax captures them at import time.
_cache = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# The sandbox's sitecustomize registers the real accelerator backend and
# overrides jax_platforms after import, so the env var alone is not
# enough: push the override through jax.config too. Opt out with
# JEPSEN_TPU_TEST_REAL_DEVICE=1 for a real-device run (tests needing
# more devices than the real machine has then skip via the
# `mesh`/`devices8` fixtures).
if os.environ.get("JEPSEN_TPU_TEST_REAL_DEVICE") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

# sitecustomize may have imported jax at interpreter start, before any
# of the env vars above — mirror them into jax.config so they stick.
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running e2e, excluded from tier-1 (-m 'not slow')")
    # the wgl kernels donate their packed segment tensors; backends
    # that can't alias them (CPU, which tier-1 forces) warn per
    # compile — pytest resets warning filters, so wgl.py's module
    # filter needs re-asserting here
    config.addinivalue_line(
        "filterwarnings",
        "ignore:Some donated buffers were not usable")
