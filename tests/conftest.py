"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding
(pjit/shard_map over a Mesh) is exercised without TPU hardware. Must run
before the first `import jax` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

# Persistent compilation cache: the WGL/elle kernels compile once per
# shape bucket; cache across test runs.
_cache = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
