"""Coverage atlas tests: record schema round-trip, fault folding,
anomaly-class outcomes (explicit negatives included), atlas merge
idempotence under re-analysis, gap-report/--suggest determinism, the
web heatmap, and the two-seeded-runs acceptance path from ISSUE 7."""

import json
import urllib.request

import pytest

from jepsen_tpu import checker as jchecker
from jepsen_tpu import core, coverage, testing, web
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as jnemesis
from jepsen_tpu import net
from jepsen_tpu import store as jstore
from jepsen_tpu.__main__ import _demo_responder
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import History, Op, op
from jepsen_tpu.workloads import sets as sets_wl


# ---------------------------------------------------------------------------
# Fault folding + taxonomy
# ---------------------------------------------------------------------------

class TestFaultFolding:
    def test_begin_end_pairs_to_window(self):
        acts = [
            {"kind": "partition", "f": "start", "phase": "begin",
             "t0": 10},
            {"kind": "partition", "f": "stop", "phase": "end",
             "t0": 20, "t1": 25},
            {"kind": "partition", "f": "start", "phase": "begin",
             "t0": 40},
        ]
        out = coverage.fold_faults(acts)
        assert out == [{"kind": "partition", "count": 2,
                        "windows": [[10, 25], [40, None]]}]

    def test_pulse_is_degenerate_window(self):
        out = coverage.fold_faults(
            [{"kind": "file-bitflip", "f": "bitflip",
              "phase": "pulse", "t0": 7, "t1": 9}])
        assert out == [{"kind": "file-bitflip", "count": 1,
                        "windows": [[7, 9]]}]

    def test_harness_counts_ride_along(self):
        out = coverage.fold_faults([], {"harness-drop-connection": 3})
        assert out == [{"kind": "harness-drop-connection", "count": 3,
                        "windows": []}]

    def test_faults_from_history_fallback(self):
        """The interpreter journals each nemesis op twice (dispatch
        invocation + completion, both info on the nemesis process):
        the fallback must count each activation ONCE, matching the
        live recorder."""
        hist = History([
            op(type="info", process="nemesis", f="start-partition",
               value=None, time=5),
            op(type="info", process="nemesis", f="start-partition",
               value="isolated", time=6),
            op(type="invoke", process=0, f="read", value=None,
               time=7),
            op(type="ok", process=0, f="read", value=1, time=8),
            op(type="info", process="nemesis", f="stop-partition",
               value=None, time=9),
            op(type="info", process="nemesis", f="stop-partition",
               value="healed", time=10),
        ])
        out = coverage.faults_from_history(hist)
        assert out == [{"kind": "partition", "count": 1,
                        "windows": [[5, 9]]}]

    def test_fallback_counts_match_live_recording(self, tmp_path):
        """End-to-end pin of the double-journal hazard: the same run's
        history-derived fault counts must equal the live recorder's
        (a crash-recovered run must not report 2x the injections)."""
        t = core.run(_partitioned_register_test(tmp_path))
        rec = coverage.load_record(t["store_dir"])
        live = {f["kind"]: f["count"] for f in rec["faults"]}
        derived = {f["kind"]: f["count"]
                   for f in coverage.faults_from_history(t["history"])}
        assert live == derived
        assert live.get("partition", 0) >= 1
        # the schedule signature counts each journaled pair once too
        n_entries = sum(1 for o in t["history"]
                        if not isinstance(o.process, int))
        assert rec["signature"]["nemesis-ops"] * 2 == n_entries

    def test_nemesis_declared_kinds(self):
        assert jnemesis.partition_random_halves().fault_kinds() == {
            "start": ("partition", "begin"),
            "stop": ("partition", "end")}
        assert jnemesis.hammer_time("x").fault_kinds() == {
            "start": ("process-pause", "begin"),
            "stop": ("process-pause", "end")}

    def test_validate_wrapper_records_activation(self):
        """The nemesis Validate wrapper records every completed fault
        activation with its nemesis-declared kind + span window."""
        rec = coverage.Recorder()

        class Boring(jnemesis.Nemesis):
            def invoke(self, test, o):
                return o

            def fs(self):
                return {"start", "stop"}

            def fault_kinds(self):
                return {"start": ("partition", "begin"),
                        "stop": ("partition", "end")}

        import unittest.mock as mock

        from jepsen_tpu import util

        util.init_relative_time()
        v = jnemesis.validate(Boring())
        with mock.patch.object(coverage, "_global", rec):
            v.invoke({}, Op(index=0, time=0, type="info",
                            process="nemesis", f="start", value=None))
            v.invoke({}, Op(index=1, time=1, type="info",
                            process="nemesis", f="stop", value=None))
        faults = coverage.fold_faults(rec.activations())
        assert len(faults) == 1 and faults[0]["kind"] == "partition"
        assert faults[0]["count"] == 1
        assert len(faults[0]["windows"]) == 1
        t0, t1 = faults[0]["windows"][0]
        assert t1 is not None and t1 >= t0 >= 0


# ---------------------------------------------------------------------------
# Anomaly outcomes
# ---------------------------------------------------------------------------

class TestAnomalyOutcomes:
    def test_explicit_negative_results(self):
        """A valid verdict still reports every checked class — the
        'fault fired, anomaly class checked, none found' cell."""
        results = {"valid?": True,
                   "workload": {"valid?": True,
                                "anomaly-classes": {
                                    "nonlinearizable": "clean"}}}
        out = coverage.anomaly_outcomes(results)
        assert out == [{"class": "nonlinearizable",
                        "checker": "workload",
                        "outcome": "clean"}]

    def test_witnessed_carries_op_indices(self):
        results = {"valid?": False,
                   "workload": {
                       "valid?": False,
                       "anomaly-classes": {"G1a": "witnessed",
                                           "G0": "clean"},
                       "anomalies": {"G1a": [
                           {"op-indices": [3, 7]}]}}}
        out = {a["class"]: a for a in
               coverage.anomaly_outcomes(results)}
        assert out["G1a"]["outcome"] == "witnessed"
        assert out["G1a"]["op-indices"] == [3, 7]
        assert out["G0"]["outcome"] == "clean"

    def test_witnessed_dominates_across_checkers(self):
        results = {
            "a": {"anomaly-classes": {"set-lost": "clean"}},
            "b": {"anomaly-classes": {"set-lost": "witnessed"}}}
        out = coverage.anomaly_outcomes(results)
        assert out[0]["outcome"] == "witnessed"

    def test_watchdog_is_a_checked_class(self):
        out = coverage.anomaly_outcomes(
            {"valid?": True, "watchdog": {"count": 2}})
        assert out == [{"class": "watchdog", "checker": "watchdog",
                        "outcome": "witnessed"}]

    def test_checker_taggers(self):
        """The checker-module taxonomy threads: every family attaches
        anomaly-classes with explicit negatives."""
        hist = History([
            op(type="invoke", process=0, f="add", value=1),
            op(type="ok", process=0, f="add", value=1),
            op(type="invoke", process=0, f="read", value=None),
            op(type="ok", process=0, f="read", value=[1]),
        ])
        res = jchecker.check(jchecker.set_checker(), {}, hist)
        assert res["anomaly-classes"] == {"set-lost": "clean",
                                          "set-unexpected": "clean"}
        lossy = History([
            op(type="invoke", process=0, f="add", value=1),
            op(type="ok", process=0, f="add", value=1),
            op(type="invoke", process=0, f="read", value=None),
            op(type="ok", process=0, f="read", value=[]),
        ])
        res = jchecker.check(jchecker.set_checker(), {}, lossy)
        assert res["anomaly-classes"]["set-lost"] == "witnessed"

    def test_elle_checked_classes(self):
        from jepsen_tpu.tpu import elle

        hist = History([
            op(type="invoke", process=0, f="txn",
               value=[["append", "x", 1]]),
            op(type="ok", process=0, f="txn",
               value=[["append", "x", 1]]),
        ])
        res = elle.check_list_append(hist, {"engine": "host"})
        classes = res["anomaly-classes"]
        assert set(classes) == set(elle.CHECKED_APPEND)
        assert all(v == "clean" for v in classes.values())


# ---------------------------------------------------------------------------
# Record schema
# ---------------------------------------------------------------------------

def _synthetic_test(tmp_path=None, results=None):
    hist = History([
        op(type="info", process="nemesis", f="start-partition",
           value=None, time=2),
        op(type="invoke", process=0, f="read", value=None, time=3),
        op(type="ok", process=0, f="read", value=1, time=4),
        op(type="info", process="nemesis", f="stop-partition",
           value=None, time=5),
    ])
    t = {"name": "synthetic", "concurrency": 2,
         "spec": {"workload": "register",
                  "opts": {"rate": 10, "ops": 4}},
         "history": hist,
         "results": results if results is not None else {
             "valid?": True,
             "workload": {"valid?": True,
                          "anomaly-classes": {
                              "nonlinearizable": "clean"}}}}
    if tmp_path is not None:
        d = tmp_path / "store" / "synthetic" / "20260801T000000.0000"
        d.mkdir(parents=True, exist_ok=True)
        t["store_dir"] = str(d)
    return t


class TestRecordSchema:
    def test_round_trip(self, tmp_path):
        test = _synthetic_test(tmp_path)
        rec = coverage.write_record(test,
                                    recorder=coverage.Recorder())
        assert coverage.validate_record(rec) > 0
        loaded = coverage.load_record(test["store_dir"])
        assert coverage.validate_record(loaded) > 0
        assert loaded == json.loads(json.dumps(rec))
        assert loaded["workload"] == "register"
        # the history fallback classified the partition window
        assert loaded["faults"] == [
            {"kind": "partition", "count": 1, "windows": [[2, 5]]}]
        assert loaded["anomalies"][0]["outcome"] == "clean"
        assert loaded["signature"]["client-ops"] == 1

    def test_live_recorder_wins_over_history(self):
        rec = coverage.Recorder()
        rec.record("process-pause", "start", "begin", 1, 2)
        out = coverage.build_record(_synthetic_test(), recorder=rec)
        assert [f["kind"] for f in out["faults"]] == ["process-pause"]

    @pytest.mark.parametrize("mutate", [
        lambda r: r.pop("run"),
        lambda r: r.__setitem__("schema", 99),
        lambda r: r.__setitem__("faults", {"not": "a list"}),
        lambda r: r["faults"].append({"count": 1}),
        lambda r: r["faults"].append({"kind": "x", "count": -1}),
        lambda r: r["faults"].append(
            {"kind": "x", "count": 1, "windows": [[1]]}),
        lambda r: r["anomalies"].append({"class": "g",
                                         "outcome": "meh"}),
        lambda r: r["anomalies"].append(
            {"class": "g", "outcome": "clean", "op-indices": ["x"]}),
    ])
    def test_validate_rejects_bad_records(self, mutate):
        rec = coverage.build_record(_synthetic_test(),
                                    recorder=coverage.Recorder())
        mutate(rec)
        with pytest.raises(ValueError):
            coverage.validate_record(rec)


# ---------------------------------------------------------------------------
# Atlas merge semantics
# ---------------------------------------------------------------------------

class TestAtlas:
    def test_append_and_aggregate(self, tmp_path):
        rec = coverage.build_record(_synthetic_test(),
                                    recorder=coverage.Recorder())
        coverage.append_run(tmp_path, rec)
        entries = coverage.read_atlas(tmp_path / coverage.ATLAS_FILE)
        assert coverage.validate_atlas(entries) == 1
        cells = coverage.aggregate(entries)
        assert cells[("partition", "register",
                      "nonlinearizable")]["runs"] == 1

    def test_reappend_same_digest_is_noop(self, tmp_path):
        rec = coverage.build_record(_synthetic_test(),
                                    recorder=coverage.Recorder())
        coverage.append_run(tmp_path, rec)
        coverage.append_run(tmp_path, rec)
        path = tmp_path / coverage.ATLAS_FILE
        assert len(coverage.read_atlas(path)) == 1

    def test_reanalysis_replaces_not_doubles(self, tmp_path):
        """The --resume contract: a changed re-analysis of the same
        run appends a new line, but aggregation counts the run ONCE
        (newest entry wins)."""
        test = _synthetic_test()
        rec1 = coverage.build_record(test,
                                     recorder=coverage.Recorder())
        coverage.append_run(tmp_path, rec1)
        test["results"]["workload"]["anomaly-classes"][
            "nonlinearizable"] = "witnessed"
        test["results"]["valid?"] = False
        rec2 = coverage.build_record(test,
                                     recorder=coverage.Recorder())
        coverage.append_run(tmp_path, rec2)
        entries = coverage.read_atlas(tmp_path / coverage.ATLAS_FILE)
        assert len(entries) == 2  # journal keeps both lines...
        cells = coverage.aggregate(entries)
        cell = cells[("partition", "register", "nonlinearizable")]
        assert cell["runs"] == 1  # ...but the run counts once
        assert cell["witnessed"] == 1 and cell["clean"] == 0

    def test_torn_tail_tolerated(self, tmp_path):
        rec = coverage.build_record(_synthetic_test(),
                                    recorder=coverage.Recorder())
        coverage.append_run(tmp_path, rec)
        path = tmp_path / coverage.ATLAS_FILE
        with open(path, "a") as f:
            f.write('{"run": "torn')  # writer died mid-append
        assert len(coverage.read_atlas(path)) == 1

    def test_no_fault_run_lands_in_none_column(self, tmp_path):
        test = _synthetic_test()
        test["history"] = History([
            op(type="invoke", process=0, f="read", value=None),
            op(type="ok", process=0, f="read", value=1),
        ])
        rec = coverage.build_record(test,
                                    recorder=coverage.Recorder())
        cells = coverage.aggregate([coverage.atlas_entry(rec)])
        assert ("none", "register", "nonlinearizable") in cells

    def test_sync_store_folds_run_dirs(self, tmp_path):
        test = _synthetic_test(tmp_path)
        coverage.write_record(test, recorder=coverage.Recorder())
        base = tmp_path / "store"
        assert coverage.sync_store(base) == 1
        assert coverage.sync_store(base) == 0  # second sync: no-op
        entries = coverage.read_atlas(base / coverage.ATLAS_FILE)
        assert len(entries) == 1


# ---------------------------------------------------------------------------
# Matrix, gaps, suggestions
# ---------------------------------------------------------------------------

def _two_run_cells():
    clean = coverage.atlas_entry({
        "run": "a/1", "ts": 1.0, "workload": "register",
        "faults": [{"kind": "partition", "count": 2, "windows": []}],
        "anomalies": [{"class": "nonlinearizable",
                       "outcome": "clean"}],
        "valid": True})
    witnessed = coverage.atlas_entry({
        "run": "b/1", "ts": 2.0, "workload": "set",
        "faults": [],
        "anomalies": [{"class": "set-lost", "outcome": "witnessed"},
                      {"class": "set-unexpected",
                       "outcome": "clean"}],
        "valid": False})
    return coverage.aggregate([clean, witnessed])


class TestMatrixAndSuggest:
    def test_matrix_shows_all_three_cell_states(self):
        cells = _two_run_cells()
        txt = coverage.matrix_text(cells, ["register", "set", "bank"])
        assert "X" in txt and "o" in txt and "·" in txt
        assert "partition" in txt

    def test_gap_report_counts_unexercised_cells(self):
        cells = _two_run_cells()
        gs = coverage.gaps(cells, ["register", "set"])
        assert ("db-kill", "register") in gs
        assert ("partition", "register") not in gs
        assert ("none", "set") not in gs

    def test_suggest_deterministic_and_diverse(self):
        cells = _two_run_cells()
        s1 = coverage.suggest(cells, ["register", "set", "bank"],
                              limit=6)
        s2 = coverage.suggest(cells, ["register", "set", "bank"],
                              limit=6)
        assert s1 == s2  # pure function of the atlas: deterministic
        assert len({s["fault"] for s in s1}) == 6  # diversified
        assert all(s["config"] for s in s1)

    def test_suggest_names_runnable_config_for_gap(self):
        cells = _two_run_cells()
        got = coverage.suggest(cells, ["bank"], limit=50)
        partition_gap = [s for s in got
                         if s["fault"] == "partition"
                         and s["workload"] == "bank"]
        assert partition_gap and "--nemesis partition" in \
            partition_gap[0]["config"]

    def test_prometheus_lines_scrape_parse(self):
        from jepsen_tpu.reports.profile import \
            validate_prometheus_text

        lines = coverage.prometheus_lines(_two_run_cells())
        n = validate_prometheus_text("\n".join(lines) + "\n")
        assert n > 0
        joined = "\n".join(lines)
        assert "jepsen_tpu_coverage_runs" in joined
        assert 'jepsen_tpu_coverage_cells{status="witnessed"} 1' in \
            joined


# ---------------------------------------------------------------------------
# End-to-end: two seeded runs -> atlas -> CLI + web (+ --resume)
# ---------------------------------------------------------------------------

def _partitioned_register_test(tmp_path):
    """A clean register run under a real (dummy-remote) partition
    nemesis: the canonical negative-result cell."""
    net.clear_ip_cache()
    state = testing.AtomState()
    import random as _random

    rng = _random.Random(11)
    from jepsen_tpu.workloads import register as register_wl

    t = testing.noop_test()
    t.update(
        name="cov-register", store_base=str(tmp_path),
        nodes=["n1", "n2"], concurrency=4,
        remote=DummyRemote(_demo_responder),
        client=testing.AtomClient(state),
        nemesis=jnemesis.partition_random_halves(),
        checker=jchecker.compose({
            "stats": jchecker.stats(),
            "workload": jchecker.checker(
                lambda test, hist, opts: jchecker.anomaly_classes(
                    {"valid?": True}, nonlinearizable=False))}),
        generator=gen.clients(
            gen.limit(30, lambda: register_wl.cas_op_mix(
                rng, n_values=3)),
            gen.limit(4, gen.cycle(gen.phases(
                {"type": "info", "f": "start"},
                {"type": "info", "f": "stop"})))))
    t["spec"] = {"workload": "register", "opts": {"ops": 30}}
    return t


def _lossy_set_test(tmp_path):
    """A set run whose client acks-then-drops adds: the witnessed
    cell, with no nemesis (the `none` baseline column)."""
    w = sets_wl.workload({"ops": 40})
    t = testing.noop_test()
    t.update(
        name="cov-set", store_base=str(tmp_path),
        nodes=["n1", "n2"], concurrency=4,
        client=testing.SetClient(drop_every=5),
        checker=w["checker"],
        generator=gen.clients(w["generator"]))
    t["spec"] = {"workload": "set", "opts": {"ops": 40}}
    return t


class TestEndToEnd:
    def test_two_runs_build_the_acceptance_matrix(self, tmp_path):
        t1 = core.run(_partitioned_register_test(tmp_path))
        t2 = core.run(_lossy_set_test(tmp_path))
        assert t1["results"]["valid?"] is True
        assert t2["results"]["valid?"] is False

        # per-run records landed and validate
        for t in (t1, t2):
            rec = coverage.load_record(t["store_dir"])
            assert rec and coverage.validate_record(rec) > 0
        rec1 = coverage.load_record(t1["store_dir"])
        assert [f["kind"] for f in rec1["faults"]] == ["partition"]
        assert rec1["faults"][0]["count"] >= 1
        assert rec1["faults"][0]["windows"]

        entries = coverage.read_atlas(
            tmp_path / coverage.ATLAS_FILE)
        assert coverage.validate_atlas(entries) == 2
        cells = coverage.aggregate(entries)
        # the acceptance triple: a witnessed cell, a checked-but-
        # clean cell, and a never-exercised gap
        assert cells[("none", "set", "set-lost")]["witnessed"] == 1
        assert cells[("partition", "register",
                      "nonlinearizable")]["clean"] == 1
        assert ("db-kill", "register") in coverage.gaps(
            cells, ["register", "set"])
        # --suggest names a config filling a gap
        sug = coverage.suggest(cells, ["register", "set"], limit=50)
        assert any(s["fault"] == "db-kill" for s in sug)

        # the CLI renders the same matrix
        from jepsen_tpu import cli as jcli

        cmd = jcli.coverage_cmd(["register", "set"])["coverage"]
        import argparse

        p = cmd["parser_fn"](argparse.ArgumentParser())
        opts = p.parse_args(["--store", str(tmp_path),
                             "--suggest", "3"])
        assert cmd["run"](opts) == 0

        # atlas re-aggregation after analyze --resume: unchanged.
        # test_fn rebuilds the same checker stack from the spec (the
        # suite-builder path analyze_cmd wires for real runs)
        from jepsen_tpu import resume as jresume

        def set_test_fn(opts):
            return {"checker": sets_wl.workload(
                {"ops": opts.get("ops", 40)})["checker"]}

        before = {k: v["runs"] for k, v in cells.items()}
        jresume.analyze_run(t2["store_dir"], resume=True,
                            test_fn=set_test_fn)
        entries2 = coverage.read_atlas(
            tmp_path / coverage.ATLAS_FILE)
        after = {k: v["runs"]
                 for k, v in coverage.aggregate(entries2).items()}
        assert after == before

    def test_web_heatmap_smoke(self, tmp_path):
        core.run(_lossy_set_test(tmp_path))
        server = web.serve("127.0.0.1", 0, base=tmp_path)
        port = server.server_address[1]
        try:
            base = f"http://127.0.0.1:{port}"
            page = urllib.request.urlopen(
                base + "/coverage/").read().decode()
            assert "coverage atlas" in page
            assert "cov-set" not in page  # runs live on cell pages
            cell = urllib.request.urlopen(
                base + "/coverage/none/set").read().decode()
            assert "set-lost" in cell
            assert "cov-set" in cell  # deep link to witnessing run
            home = urllib.request.urlopen(base + "/").read().decode()
            assert "/coverage/" in home
        finally:
            server.shutdown()
