"""Consul suite tests: DB command emission via the dummy remote, KV
driver parsing, index-based CAS semantics, and a clusterless
end-to-end register run (mirrors consul/src/jepsen/consul/*.clj)."""

import base64
import json
import threading

from jepsen_tpu import checker as chk
from jepsen_tpu import control, core, independent, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.checker import models
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.suites import consul


def getent_responder(node, action):
    if action.cmd.startswith("getent ahostsv4"):
        host = action.cmd.split()[-1]
        n = int(str(host).lstrip("n") or 1)
        return f"10.0.0.{n}    STREAM {host}\n"
    if action.cmd.startswith("stat "):  # nothing cached on the "node"
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "consul"
    return None


def make_test(responder=getent_responder, nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return t


def cmds(test, node):
    return [a.cmd for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_primary_bootstraps(self):
        test = make_test()
        db = consul.ConsulDB("1.6.1", http_factory=None)
        with control.with_session(test, "n1"):
            db.setup(test, "n1")
        got = " ; ".join(cmds(test, "n1"))
        assert "consul_1.6.1_linux_amd64.zip" in got
        assert "-bootstrap" in got
        assert "-retry-join" not in got
        assert "-bind 10.0.0.1" in got
        assert "-node n1" in got

    def test_secondary_joins_primary(self):
        test = make_test()
        db = consul.ConsulDB(http_factory=None)
        with control.with_session(test, "n2"):
            db.setup(test, "n2")
        got = " ; ".join(cmds(test, "n2"))
        assert "-retry-join 10.0.0.1" in got
        assert "-bootstrap " not in got
        assert "-bind 10.0.0.2" in got

    def test_teardown_removes_state(self):
        test = make_test()
        db = consul.ConsulDB(http_factory=None)
        with control.with_session(test, "n3"):
            db.teardown(test, "n3")
        got = " ; ".join(cmds(test, "n3"))
        assert "/var/lib/consul" in got
        assert "/opt/consul" in got

    def test_restart_rejoins_never_bootstraps(self):
        test = make_test()
        db = consul.ConsulDB(http_factory=None)
        with control.with_session(test, "n1"):
            db.start(test, "n1")
        got = " ; ".join(cmds(test, "n1"))
        assert "-retry-join" in got and "-bootstrap" not in got


class FakeConsulState:
    """In-memory consul KV speaking the HTTP API's JSON shapes, with
    per-key ModifyIndex and ?cas= semantics (cas=0 creates iff
    absent)."""

    def __init__(self, nodes=("n1", "n2", "n3")):
        self.lock = threading.Lock()
        self.kv: dict = {}  # key -> (value, modify_index)
        self.index = 0
        self.nodes = list(nodes)
        self.requests: list = []  # (method, path, params)

    def request(self, method, path, params=None, body=None):
        self.requests.append((method, path, dict(params or {})))
        with self.lock:
            if path == "/v1/catalog/nodes":
                return 200, json.dumps(
                    [{"Node": n} for n in self.nodes])
            assert path.startswith("/v1/kv/")
            key = path[len("/v1/kv/"):]
            if method == "GET":
                if key not in self.kv:
                    return 404, ""
                value, idx = self.kv[key]
                return 200, json.dumps([{
                    "Key": key, "ModifyIndex": idx,
                    "Value": base64.b64encode(
                        value.encode()).decode()}])
            if method == "PUT":
                params = params or {}
                if "cas" in params:
                    current = self.kv.get(key, (None, 0))[1]
                    if int(params["cas"]) != current:
                        return 200, "false"
                self.index += 1
                self.kv[key] = (body, self.index)
                return 200, "true"
            raise AssertionError(f"unexpected {method} {path}")


class FakeHttpFactory:
    def __init__(self, state=None):
        self.state = state or FakeConsulState()

    def __call__(self, node, consistency=None, timeout=5.0):
        http = consul.ConsulHttp(node, consistency=consistency,
                                 timeout=timeout)
        http.request = self.state.request
        return http


class TestKvDriver:
    def test_get_missing_key(self):
        http = FakeHttpFactory()("n1")
        assert http.get("register/0") == (None, None)

    def test_put_then_get_roundtrips_base64(self):
        f = FakeHttpFactory()
        http = f("n1")
        http.put("register/0", "3")
        value, idx = http.get("register/0")
        assert value == "3" and idx == 1

    def test_cas_success_and_value_mismatch(self):
        f = FakeHttpFactory()
        http = f("n1")
        http.put("k", "1")
        assert http.cas("k", "1", "2") is True
        assert http.get("k")[0] == "2"
        assert http.cas("k", "1", "9") is False  # old value gone
        assert http.get("k")[0] == "2"

    def test_cas_on_missing_key_fails(self):
        http = FakeHttpFactory()("n1")
        assert http.cas("nope", "1", "2") is False

    def test_cas_index_race_loses(self):
        """A concurrent write between the read and the guarded PUT
        bumps ModifyIndex, so the CAS must fail."""
        f = FakeHttpFactory()
        http = f("n1")
        http.put("k", "1")
        real_request = http.request
        raced = {"done": False}

        def racing_request(method, path, params=None, body=None):
            if (method == "PUT" and "cas" in (params or {})
                    and not raced["done"]):
                raced["done"] = True
                real_request("PUT", path, {}, "1")  # sneak a write in
            return real_request(method, path, params, body)

        http.request = racing_request
        assert http.cas("k", "1", "2") is False
        assert f.state.kv["k"][0] == "1"

    def test_consistency_param_threads_through(self):
        f = FakeHttpFactory()
        http = f("n1", consistency="stale")
        http.put("k", "1")
        http.get("k")
        gets = [p for (m, path, p) in f.state.requests if m == "GET"]
        assert all("stale" in p for p in gets)

    def test_await_cluster_ready(self):
        f = FakeHttpFactory(FakeConsulState(nodes=["n1", "n2"]))
        consul.await_cluster_ready(f("n1"), 2, timeout_secs=1)

    def test_await_cluster_ready_times_out(self):
        import pytest

        from jepsen_tpu import util

        f = FakeHttpFactory(FakeConsulState(nodes=["n1"]))
        with pytest.raises(util.Timeout):
            consul.await_cluster_ready(f("n1"), 3, timeout_secs=0.1)


class TestEndToEnd:
    def test_register_workload_clusterless(self):
        factory = FakeHttpFactory()
        opts = {"concurrency": 6, "keys": 2, "ops_per_key": 60,
                "seed": 7}
        w = consul.register_workload(opts)
        w["client"].http_factory = factory

        test = testing.noop_test()
        test.update(
            nodes=["n1", "n2", "n3"], concurrency=6,
            client=w["client"],
            checker=w["checker"],
            generator=gen.clients(gen.stagger(0.0005, w["generator"])))
        test = core.run(test)
        assert test["results"]["valid?"] is True
        # both keys saw ops, with reads, writes and cas attempts
        fs = {op.f for op in test["history"]}
        assert fs == {"read", "write", "cas"}
        keys = {independent.key_(op.value) for op in test["history"]
                if op.value is not None}
        assert {0, 1} <= keys

    def test_phantom_read_detected(self):
        """A fake that returns a never-written value on one read must
        fail the linearizable checker (values are drawn from 0..4, so
        99 is impossible under any ordering)."""

        class PhantomState(FakeConsulState):
            def __init__(self):
                super().__init__()
                self.reads = 0

            def request(self, method, path, params=None, body=None):
                if method == "GET" and path.startswith("/v1/kv/"):
                    self.reads += 1
                    # every GET from the 20th on: a cas's internal
                    # pre-read swallowing a single phantom would hide
                    # the anomaly from the reading threads
                    if self.reads >= 20:
                        return 200, json.dumps([{
                            "Key": path[len("/v1/kv/"):],
                            "ModifyIndex": 1,
                            "Value": base64.b64encode(
                                b"99").decode()}])
                return super().request(method, path, params, body)

        factory = FakeHttpFactory(PhantomState())
        opts = {"concurrency": 4, "keys": 1, "ops_per_key": 80,
                "seed": 3}
        w = consul.register_workload(opts)
        w["client"].http_factory = factory

        test = testing.noop_test()
        test.update(
            nodes=["n1"], concurrency=4,
            client=w["client"],
            checker=w["checker"],
            generator=gen.clients(gen.stagger(0.0005, w["generator"])))
        test = core.run(test)
        assert test["results"]["valid?"] is False


class TestCli:
    def test_test_map_shape(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                "ssh": {"dummy": True}, "time_limit": 5,
                "workload": "register", "seed": 1}
        test = consul.consul_test(opts)
        assert test["name"] == "consul-register"
        assert isinstance(test["db"], consul.ConsulDB)
        assert test["nodes"] == ["n1", "n2", "n3"]

    def test_consistency_opt_reaches_client(self):
        opts = {"nodes": ["n1"], "concurrency": 2,
                "ssh": {"dummy": True}, "consistency": "stale",
                "workload": "register"}
        test = consul.consul_test(opts)
        assert test["client"].consistency == "stale"

    def test_concurrency_one_still_writes(self):
        """Reserve must never starve the write/cas mix (review r3)."""
        factory = FakeHttpFactory()
        opts = {"concurrency": 1, "keys": 1, "ops_per_key": 40,
                "seed": 5}
        w = consul.register_workload(opts)
        w["client"].http_factory = factory
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=1,
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0005, w["generator"])))
        test = core.run(test)
        assert test["results"]["valid?"] is True
        fs = {op.f for op in test["history"]}
        assert "write" in fs or "cas" in fs

    def test_corrupt_value_crashes_to_info_not_fail(self):
        """A non-integer KV value must not be misfiled as a clean
        network :fail (review r3)."""

        class CorruptState(FakeConsulState):
            def request(self, method, path, params=None, body=None):
                if method == "GET" and path.startswith("/v1/kv/"):
                    return 200, json.dumps([{
                        "Key": path[len("/v1/kv/"):],
                        "ModifyIndex": 1,
                        "Value": base64.b64encode(
                            b"not-a-number").decode()}])
                return super().request(method, path, params, body)

        client = consul.ConsulRegisterClient(
            http_factory=FakeHttpFactory(CorruptState()))
        c = client.open({}, "n1")
        import pytest
        from jepsen_tpu.history import Op

        op = Op(type="invoke", process=0, f="read",
                value=consul.independent.ktuple(0, None))
        with pytest.raises(ValueError):
            c.invoke({}, op)
