"""Galera suite tests: cluster bootstrap command emission via the
dummy remote, an in-memory mysql speaking the suite's SQL batches, and
clusterless end-to-end bank/set runs (mirrors
galera/src/jepsen/galera.clj)."""

import re
import threading

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, RemoteError
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import galera as gal


def make_test(nodes=("n1", "n2", "n3")):
    remote = DummyRemote()
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return core.prepare_test(t)


def cmds(test, node):
    return [a.cmd for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_bootstrap_flow(self):
        test = make_test()
        db = gal.GaleraDB()
        control.on_nodes(test, lambda t, n: db.setup(t, n))
        got1 = " ; ".join(cmds(test, "n1"))
        got2 = " ; ".join(cmds(test, "n2"))
        # only the primary bootstraps the new cluster
        assert "--wsrep-new-cluster" in got1
        assert "--wsrep-new-cluster" not in got2
        assert "service mysql start" in got2
        # debconf preseed + stock-dir stash on every node
        for got in (got1, got2):
            assert "debconf-set-selections" in got
            assert "mariadb-galera-server" in got
            assert "/var/lib/mysql-stock" in got
        # cluster address lists every node
        acts = [a for a in test["sessions"]["n2"].log
                if isinstance(a, Action) and a.stdin]
        cnf = next(a.stdin for a in acts if "jepsen.cnf" in a.cmd)
        assert "gcomm://n1,n2,n3" in cnf
        # accounts seeded once, on the primary
        assert "INSERT IGNORE INTO jepsen.accounts" in got1
        assert "INSERT IGNORE" not in got2

    def test_teardown_restores_stock(self):
        test = make_test()
        db = gal.GaleraDB()
        with control.with_session(test, "n1"):
            db.teardown(test, "n1")
        got = " ; ".join(cmds(test, "n1"))
        assert "rm -rf /var/lib/mysql" in got
        assert "cp -rp /var/lib/mysql-stock /var/lib/mysql" in got


class FakeMysql:
    """Executes the suite's SQL batches atomically under one lock — a
    perfectly consistent single 'cluster'."""

    def __init__(self, accounts=8, balance=10):
        self.lock = threading.Lock()
        self.accounts = {i: balance for i in range(accounts)}
        self.sets: list = []

    def run(self, sql: str) -> str:
        with self.lock:
            if "CONCAT('b='" in sql:
                return "b=" + ",".join(
                    f"{i}:{b}" for i, b in sorted(self.accounts.items()))
            if "START TRANSACTION" in sql:
                f = int(re.search(r"WHERE id = (\d+);", sql).group(1))
                m = re.search(
                    r"balance - (\d+) WHERE id = (\d+)", sql)
                a, f2 = int(m.group(1)), int(m.group(2))
                t = int(re.search(
                    r"balance \+ \d+ WHERE id = (\d+)", sql).group(1))
                assert f == f2
                if self.accounts[f] >= a:
                    self.accounts[f] -= a
                    self.accounts[t] += a
                    return "applied=1"
                return "applied=0"
            if "INSERT INTO sets" in sql:
                self.sets.append(int(
                    re.search(r"VALUES \((\d+)\)", sql).group(1)))
                return ""
            if "CONCAT('s='" in sql:
                return "s=" + ",".join(map(str, self.sets))
            raise AssertionError(f"fake mysql can't parse: {sql!r}")


class FakeMysqlFactory:
    def __init__(self, state=None):
        self.state = state or FakeMysql()

    def __call__(self, test, node, timeout=10.0):
        factory = self

        class _M:
            def run(self, sql):
                return factory.state.run(sql)

            def close(self):
                pass

        return _M()


class TestEndToEnd:
    def _run(self, workload_fn, opts, factory):
        w = workload_fn(opts)
        w["client"].mysql_factory = factory
        test = testing.noop_test()
        test.update(nodes=["n1", "n2"],
                    concurrency=opts.get("concurrency", 4),
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0005, gen.limit(
                            opts.get("ops", 200), w["generator"]))))
        return core.run(test)

    def test_bank_conserves_total(self):
        test = self._run(gal.bank_workload,
                         {"seed": 5, "ops": 200}, FakeMysqlFactory())
        assert test["results"]["valid?"] is True
        reads = [op for op in test["history"]
                 if op.type == "ok" and op.f == "read"]
        assert reads and all(sum(op.value.values()) == 80
                             for op in reads)
        # with amounts up to 5 against 10-unit accounts, some
        # transfer hits insufficient funds over 200 ops (seeded)
        assert any(op.type == "fail" and op.f == "transfer"
                   for op in test["history"])

    def test_bank_detects_lost_credit(self):
        class Lossy(FakeMysql):
            def __init__(self):
                super().__init__()
                self.n = 0

            def run(self, sql):
                if "START TRANSACTION" in sql:
                    self.n += 1
                    if self.n % 5 == 0:
                        # debit applies, credit lost: shrinking total
                        m = re.search(
                            r"balance - (\d+) WHERE id = (\d+)", sql)
                        a, f = int(m.group(1)), int(m.group(2))
                        with self.lock:
                            if self.accounts[f] >= a:
                                self.accounts[f] -= a
                                return "applied=1"
                            return "applied=0"
                return super().run(sql)

        test = self._run(gal.bank_workload, {"seed": 7, "ops": 200},
                         FakeMysqlFactory(Lossy()))
        assert test["results"]["valid?"] is False

    def test_set_workload(self):
        gen_opts = {"ops": 100, "concurrency": 4}
        w = gal.set_workload(gen_opts)
        w["client"].mysql_factory = FakeMysqlFactory()
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=4,
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(gen.phases(
                        gen.stagger(0.0003, w["generator"]),
                        w["final_generator"])))
        test = core.run(test)
        assert test["results"]["valid?"] is True

    def test_set_detects_lost_insert(self):
        class Dropping(FakeMysql):
            def __init__(self):
                super().__init__()
                self.n = 0

            def run(self, sql):
                if "INSERT INTO sets" in sql:
                    self.n += 1
                    if self.n == 3:
                        return ""  # ack but drop
                return super().run(sql)

        w = gal.set_workload({"ops": 60})
        w["client"].mysql_factory = FakeMysqlFactory(Dropping())
        test = testing.noop_test()
        test.update(nodes=["n1"], concurrency=2,
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(gen.phases(
                        gen.stagger(0.0003, w["generator"]),
                        w["final_generator"])))
        test = core.run(test)
        assert test["results"]["valid?"] is False
        assert test["results"]["lost"]


class TestClientErrors:
    def test_deadlock_is_definite_fail(self):
        class Deadlocking:
            def __call__(self, test, node, timeout=10.0):
                class _M:
                    def run(self, sql):
                        raise RemoteError(
                            "mysql failed", exit=1, out="",
                            err="ERROR 1213 (40001): Deadlock found",
                            cmd="mysql", node=node)

                    def close(self):
                        pass

                return _M()

        c = gal.GaleraBankClient(mysql_factory=Deadlocking()).open(
            {"nodes": ["n1"]}, "n1")
        op = Op(type="invoke", process=0, f="transfer",
                value={"from": 0, "to": 1, "amount": 3})
        assert c.invoke({}, op).type == "fail"

    def test_cli_map(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 3,
                "ssh": {"dummy": True}, "time_limit": 5}
        test = gal.galera_test(opts)
        assert test["name"] == "galera-bank"
        assert isinstance(test["db"], gal.GaleraDB)
