"""etcd suite tests: DB command emission via the dummy remote, client
semantics against an in-memory fake gateway, and clusterless
end-to-end runs (correct + broken fakes)."""

import json
import threading
import urllib.error

import pytest

from jepsen_tpu import checker as chk
from jepsen_tpu import control, core, independent, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import op
from jepsen_tpu.suites import etcd


def fresh_node_responder(node, action):
    """stat fails: nothing is installed/cached on this 'node' yet."""
    from jepsen_tpu.control.core import Result

    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "etcd-v3.5.15-linux-amd64"
    return None


@pytest.fixture()
def test_map():
    remote = DummyRemote(fresh_node_responder)
    nodes = ["n1", "n2", "n3"]
    t = {"nodes": nodes, "remote": remote, "ssh": {},
         "sessions": {n: remote.connect({"host": n}) for n in nodes}}
    return t


def cmds(test, node):
    return [a.cmd for a in test["sessions"][node].log
            if isinstance(a, Action)]


def test_initial_cluster(test_map):
    assert etcd.initial_cluster(test_map) == (
        "n1=http://n1:2380,n2=http://n2:2380,n3=http://n3:2380")


def test_db_setup_commands(test_map):
    db = etcd.EtcdDB("v3.5.15")
    with control.with_session(test_map, "n1"):
        db.setup(test_map, "n1")
    got = cmds(test_map, "n1")
    assert any(c.startswith("wget") and "etcd-v3.5.15-linux-amd64"
               in c for c in got)
    daemon = [c for c in got if c.startswith("start-stop-daemon")]
    assert len(daemon) == 1
    d = daemon[0]
    assert "--startas /opt/etcd/etcd" in d
    assert "--name n1" in d
    assert "--listen-peer-urls http://n1:2380" in d
    assert ("--initial-cluster "
            "n1=http://n1:2380,n2=http://n2:2380,n3=http://n3:2380"
            in d)
    assert "nc -z localhost 2379" in got


def test_db_teardown_kill_pause(test_map):
    db = etcd.EtcdDB()
    with control.with_session(test_map, "n2"):
        db.teardown(test_map, "n2")
        db.kill(test_map, "n2")
        db.pause(test_map, "n2")
        db.resume(test_map, "n2")
    got = cmds(test_map, "n2")
    assert "killall -9 -w /opt/etcd/etcd" in got
    assert "rm -rf /opt/etcd" in got
    assert any("pgrep -f --ignore-ancestors etcd" in c
               and "kill -9" in c for c in got)
    assert any("kill -STOP" in c for c in got)
    assert any("kill -CONT" in c for c in got)


# ---------------------------------------------------------------------------
# Fake gateway
# ---------------------------------------------------------------------------

class FakeEtcd:
    """Shared in-memory etcd v3 KV semantics (linearizable), with real
    mod revisions so guarded txns behave like the gateway."""

    def __init__(self):
        self.lock = threading.Lock()
        self.kv: dict = {}
        self.rev: dict = {}
        self.next_rev = 0

    def _write(self, key, value):
        self.next_rev += 1
        self.kv[key] = value
        self.rev[key] = self.next_rev

    def factory(self, node):
        return FakeHttp(self)


class FakeHttp:
    def __init__(self, state: FakeEtcd):
        self.state = state

    def get(self, key):
        with self.state.lock:
            if key not in self.state.kv:
                return None, None
            return self.state.kv[key], self.state.rev[key]

    def put(self, key, value):
        with self.state.lock:
            self.state._write(key, value)

    def cas(self, key, old, new):
        with self.state.lock:
            if self.state.kv.get(key) == old:
                self.state._write(key, new)
                return True
            return False

    def cas_create(self, key, new):
        with self.state.lock:
            if key not in self.state.kv:
                self.state._write(key, new)
                return True
            return False

    def txn_rw(self, guards, puts):
        with self.state.lock:
            for k, rev in guards:
                if (self.state.rev.get(k) or 0) != (rev or 0):
                    return False
            for k, v in puts:
                self.state._write(k, v)
            return True


def test_register_client_ops():
    state = FakeEtcd()
    c = etcd.EtcdRegisterClient(state.factory).open({}, "n1")
    t = independent.ktuple
    done = c.invoke({}, op(type="invoke", f="read", value=t(1, None)))
    assert done.type == "ok" and done.value == t(1, None)
    done = c.invoke({}, op(type="invoke", f="write", value=t(1, 3)))
    assert done.type == "ok"
    done = c.invoke({}, op(type="invoke", f="read", value=t(1, None)))
    assert done.value == t(1, 3)
    done = c.invoke({}, op(type="invoke", f="cas", value=t(1, [3, 4])))
    assert done.type == "ok"
    done = c.invoke({}, op(type="invoke", f="cas", value=t(1, [9, 5])))
    assert done.type == "fail"
    done = c.invoke({}, op(type="invoke", f="read", value=t(1, None)))
    assert done.value == t(1, 4)


def test_append_client_txns():
    state = FakeEtcd()
    c = etcd.EtcdAppendClient(state.factory).open({}, "n1")
    done = c.invoke({}, op(type="invoke", f="txn",
                           value=[["append", "x", 1], ["r", "x", None]]))
    assert done.type == "ok"
    assert done.value == [["append", "x", 1], ["r", "x", [1]]]
    c.invoke({}, op(type="invoke", f="txn",
                    value=[["append", "x", 2]]))
    done = c.invoke({}, op(type="invoke", f="txn",
                           value=[["r", "x", None]]))
    assert done.value == [["r", "x", [1, 2]]]


def test_error_mapping():
    class Boom:
        def __init__(self, exc):
            self.exc = exc

        def get(self, key):
            raise self.exc

    refused = urllib.error.URLError(ConnectionRefusedError(111))
    c = etcd.EtcdRegisterClient(lambda n: Boom(refused)).open({}, "n1")
    done = c.invoke({}, op(type="invoke", f="read",
                           value=independent.ktuple(1, None)))
    assert done.type == "fail"  # definitely never executed

    timed = urllib.error.URLError(TimeoutError())
    c = etcd.EtcdRegisterClient(lambda n: Boom(timed)).open({}, "n1")
    done = c.invoke({}, op(type="invoke", f="read",
                           value=independent.ktuple(1, None)))
    assert done.type == "info"  # indeterminate


# ---------------------------------------------------------------------------
# Clusterless end-to-end
# ---------------------------------------------------------------------------

def run_suite_workload(name, client):
    opts = {"workload": name, "nodes": ["n1", "n2", "n3"],
            "concurrency": 3, "ssh": {"dummy": True},
            "time_limit": 5, "rate": 500, "ops_per_key": 60,
            "ops": 120, "seed": 7}
    test = etcd.etcd_test(opts)
    # dummy infrastructure: no OS setup, no real DB, fake gateway, no
    # nemesis schedule — the workload generator alone
    from jepsen_tpu import db as jdb, os_setup
    w = etcd.WORKLOADS[name](opts)
    test["os"] = os_setup.noop
    test["db"] = jdb.noop
    test["client"] = client
    test["nemesis"] = None
    test["generator"] = gen.clients(w["generator"])
    test["name"] = None
    return core.run(test)


def test_register_end_to_end_valid():
    state = FakeEtcd()
    t = run_suite_workload(
        "register", etcd.EtcdRegisterClient(state.factory))
    assert t["results"]["valid?"] is True


def test_append_end_to_end_valid():
    state = FakeEtcd()
    t = run_suite_workload("append", etcd.EtcdAppendClient(state.factory))
    assert t["results"]["valid?"] is True


class BrokenHttp(FakeHttp):
    """Loses every third write silently: a linearizability violation."""

    def __init__(self, state):
        super().__init__(state)

    def put(self, key, value):
        with self.state.lock:
            self.state.n = getattr(self.state, "n", 0) + 1
            if self.state.n % 3 == 0:
                return  # dropped write acked as ok
            self.state.kv[key] = value


def test_register_end_to_end_catches_lost_writes():
    state = FakeEtcd()
    t = run_suite_workload(
        "register",
        etcd.EtcdRegisterClient(lambda n: BrokenHttp(state)))
    assert t["results"]["valid?"] is False


def test_nemesis_menu():
    """--nemesis selects composed fault packages; empty keeps the
    classic partitioner (reference suites' nemesis menus)."""
    from jepsen_tpu.nemesis.core import Partitioner
    from jepsen_tpu.nemesis.membership import MembershipNemesis

    base = {"nodes": ["n1", "n2", "n3"], "concurrency": 3, "ssh": {}}
    t = etcd.etcd_test(dict(base))
    assert isinstance(t["nemesis"], Partitioner)

    t = etcd.etcd_test(dict(base, faults=["partition", "kill"]))
    fs = t["nemesis"].fs()
    assert "kill" in fs, fs
    assert {"start-partition", "stop-partition"} & fs, fs
    # kill leaves dead nodes: the final generator must heal
    assert t["generator"] is not None

    # membership sub-options flow through; caller's dict not mutated
    mopts = {"seed": 3}
    t = etcd.etcd_test(dict(base, faults=["membership"],
                            membership=mopts))
    assert "state" not in mopts  # no mutation of caller opts
    nem = t["nemesis"]
    # membership ops must route somewhere in the composed nemesis
    assert {"add-member", "remove-member"} <= nem.fs(), nem.fs()
    subs = [n for _spec, n in getattr(nem, "pairs", [])]
    assert (isinstance(nem, MembershipNemesis)
            or any(isinstance(x, MembershipNemesis) for x in subs)), nem


class TestTestAll:
    """test-all sweep shape (tidb core.clj:47-60 workload-options)."""

    def test_sweep_covers_workloads_and_faults(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 3,
                "ssh": {"dummy": True}, "time_limit": 1, "seed": 1}
        tests = list(etcd.all_tests(opts))
        assert len(tests) == (len(etcd.WORKLOADS)
                              * len(etcd.FAULT_OPTIONS))
        names = {t["name"] for t in tests}
        assert names == {"etcd-register", "etcd-append"}
        # each test is independently constructed (no shared nemesis
        # state across sweep entries)
        nemeses = [id(t["nemesis"]) for t in tests]
        assert len(set(nemeses)) == len(nemeses)

    def test_sweep_narrows_and_repeats(self):
        opts = {"nodes": ["n1"], "concurrency": 2,
                "ssh": {"dummy": True}, "time_limit": 1,
                "workload": "append", "faults": ["kill"],
                "test_count": 3, "seed": 1}
        tests = list(etcd.all_tests(opts))
        assert len(tests) == 3  # one combo, three repetitions
        assert {t["name"] for t in tests} == {"etcd-append"}

    def test_single_test_defaults_to_register(self):
        opts = {"nodes": ["n1"], "concurrency": 2,
                "ssh": {"dummy": True}, "workload": None}
        assert etcd.etcd_test(opts)["name"] == "etcd-register"
