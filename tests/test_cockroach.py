"""Cockroach suite tests: cluster init command emission via the dummy
remote, an in-memory cockroach speaking the suite's SQL shapes, and
clusterless end-to-end runs of all four workloads (mirrors
cockroachdb/src/jepsen/cockroach/*.clj)."""

import re
import threading
from decimal import Decimal

from jepsen_tpu import control, core, independent, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.suites import cockroach as crdb


def responder(node, action):
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "cockroach-v23.1.14.linux-amd64"
    return None


def make_test(nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return core.prepare_test(t)


def cmds(test, node):
    return [a.cmd for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_setup_and_init_flow(self):
        test = make_test()
        db = crdb.CockroachDB()
        control.on_nodes(test, lambda t, n: db.setup(t, n))
        got1 = " ; ".join(cmds(test, "n1"))
        got2 = " ; ".join(cmds(test, "n2"))
        for got in (got1, got2):
            assert "cockroach-v23.1.14.linux-amd64.tgz" in got
            assert "--join n1:26257,n2:26257,n3:26257" in got
            assert "--insecure" in got
        # init + schema happen once, on the primary
        assert "init --insecure" in got1
        assert "init --insecure" not in got2
        assert "CREATE DATABASE IF NOT EXISTS jepsen" in got1
        assert "CHECK (balance >= 0)" in got1
        assert "cluster" not in got2 or "CREATE" not in got2

    def test_teardown(self):
        test = make_test()
        db = crdb.CockroachDB()
        with control.with_session(test, "n1"):
            db.teardown(test, "n1")
        got = " ; ".join(cmds(test, "n1"))
        assert "/var/lib/cockroach" in got


class FakeCrdb:
    """In-memory cockroach speaking the suite's SQL shapes in tsv,
    atomically under one lock."""

    def __init__(self):
        self.lock = threading.Lock()
        self.kv: dict = {}
        self.accounts = {i: 10 for i in range(8)}
        self.mono: list = []
        self.clock = 0
        self.seq: set = set()

    def run(self, sql: str) -> str:
        with self.lock:
            if sql.startswith("SELECT v FROM kv"):
                k = int(re.search(r"k = (\d+)", sql).group(1))
                if k in self.kv:
                    return f"v\n{self.kv[k]}"
                return "v"
            if sql.startswith("UPSERT INTO kv"):
                k, v = map(int, re.search(
                    r"\((\d+), (\d+)\)", sql).groups())
                self.kv[k] = v
                return ""
            if sql.startswith("UPDATE kv"):
                m = re.search(r"SET v = (\d+) WHERE k = (\d+) "
                              r"AND v = (\d+)", sql)
                new, k, old = map(int, m.groups())
                if self.kv.get(k) == old:
                    self.kv[k] = new
                    return f"v\n{new}"
                return "v"
            if sql.startswith("INSERT INTO mono"):
                m = re.search(r"(\d+), (\d+), (\d+) FROM mono", sql)
                node, proc, tb = map(int, m.groups())
                val = max((r["val"] for r in self.mono), default=0) + 1
                self.clock += 1
                row = {"val": val, "sts": Decimal(self.clock),
                       "node": node, "process": proc, "tb": tb}
                self.mono.append(row)
                return ("val\tsts\tnode\tprocess\ttb\n"
                        f"{val}\t{self.clock}\t{node}\t{proc}\t{tb}")
            if sql.startswith("SELECT val, sts"):
                rows = sorted(self.mono, key=lambda r: r["sts"])
                out = ["val\tsts\tnode\tprocess\ttb"]
                for r in rows:
                    out.append(f"{r['val']}\t{r['sts']}\t{r['node']}"
                               f"\t{r['process']}\t{r['tb']}")
                return "\n".join(out)
            if sql.startswith("INSERT INTO seq"):
                self.seq.add(re.search(r"'([^']+)'", sql).group(1))
                return ""
            if sql.startswith("SELECT key FROM seq"):
                k = re.search(r"= '([^']+)'", sql).group(1)
                return f"key\n{k}" if k in self.seq else "key"
            if sql.startswith("SELECT id, balance"):
                out = ["id\tbalance"]
                for i, b in sorted(self.accounts.items()):
                    out.append(f"{i}\t{b}")
                return "\n".join(out)
            if sql.startswith("BEGIN"):
                m = re.search(r"balance - (\d+) WHERE id = (\d+)", sql)
                a, f = int(m.group(1)), int(m.group(2))
                t = int(re.search(
                    r"balance \+ \d+ WHERE id = (\d+)", sql).group(1))
                from jepsen_tpu.control.core import RemoteError

                if self.accounts[f] < a:
                    raise RemoteError(
                        "cockroach sql failed", exit=1, out="",
                        err='violates check constraint '
                            '"accounts_balance_check"',
                        cmd="cockroach", node="n1")
                self.accounts[f] -= a
                self.accounts[t] += a
                return ""
            raise AssertionError(f"fake crdb can't parse: {sql!r}")


class FakeSqlFactory:
    def __init__(self, state=None):
        self.state = state or FakeCrdb()

    def __call__(self, test, node, timeout=10.0):
        factory = self

        class _S:
            def run(self, sql):
                return factory.state.run(sql)

            def close(self):
                pass

        return _S()


def run_workload(workload_fn, opts, factory, final=False):
    w = workload_fn(opts)
    w["client"].sql_factory = factory
    test = testing.noop_test()
    phases = [gen.stagger(0.0004, gen.limit(opts.get("gen_ops", 200),
                                            w["generator"]))
              if not w.get("final_generator")
              else gen.stagger(0.0004, w["generator"])]
    if w.get("final_generator"):
        phases.append(w["final_generator"])
    test.update(nodes=["n1", "n2"],
                concurrency=opts.get("concurrency", 6),
                key_count=w.get("key_count", 5),
                client=w["client"],
                checker=w["checker"],
                generator=gen.clients(gen.phases(*phases)))
    return core.run(test)


class TestEndToEnd:
    def test_register_valid(self):
        test = run_workload(
            crdb.register_workload,
            {"concurrency": 6, "keys": 2, "ops_per_key": 50,
             "seed": 3}, FakeSqlFactory())
        assert test["results"]["valid?"] is True

    def test_bank_valid_and_check_guard(self):
        test = run_workload(
            crdb.bank_workload,
            {"concurrency": 4, "seed": 5, "gen_ops": 150},
            FakeSqlFactory())
        assert test["results"]["valid?"] is True
        # overdrafts come back as definite fails via the CHECK error
        fails = [op for op in test["history"]
                 if op.f == "transfer" and op.type == "fail"]
        assert all("check constraint" in (op.error or "")
                   for op in fails)

    def test_monotonic_valid(self):
        test = run_workload(
            crdb.monotonic_workload,
            {"concurrency": 4, "ops": 120}, FakeSqlFactory())
        assert test["results"]["valid?"] is True
        assert test["results"]["add-count"] > 30

    def test_monotonic_detects_skew(self):
        class Skewed(FakeCrdb):
            def run(self, sql):
                out = super().run(sql)
                if sql.startswith("INSERT INTO mono") and \
                        len(self.mono) % 7 == 0:
                    # rewrite the stored timestamp backwards
                    with self.lock:
                        self.mono[-1]["sts"] = Decimal(
                            max(self.clock - 5, 0))
                return out

        test = run_workload(
            crdb.monotonic_workload,
            {"concurrency": 4, "ops": 150}, FakeSqlFactory(Skewed()))
        assert test["results"]["valid?"] is False

    def test_sequential_valid(self):
        test = run_workload(
            crdb.sequential_workload,
            {"concurrency": 6, "ops": 200, "seed": 9},
            FakeSqlFactory())
        assert test["results"]["valid?"] is True
        assert test["results"]["bad-count"] == 0

    def test_sequential_detects_reorder(self):
        class Dropping(FakeCrdb):
            """Hides _0 subkeys from reads while later ones exist."""

            def run(self, sql):
                if sql.startswith("SELECT key FROM seq") and \
                        "_0'" in sql:
                    return "key"
                return super().run(sql)

        test = run_workload(
            crdb.sequential_workload,
            {"concurrency": 6, "ops": 200, "seed": 9},
            FakeSqlFactory(Dropping()))
        assert test["results"]["valid?"] is False


class TestCli:
    def test_map_shape(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                "ssh": {"dummy": True}, "time_limit": 5}
        test = crdb.cockroach_test(opts)
        assert test["name"] == "cockroach-register"
        assert isinstance(test["db"], crdb.CockroachDB)

    def test_monotonic_final_phase_wired(self):
        opts = {"nodes": ["n1"], "concurrency": 2,
                "ssh": {"dummy": True}, "workload": "monotonic",
                "time_limit": 5}
        test = crdb.cockroach_test(opts)
        assert test["name"] == "cockroach-monotonic"


class FakeCrdbFull(FakeCrdb):
    """FakeCrdb extended with the sets / comments / g2 / multitable
    bank statement shapes. broken='causal-reverse' delays write
    visibility: an insert lands only after a LATER insert to the same
    key arrives (T2 visible without T1); broken='g2-race' skips the
    predicate-read guard every other insert."""

    def __init__(self, broken=None):
        super().__init__()
        self.broken = broken
        self.sets: list = []
        self.comments: dict = {}   # table -> {id: key}
        self.held: dict = {}       # key -> held-back (table, id)
        self.g2: dict = {"g2a": {}, "g2b": {}}
        self.g2_calls = 0
        self.banks = {i: 10 for i in range(8)}

    def run(self, sql: str) -> str:
        with self.lock:
            out = self._full(sql)
        if out is not None:
            return out
        return super().run(sql)

    def _full(self, sql: str):
        if sql.startswith("INSERT INTO sets"):
            self.sets.append(int(re.search(r"\((\d+)\)", sql)
                                 .group(1)))
            return ""
        if sql.startswith("SELECT v FROM sets"):
            return "v\n" + "\n".join(map(str, self.sets))
        m = re.match(r"INSERT INTO (comment_\d+) \(id, key\) VALUES "
                     r"\((\d+), (\d+)\);", sql)
        if m:
            t, i, k = m.group(1), int(m.group(2)), int(m.group(3))
            if self.broken == "causal-reverse" and k not in self.held:
                # FIRST write acks but stays invisible while LATER
                # writes land visibly -> T2 visible without T1
                self.held[k] = [t, i, 0]
            else:
                self.comments.setdefault(t, {})[(k, i)] = k
                if k in self.held:
                    h = self.held[k]
                    h[2] += 1
                    if h[2] >= 3:  # finally becomes visible
                        self.comments.setdefault(
                            h[0], {})[(k, h[1])] = k
                        del self.held[k]
            return ""
        if "FROM comment_0" in sql:
            k = int(re.search(r"key = (\d+)", sql).group(1))
            ids = [str(i) for t, rows in sorted(
                       self.comments.items())
                   for (kk, i) in sorted(rows) if kk == k]
            return "id\n" + "\n".join(ids)
        m = re.search(r"INSERT INTO (g2a|g2b) \(id, k\) SELECT "
                      r"(\d+), (\d+) WHERE NOT EXISTS", sql)
        if m:
            t, i, k = m.group(1), int(m.group(2)), int(m.group(3))
            na = sum(1 for v in self.g2["g2a"].values() if v == k)
            nb = sum(1 for v in self.g2["g2b"].values() if v == k)
            # 'g2-race': the predicate read inside the txn is blind to
            # concurrent commits (the G2 anomaly itself)
            if (na or nb) and self.broken != "g2-race":
                return "id"  # guard saw a row: zero rows inserted
            self.g2[t][i] = k
            return f"id\n{i}"
        if re.search(r"SELECT balance FROM bank0", sql):
            return "balance\n" + "\n".join(
                str(self.banks[i]) for i in range(8))
        m = re.search(r"UPDATE bank(\d+) SET balance = balance - "
                      r"(\d+).*UPDATE bank(\d+) SET balance = "
                      r"balance \+ (\d+)", sql, re.S)
        if m:
            f, a = int(m.group(1)), int(m.group(2))
            t = int(m.group(3))
            if self.banks[f] - a < 0:
                raise _CrdbError("violates check constraint "
                                 "balance >= 0")
            self.banks[f] -= a
            self.banks[t] += a
            return ""
        return None


class _CrdbError(Exception):
    pass


class FakeFullFactory(FakeSqlFactory):
    def __init__(self, state=None, broken=None):
        self.state = state or FakeCrdbFull(broken)

    def __call__(self, test, node, timeout=10.0):
        factory = self

        class _S:
            def run(self, sql):
                try:
                    return factory.state.run(sql)
                except _CrdbError as e:
                    from jepsen_tpu.control.core import RemoteError

                    raise RemoteError("sql failed", exit=1, out="",
                                      err=str(e), cmd="sql",
                                      node=node)

            def close(self):
                pass

        return _S()


class TestNewWorkloads:
    def test_sets(self):
        t = run_workload(crdb.sets_workload, {"ops": 100,
                                            "gen_ops": 130},
                         FakeFullFactory())
        assert t["results"]["valid?"] is True, t["results"]

    def test_comments_healthy(self):
        t = run_workload(crdb.comments_workload,
                         {"keys": [0, 1], "per-key-limit": 40,
                          "gen_ops": 100},
                         FakeFullFactory())
        assert t["results"]["valid?"] is True, t["results"]

    def test_comments_detects_causal_reverse(self):
        t = run_workload(crdb.comments_workload,
                         {"keys": [0], "per-key-limit": 80,
                          "group-size": 3, "gen_ops": 120,
                          "concurrency": 6},
                         FakeFullFactory(broken="causal-reverse"))
        assert t["results"]["valid?"] is False

    def test_g2_healthy_and_racy(self):
        t = run_workload(crdb.g2_workload,
                         {"keys": list(range(1, 13)),
                          "gen_ops": 60, "concurrency": 6},
                         FakeFullFactory())
        assert t["results"]["valid?"] is True, t["results"]
        t = run_workload(crdb.g2_workload,
                         {"keys": list(range(1, 13)),
                          "gen_ops": 60, "concurrency": 6},
                         FakeFullFactory(broken="g2-race"))
        assert t["results"]["valid?"] is False

    def test_bank_multitable(self):
        t = run_workload(crdb.bank_multitable_workload,
                         {"ops": 80, "gen_ops": 100},
                         FakeFullFactory())
        assert t["results"]["valid?"] is True, t["results"]

    def test_menu_matches_reference(self):
        # cockroach.clj test menu
        assert set(crdb.WORKLOADS) == {
            "register", "bank", "bank-multitable", "monotonic",
            "sequential", "sets", "comments", "g2"}
