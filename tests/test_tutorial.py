"""Executes every runnable code block in doc/tutorial/ — the tutorial
is a contract (reference arc: doc/tutorial/index.md chapters 1-8), and
running it in CI keeps the prose from rotting away from the API."""

import re
from pathlib import Path

import pytest

DOC = Path(__file__).resolve().parent.parent / "doc" / "tutorial"
CHAPTERS = sorted(p.name for p in DOC.glob("0*.md"))


def blocks(chapter: str) -> list[str]:
    text = (DOC / chapter).read_text()
    out = []
    for m in re.finditer(r"```python([^\n`]*)\n(.*?)```", text,
                         re.S):
        tag, body = m.group(1).strip(), m.group(2)
        if tag == "no-run":
            continue
        out.append(body)
    return out


def test_all_chapters_present():
    assert CHAPTERS == [
        "01-scaffolding.md", "02-db.md", "03-client.md",
        "04-checker.md", "05-nemesis.md", "06-refining.md",
        "07-parameters.md", "08-set.md"]
    index = (DOC / "index.md").read_text()
    for ch in CHAPTERS:
        assert ch in index


@pytest.mark.parametrize("chapter", CHAPTERS)
def test_chapter_runs(chapter):
    ns: dict = {}
    bs = blocks(chapter)
    assert bs, f"{chapter} has no runnable blocks"
    for i, body in enumerate(bs):
        try:
            exec(compile(body, f"{chapter}[block {i}]", "exec"), ns)
        except Exception as e:
            raise AssertionError(
                f"{chapter} block {i} failed: {e!r}\n{body}") from e
