"""control.util + os_setup tests: exact command lines via dummy
sessions (the reference pattern: assert what would run on a node)."""

import pytest

from jepsen_tpu import control
from jepsen_tpu.control import util as cu
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.os_setup import debian


def make_session(responder=None):
    remote = DummyRemote(responder)
    test = {"nodes": ["n1"], "remote": remote,
            "sessions": {"n1": remote.connect({"host": "n1"})}}
    return test, test["sessions"]["n1"]


def logged(sess):
    return [(a.cmd, a.sudo) for a in sess.log if isinstance(a, Action)]


def test_grepkill_default_signal():
    test, sess = make_session()
    with control.with_session(test, "n1"):
        cu.grepkill("etcd")
    assert ("pgrep -f --ignore-ancestors etcd | xargs "
            "--no-run-if-empty kill -9", None) in logged(sess)


def test_grepkill_named_signal():
    test, sess = make_session()
    with control.with_session(test, "n1"):
        with control.su():
            cu.grepkill("etcd", "stop")
    assert ("pgrep -f --ignore-ancestors etcd | xargs "
            "--no-run-if-empty kill -STOP", "root") in logged(sess)


def test_start_daemon():
    test, sess = make_session()
    with control.with_session(test, "n1"):
        res = cu.start_daemon(
            {"logfile": "/var/log/db.log", "pidfile": "/run/db.pid",
             "chdir": "/opt/db"},
            "/opt/db/bin/db", "--port", 2379)
    assert res == "started"
    cmds = [c for c, _ in logged(sess)]
    assert cmds[0].startswith("echo `date +'%Y-%m-%d %H:%M:%S'`")
    assert cmds[0].endswith(">> /var/log/db.log")
    assert cmds[1] == (
        "start-stop-daemon --start --background --no-close "
        "--make-pidfile --exec /opt/db/bin/db --pidfile /run/db.pid "
        "--chdir /opt/db --startas /opt/db/bin/db -- --port 2379 "
        ">> /var/log/db.log 2>&1")


def test_start_daemon_env_and_name():
    test, sess = make_session()
    with control.with_session(test, "n1"):
        cu.start_daemon(
            {"logfile": "/l", "chdir": "/", "pidfile": None,
             "env": {"SEEDS": "flax"}, "match_process_name": True,
             "process_name": "dbd"},
            "/bin/db")
    cmds = [c for c, _ in logged(sess)]
    assert cmds[1] == (
        "SEEDS=flax start-stop-daemon --start --background --no-close "
        "--exec /bin/db --name dbd --chdir / --startas /bin/db -- "
        ">> /l 2>&1")


def test_start_daemon_already_running():
    def responder(node, action):
        if "start-stop-daemon" in action.cmd:
            return Result(exit=1, out="", err="", cmd=action.cmd)
        return None

    test, sess = make_session(responder)
    with control.with_session(test, "n1"):
        res = cu.start_daemon({"logfile": "/l", "chdir": "/"}, "/bin/db")
    assert res == "already-running"


def test_stop_daemon_by_cmd():
    test, sess = make_session()
    with control.with_session(test, "n1"):
        cu.stop_daemon("etcd", "/run/etcd.pid")
    cmds = [c for c, _ in logged(sess)]
    assert "killall -9 -w etcd" in cmds
    assert "rm -rf /run/etcd.pid" in cmds


def test_write_file_uses_stdin():
    test, sess = make_session()
    with control.with_session(test, "n1"):
        cu.write_file("hello\nworld", "/etc/motd")
    acts = [a for a in sess.log if isinstance(a, Action)]
    assert acts[0].cmd == "cat > /etc/motd"
    assert acts[0].stdin == "hello\nworld"


def test_cached_wget_key_is_base64():
    import base64

    url = "https://example.com/v1.2/foo.tar"
    enc = base64.b64encode(url.encode()).decode()

    def responder(node, action):
        # "stat" existence probe fails -> must download
        if action.cmd.startswith("stat"):
            return Result(exit=1, out="", err="no such file",
                          cmd=action.cmd)
        return None

    test, sess = make_session(responder)
    with control.with_session(test, "n1"):
        dest = cu.cached_wget(url)
    assert dest == f"{cu.WGET_CACHE_DIR}/{enc}"
    wgets = [a for a in sess.log if isinstance(a, Action)
             and a.cmd.startswith("wget")]
    assert len(wgets) == 1
    assert f"-O {cu.WGET_CACHE_DIR}/{enc}" in wgets[0].cmd
    assert wgets[0].dir == cu.WGET_CACHE_DIR


def test_await_tcp_port_immediate():
    test, sess = make_session()
    with control.with_session(test, "n1"):
        cu.await_tcp_port(2379, timeout_secs=1)
    assert ("nc -z localhost 2379", None) in logged(sess)


# ---------------------------------------------------------------------------
# Debian OS
# ---------------------------------------------------------------------------

def debian_responder(installed=("wget", "curl")):
    sel = "\n".join(f"{p}\tinstall" for p in installed)

    def responder(node, action):
        cmd = action.cmd
        if cmd.startswith("cat /etc/hosts"):
            return "127.0.0.1\tlocalhost\n10.0.0.1\tn1"
        if cmd.startswith("date +%s"):
            return "1000000"
        if cmd.startswith("stat -c %Y"):
            return "999999"  # 1s since last update: fresh
        if cmd.startswith("dpkg --get-selections"):
            return sel
        return None

    return responder


def test_debian_setup_installs_missing():
    remote = DummyRemote(debian_responder())
    test = {"nodes": ["n1"], "remote": remote, "net": None,
            "sessions": {"n1": remote.connect({"host": "n1"})}}
    sess = test["sessions"]["n1"]
    with control.with_session(test, "n1"):
        debian.Debian().setup(test, "n1")
    cmds = [c for c, s in logged(sess) if s == "root"]
    installs = [c for c in cmds if "apt-get install" in c]
    assert len(installs) == 1
    assert installs[0].startswith(
        "env DEBIAN_FRONTEND=noninteractive apt-get install -y "
        "--allow-downgrades --allow-change-held-packages")
    assert "tcpdump" in installs[0]
    assert "wget" not in installs[0].replace("--", "")  # already there
    # apt-get update was NOT run (cache is fresh)
    assert not any("apt-get --allow-releaseinfo-change update" in c
                   for c in cmds)


def test_debian_stale_cache_updates():
    def responder(node, action):
        base = debian_responder()(node, action)
        if action.cmd.startswith("stat -c %Y"):
            return "0"  # ancient
        return base

    remote = DummyRemote(responder)
    test = {"nodes": ["n1"], "remote": remote,
            "sessions": {"n1": remote.connect({"host": "n1"})}}
    with control.with_session(test, "n1"):
        debian.maybe_update()
    cmds = [c for c, s in logged(test["sessions"]["n1"])]
    assert "apt-get --allow-releaseinfo-change update" in cmds


def test_debian_install_pinned_version():
    def responder(node, action):
        if action.cmd.startswith("apt-cache policy"):
            return "foo:\n  Installed: 1.0\n  Candidate: 2.0"
        return None

    remote = DummyRemote(responder)
    test = {"nodes": ["n1"], "remote": remote,
            "sessions": {"n1": remote.connect({"host": "n1"})}}
    with control.with_session(test, "n1"):
        debian.install({"foo": "2.0"})
    cmds = [c for c, _ in logged(test["sessions"]["n1"])]
    assert any(c.endswith("foo=2.0") for c in cmds)


def test_debian_hostfile_rewrite():
    def responder(node, action):
        if action.cmd == "cat /etc/hosts":
            return "127.0.0.1\tn1.local n1\n10.0.0.1\tn1"
        return None

    remote = DummyRemote(responder)
    test = {"nodes": ["n1"], "remote": remote,
            "sessions": {"n1": remote.connect({"host": "n1"})}}
    with control.with_session(test, "n1"):
        debian.setup_hostfile()
    cmds = [c for c, s in logged(test["sessions"]["n1"])
            if s == "root"]
    assert any(c.startswith("echo ") and "> /etc/hosts" in c
               for c in cmds)
