"""Yugabyte suite tests: master/tserver orchestration via the dummy
remote, a scripted ysql/ycql runner executing the clients' statement
shapes, and clusterless e2e runs across the API-parameterized workload
matrix — healthy and with seeded bugs (mirrors
yugabyte/src/yugabyte/core.clj's workload matrix)."""

import re
import threading

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, RemoteError
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import yugabyte as yb


def make_test(responder=None, nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return t


def cmds(test, node):
    return [a for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_daemons_wired_to_all_masters(self):
        test = make_test()
        db = yb.YbDB()
        with control.with_session(test, "n2"):
            db._start_master(test, "n2")
            db._start_tserver(test, "n2")
        got = " ; ".join(a.cmd for a in cmds(test, "n2"))
        assert "yb-master" in got and "yb-tserver" in got
        assert f"n1:{yb.MASTER_PORT},n2:{yb.MASTER_PORT}," \
               f"n3:{yb.MASTER_PORT}" in got
        assert "--replication_factor 3" in got
        assert "--start_pgsql_proxy" in got

    def test_kill_greps_both(self):
        test = make_test()
        db = yb.YbDB()
        with control.with_session(test, "n1"):
            db.kill(test, "n1")
        got = " ; ".join(a.cmd for a in cmds(test, "n1"))
        assert "yb-master" in got and "yb-tserver" in got


class _SqlError(Exception):
    pass


class FakeYb:
    """Executes the statement shapes the suite's clients emit, over
    in-memory tables with a global lock (statements and BEGIN..COMMIT
    blocks are atomic — a serializable-by-construction store).
    broken='null-default' makes ALTER..DEFAULT leave existing rows
    NULL (the DDL race default_value.clj hunts);
    broken='lost-update' drops every 5th UPDATE silently."""

    def __init__(self, broken=None):
        self.lock = threading.Lock()
        self.broken = broken
        self.tables: dict = {}   # name -> {pk: {col: val}}
        self.columns: dict = {}  # name -> [cols]
        self.serial: dict = {}
        self.updates = 0

    def run(self, stmt: str) -> str:
        with self.lock:
            out = []
            for s in stmt.split(";"):
                s = s.strip()
                if not s or s.upper().startswith(("BEGIN", "COMMIT")):
                    continue
                r = self._one(s)
                if r:
                    out.append(r)
            return "\n".join(out) + ("\n" if out else "")

    # -- statement shapes ------------------------------------------------

    def _one(self, s: str) -> str:
        u = s.upper()
        if u.startswith("CREATE TABLE"):
            m = re.search(r"CREATE TABLE IF NOT EXISTS (\w+)\s*\((.*)\)",
                          s, re.I | re.S)
            name, cols = m.group(1), m.group(2)
            self.tables.setdefault(name, {})
            self.columns.setdefault(
                name, [c.strip().split()[0] for c in cols.split(",")])
            return ""
        if u.startswith("CREATE INDEX"):
            return ""
        if u.startswith("ALTER TABLE"):
            m = re.search(r"ALTER TABLE (\w+) ADD COLUMN IF NOT EXISTS "
                          r"(\w+) INT NOT NULL DEFAULT (\d+)", s, re.I)
            t, col, d = m.group(1), m.group(2), int(m.group(3))
            if col not in self.columns[t]:
                self.columns[t].append(col)
                for row in self.tables[t].values():
                    row[col] = None if self.broken == "null-default" \
                        else d
            return ""
        if u.startswith("INSERT INTO"):
            return self._insert(s)
        if u.startswith("UPDATE"):
            return self._update(s)
        if u.startswith("SELECT"):
            return self._select(s)
        raise AssertionError(f"fake yb can't parse: {s!r}")

    def _insert(self, s: str) -> str:
        m = re.search(r"INSERT INTO (\w+) \(([^)]*)\) VALUES "
                      r"\(([^)]*)\)(?:\s+ON CONFLICT \((\w+)\) DO "
                      r"(NOTHING|UPDATE SET (\w+) = ('?[\w,]+'?)))?",
                      s, re.I)
        if m is None:
            m2 = re.search(r"INSERT INTO (\w+) DEFAULT VALUES", s, re.I)
            t = m2.group(1)
            pk = self.serial[t] = self.serial.get(t, 0) + 1
            row = {"id": pk}
            for c in self.columns[t][1:]:
                row[c] = 0  # server-side default fills new rows
            self.tables[t][pk] = row
            return ""
        t, cols, vals = m.group(1), m.group(2), m.group(3)
        cols = [c.strip() for c in cols.split(",")]
        vals = [v.strip().strip("'") for v in vals.split(",")]
        row = dict(zip(cols, [self._coerce(v) for v in vals]))
        pk = row[cols[0]]
        exists = pk in self.tables[t]
        if exists:
            if m.group(5) and m.group(5).upper() == "NOTHING":
                return ""
            if m.group(6):  # DO UPDATE SET col = v
                self.tables[t][pk][m.group(6)] = self._coerce(
                    m.group(7).strip("'"))
                return ""
            raise _SqlError(f"duplicate key {pk}")
        self.tables[t][pk] = row
        return ""

    def _coerce(self, v):
        try:
            return int(v)
        except (TypeError, ValueError):
            return v

    def _update(self, s: str) -> str:
        self.updates += 1
        if self.broken == "lost-update" and self.updates % 5 == 0:
            m = re.search(r"RETURNING", s, re.I)
            return "0" if m else ""
        m = re.search(
            r"UPDATE (\w+) SET (\w+) = (.+?) WHERE (\w+) = "
            r"('?\w+'?)(?:\s+AND (\w+) = (\w+))?"
            r"(?:\s+RETURNING (\w+))?$", s, re.I)
        t, col, expr = m.group(1), m.group(2), m.group(3)
        pk = self._coerce(m.group(5).strip("'"))
        rows = self.tables.get(t, {})
        if pk not in rows:
            return "" if not m.group(8) else ""
        row = rows[pk]
        if m.group(6) and row.get(m.group(6)) != self._coerce(
                m.group(7)):
            return ""  # guard failed: 0 rows
        am = re.match(rf"{col} ([+-]) (\d+)", expr.strip())
        if am:
            delta = int(am.group(2))
            row[col] = (row.get(col) or 0) + (
                delta if am.group(1) == "+" else -delta)
        elif expr.strip().startswith(f"{t}.{col} ||"):
            suffix = re.search(r"\|\| ',?(\d+)'", expr).group(1)
            row[col] = f"{row[col]},{suffix}"
        else:
            row[col] = self._coerce(expr.strip().strip("'"))
        return str(row[col]) if m.group(8) else ""

    def _select(self, s: str) -> str:
        m = re.search(r"SELECT (.+?) FROM (\w+)"
                      r"(?:\s+WHERE (\w+) = ('?\w+'?))?"
                      r"(?:\s+ORDER BY .*)?$", s, re.I)
        want, t = m.group(1).strip(), m.group(2)
        rows = list(self.tables.get(t, {}).values())
        if m.group(3):
            pk = self._coerce(m.group(4).strip("'"))
            rows = [r for r in rows if r.get(m.group(3)) == pk]
        out = []
        for r in rows:
            if want == "*":
                cells = [("" if r.get(c) is None else str(r.get(c)))
                         for c in self.columns[t]]
                out.append("|".join(cells))
            else:
                v = r.get(want)
                if v is not None:
                    out.append(str(v))
        return "\n".join(out)


class FakeRunnerFactory:
    dialect = "fake"

    def __init__(self, state=None):
        self.state = state or FakeYb()

    def __call__(self, test, node, timeout=10.0):
        factory = self

        class _R:
            dialect = "fake"

            def run(self, stmt):
                try:
                    return factory.state.run(stmt)
                except _SqlError as e:
                    raise RemoteError("sql failed", exit=1, out="",
                                      err=str(e), cmd="sql",
                                      node=node)

            def close(self):
                pass

        return _R()


def run_clusterless(workload: dict, concurrency=6) -> dict:
    t = testing.noop_test()
    t.update(
        nodes=["n1", "n2", "n3"],
        concurrency=concurrency,
        client=workload["client"],
        checker=workload["checker"],
        generator=gen.clients(workload["generator"]))
    for extra in ("total-amount", "accounts"):
        if extra in workload:
            t[extra] = workload[extra]
    return core.run(t)


def _wl(name, state, **opts):
    w, _ = yb.workload_for(name, dict(opts))
    w["client"].runner_factory = FakeRunnerFactory(state)
    w["client"].runner = state
    w["client"].setup({})
    return w


class TestWorkloadsEndToEnd:
    def test_counter(self):
        fake = FakeYb()
        w = _wl("ysql/counter", fake, ops=60)
        w["client"].runner = FakeRunnerFactory(fake)(None, "n1")
        w["client"].setup({})
        t = run_clusterless(w)
        assert t["results"]["valid?"] is True, t["results"]

    def test_set(self):
        t = run_clusterless(_wl("ysql/set", FakeYb(), ops=60))
        assert t["results"]["valid?"] is True, t["results"]

    def test_bank_conserves(self):
        t = run_clusterless(_wl("ysql/bank", FakeYb(), ops=80))
        assert t["results"]["valid?"] is True, t["results"]

    def test_bank_multitable(self):
        t = run_clusterless(_wl("ysql/bank-multitable", FakeYb(),
                                ops=80))
        assert t["results"]["valid?"] is True, t["results"]

    def test_bank_detects_lost_updates(self):
        t = run_clusterless(_wl("ysql/bank",
                                FakeYb(broken="lost-update"),
                                ops=80))
        assert t["results"]["valid?"] is False

    def test_single_key_acid(self):
        t = run_clusterless(_wl("ysql/single-key-acid", FakeYb(),
                                keys=[0, 1], ops_per_key=40,
                                group_size=3, seed=7))
        assert t["results"]["valid?"] is True, t["results"]

    def test_multi_key_acid(self):
        t = run_clusterless(_wl("ysql/multi-key-acid", FakeYb(),
                                keys=[0, 1], ops_per_key=30,
                                group_size=3, seed=7))
        assert t["results"]["valid?"] is True, t["results"]

    def test_append_elle(self):
        t = run_clusterless(_wl("ysql/append", FakeYb(), ops=100))
        assert t["results"]["valid?"] is True, t["results"]

    def test_default_value_healthy(self):
        t = run_clusterless(_wl("ysql/default-value", FakeYb(),
                                ops=80))
        assert t["results"]["valid?"] is True, t["results"]

    def test_default_value_detects_null_race(self):
        t = run_clusterless(_wl("ysql/default-value",
                                FakeYb(broken="null-default"),
                                ops=120))
        assert t["results"]["valid?"] is False

    def test_matrix_builds(self):
        for name in yb.WORKLOADS:
            w, full = yb.workload_for(name, {"ops": 5})
            assert {"generator", "checker", "client"} <= set(w), name
            assert "/" in full

    def test_bare_name_uses_api_opt(self):
        w, full = yb.workload_for("set", {"ops": 5, "api": "ycql"})
        assert full == "ycql/set"
        assert w["client"].runner_factory is yb.RUNNERS["ycql"]
