"""Yugabyte suite tests: master/tserver orchestration via the dummy
remote, a scripted ysql/ycql runner executing the clients' statement
shapes, and clusterless e2e runs across the API-parameterized workload
matrix — healthy and with seeded bugs (mirrors
yugabyte/src/yugabyte/core.clj's workload matrix)."""

import re
import threading

from jepsen_tpu import control, core, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, RemoteError
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import yugabyte as yb


def make_test(responder=None, nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return t


def cmds(test, node):
    return [a for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_daemons_wired_to_all_masters(self):
        test = make_test()
        db = yb.YbDB()
        with control.with_session(test, "n2"):
            db._start_master(test, "n2")
            db._start_tserver(test, "n2")
        got = " ; ".join(a.cmd for a in cmds(test, "n2"))
        assert "yb-master" in got and "yb-tserver" in got
        assert f"n1:{yb.MASTER_PORT},n2:{yb.MASTER_PORT}," \
               f"n3:{yb.MASTER_PORT}" in got
        assert "--replication_factor 3" in got
        assert "--start_pgsql_proxy" in got

    def test_kill_greps_both(self):
        test = make_test()
        db = yb.YbDB()
        with control.with_session(test, "n1"):
            db.kill(test, "n1")
        got = " ; ".join(a.cmd for a in cmds(test, "n1"))
        assert "yb-master" in got and "yb-tserver" in got


class _SqlError(Exception):
    pass


class FakeYb:
    """Executes the statement shapes the suite's clients emit, over
    in-memory tables with a global lock (statements and BEGIN..COMMIT
    blocks are atomic — a serializable-by-construction store).
    broken='null-default' makes ALTER..DEFAULT leave existing rows
    NULL (the DDL race default_value.clj hunts);
    broken='lost-update' drops every 5th UPDATE silently."""

    def __init__(self, broken=None):
        self.lock = threading.Lock()
        self.broken = broken
        self.tables: dict = {}   # name -> {pk: {col: val}}
        self.columns: dict = {}  # name -> [cols]
        self.serial: dict = {}
        self.updates = 0

    def run(self, stmt: str) -> str:
        with self.lock:
            out = []
            for s in stmt.split(";"):
                s = s.strip()
                if not s or s.upper().startswith(
                        ("BEGIN", "COMMIT", "END TRANSACTION")):
                    continue
                r = self._one(s)
                if r:
                    out.append(r)
            return "\n".join(out) + ("\n" if out else "")

    # -- statement shapes ------------------------------------------------

    def _one(self, s: str) -> str:
        u = s.upper()
        if u.startswith("CREATE TABLE"):
            m = re.search(r"CREATE TABLE IF NOT EXISTS (\w+)\s*\((.*)\)",
                          s, re.I | re.S)
            name, cols = m.group(1), m.group(2)
            self.tables.setdefault(name, {})
            self.columns.setdefault(
                name, [c.strip().split()[0] for c in cols.split(",")])
            return ""
        if u.startswith("CREATE INDEX"):
            return ""
        if u.startswith("ALTER TABLE"):
            m = re.search(r"ALTER TABLE (\w+) ADD COLUMN IF NOT EXISTS "
                          r"(\w+) INT NOT NULL DEFAULT (\d+)", s, re.I)
            t, col, d = m.group(1), m.group(2), int(m.group(3))
            if col not in self.columns[t]:
                self.columns[t].append(col)
                for row in self.tables[t].values():
                    row[col] = None if self.broken == "null-default" \
                        else d
            return ""
        if u.startswith("INSERT INTO"):
            return self._insert(s)
        if u.startswith("UPDATE"):
            return self._update(s)
        if u.startswith("SELECT"):
            return self._select(s)
        raise AssertionError(f"fake yb can't parse: {s!r}")

    def _insert(self, s: str) -> str:
        m = re.search(r"INSERT INTO (\w+) \(([^)]*)\) VALUES "
                      r"\(([^)]*)\)(?:\s+ON CONFLICT \((\w+)\) DO "
                      r"(NOTHING|UPDATE SET (\w+) = (.+)))?",
                      s, re.I)
        if m is None:
            m2 = re.search(r"INSERT INTO (\w+) DEFAULT VALUES", s, re.I)
            t = m2.group(1)
            pk = self.serial[t] = self.serial.get(t, 0) + 1
            row = {"id": pk}
            for c in self.columns[t][1:]:
                row[c] = 0  # server-side default fills new rows
            self.tables[t][pk] = row
            return ""
        t, cols, vals = m.group(1), m.group(2), m.group(3)
        cols = [c.strip() for c in cols.split(",")]
        vals = [v.strip().strip("'") for v in vals.split(",")]
        row = dict(zip(cols, [self._coerce(v) for v in vals]))
        pk = row[cols[0]]
        exists = pk in self.tables[t]
        if exists:
            if m.group(5) and m.group(5).upper() == "NOTHING":
                return ""
            if m.group(6):  # DO UPDATE SET col = v | col = t.col || ',v'
                col, expr = m.group(6), m.group(7).strip()
                old = self.tables[t][pk]
                cm = re.match(rf"{t}\.{col} \|\| ',?(\w+)'$", expr)
                if cm:
                    old[col] = f"{old[col]},{cm.group(1)}"
                else:
                    old[col] = self._coerce(expr.strip("'"))
                return ""
            raise _SqlError(f"duplicate key {pk}")
        self.tables[t][pk] = row
        return ""

    def _coerce(self, v):
        # NOT int(): python accepts '_' digit separators, so the
        # multireg id '0_0' would silently coerce to 0
        if isinstance(v, str) and re.fullmatch(r"-?\d+", v):
            return int(v)
        return v

    def _update(self, s: str) -> str:
        self.updates += 1
        if self.broken == "lost-update" and self.updates % 5 == 0:
            m = re.search(r"RETURNING", s, re.I)
            return "0" if m else ""
        m = re.search(
            r"UPDATE (\w+) SET (\w+) = (.+?) WHERE (\w+) = "
            r"('?\w+'?)(?:\s+AND (\w+) = (\w+))?"
            r"(?:\s+RETURNING (\w+))?$", s, re.I)
        t, col, expr = m.group(1), m.group(2), m.group(3)
        pk = self._coerce(m.group(5).strip("'"))
        rows = self.tables.get(t, {})
        if pk not in rows:
            return "" if not m.group(8) else ""
        row = rows[pk]
        if m.group(6) and row.get(m.group(6)) != self._coerce(
                m.group(7)):
            return ""  # guard failed: 0 rows
        am = re.match(rf"{col} ([+-]) (\d+)", expr.strip())
        if am:
            delta = int(am.group(2))
            row[col] = (row.get(col) or 0) + (
                delta if am.group(1) == "+" else -delta)
        elif expr.strip().startswith(f"{t}.{col} ||"):
            suffix = re.search(r"\|\| ',?(\d+)'", expr).group(1)
            row[col] = f"{row[col]},{suffix}"
        else:
            row[col] = self._coerce(expr.strip().strip("'"))
        return str(row[col]) if m.group(8) else ""

    def _select(self, s: str) -> str:
        m = re.match(r"SELECT 'm(\d+)=' \|\| COALESCE\(\(SELECT "
                     r"(?:CAST\(v AS TEXT\)|v) FROM (\w+) WHERE "
                     r"k = (\d+)\), '~'\)$", s, re.I)
        if m:
            i, t, k = m.group(1), m.group(2), int(m.group(3))
            row = self.tables.get(t, {}).get(k)
            v = row.get("v") if row else None
            return f"m{i}=" + ("~" if v is None else str(v))
        if re.match(r"SELECT \d+ AS id, balance FROM bank\d+",
                    s, re.I):
            parts = re.findall(
                r"SELECT (\d+) AS id, balance FROM (bank\d+) "
                r"WHERE id = 0", s, re.I)
            out = []
            for a, t in parts:
                row = self.tables.get(t, {}).get(0)
                if row is not None:
                    out.append(f"{a}|{row['balance']}")
            return "\n".join(out)
        m = re.match(r"SELECT id, balance FROM bank ORDER BY id$",
                     s, re.I)
        if m:
            rows = sorted(self.tables.get("bank", {}).items())
            return "\n".join(f"{i}|{r['balance']}"
                              for i, r in rows)
        m = re.match(r"SELECT id, val FROM multireg WHERE id IN "
                     r"\(([^)]*)\)$", s, re.I)
        if m:
            ids = [x.strip().strip("'") for x in
                   m.group(1).split(",")]
            out = []
            for i in ids:
                row = self.tables.get("multireg", {}).get(i)
                if row is not None:
                    out.append(f"{i}|{row['val']}")
            return "\n".join(out)
        m = re.search(r"SELECT (.+?) FROM (\w+)"
                      r"(?:\s+WHERE (\w+) = ('?\w+'?))?"
                      r"(?:\s+ORDER BY .*)?$", s, re.I)
        want, t = m.group(1).strip(), m.group(2)
        rows = list(self.tables.get(t, {}).values())
        if m.group(3):
            pk = self._coerce(m.group(4).strip("'"))
            rows = [r for r in rows if r.get(m.group(3)) == pk]
        out = []
        for r in rows:
            if want == "*":
                cells = [("" if r.get(c) is None else str(r.get(c)))
                         for c in self.columns[t]]
                out.append("|".join(cells))
            else:
                v = r.get(want)
                if v is not None:
                    out.append(str(v))
        return "\n".join(out)


class FakeRunnerFactory:
    dialect = "fake"

    def __init__(self, state=None):
        self.state = state or FakeYb()

    def __call__(self, test, node, timeout=10.0):
        factory = self

        class _R:
            dialect = "fake"

            def run(self, stmt):
                try:
                    return factory.state.run(stmt)
                except _SqlError as e:
                    raise RemoteError("sql failed", exit=1, out="",
                                      err=str(e), cmd="sql",
                                      node=node)

            def close(self):
                pass

        return _R()


def run_clusterless(workload: dict, concurrency=6) -> dict:
    t = testing.noop_test()
    t.update(
        nodes=["n1", "n2", "n3"],
        concurrency=concurrency,
        client=workload["client"],
        checker=workload["checker"],
        generator=gen.clients(workload["generator"]))
    for extra in ("total-amount", "accounts"):
        if extra in workload:
            t[extra] = workload[extra]
    return core.run(t)


def _wl(name, state, **opts):
    w, _ = yb.workload_for(name, dict(opts))
    w["client"].runner_factory = FakeRunnerFactory(state)
    w["client"].runner = state
    w["client"].setup({})
    return w


class TestWorkloadsEndToEnd:
    def test_counter(self):
        fake = FakeYb()
        w = _wl("ysql/counter", fake, ops=60)
        w["client"].runner = FakeRunnerFactory(fake)(None, "n1")
        w["client"].setup({})
        t = run_clusterless(w)
        assert t["results"]["valid?"] is True, t["results"]

    def test_set(self):
        t = run_clusterless(_wl("ysql/set", FakeYb(), ops=60))
        assert t["results"]["valid?"] is True, t["results"]

    def test_bank_conserves(self):
        t = run_clusterless(_wl("ysql/bank", FakeYb(), ops=80))
        assert t["results"]["valid?"] is True, t["results"]

    def test_bank_multitable(self):
        t = run_clusterless(_wl("ysql/bank-multitable", FakeYb(),
                                ops=80))
        assert t["results"]["valid?"] is True, t["results"]

    def test_bank_detects_lost_updates(self):
        t = run_clusterless(_wl("ysql/bank",
                                FakeYb(broken="lost-update"),
                                ops=80))
        assert t["results"]["valid?"] is False

    def test_single_key_acid(self):
        t = run_clusterless(_wl("ysql/single-key-acid", FakeYb(),
                                keys=[0, 1], ops_per_key=40,
                                group_size=3, seed=7))
        assert t["results"]["valid?"] is True, t["results"]

    def test_multi_key_acid(self):
        t = run_clusterless(_wl("ysql/multi-key-acid", FakeYb(),
                                keys=[0, 1], ops_per_key=30,
                                group_size=3, seed=7))
        assert t["results"]["valid?"] is True, t["results"]

    def test_append_elle(self):
        t = run_clusterless(_wl("ysql/append", FakeYb(), ops=100))
        assert t["results"]["valid?"] is True, t["results"]

    def test_default_value_healthy(self):
        t = run_clusterless(_wl("ysql/default-value", FakeYb(),
                                ops=80))
        assert t["results"]["valid?"] is True, t["results"]

    def test_default_value_detects_null_race(self):
        t = run_clusterless(_wl("ysql/default-value",
                                FakeYb(broken="null-default"),
                                ops=120))
        assert t["results"]["valid?"] is False

    def test_matrix_builds(self):
        for name in yb.WORKLOADS:
            w, full = yb.workload_for(name, {"ops": 5})
            assert {"generator", "checker", "client"} <= set(w), name
            assert "/" in full

    def test_bare_name_uses_api_opt(self):
        w, full = yb.workload_for("set", {"ops": 5, "api": "ycql"})
        assert full == "ycql/set"
        assert w["client"].runner_factory is yb.RUNNERS["ycql"]


class FakeYcql:
    """A CQL-dialect store: INSERT is an upsert, BEGIN TRANSACTION ..
    END TRANSACTION batches atomically, UPDATE .. IF val = x answers
    with an [applied] row, counter updates auto-create rows, and
    SELECT output carries ycqlsh-style headers + '(n rows)'."""

    dialect = "ycql"

    def __init__(self):
        self.lock = threading.Lock()
        self.tables: dict = {}

    def close(self):
        pass

    def run(self, stmt: str) -> str:
        with self.lock:
            out = []
            for s in stmt.split(";"):
                s = s.strip()
                # YCQL batches: 'BEGIN TRANSACTION <stmt>' glues the
                # first statement to the opener (no semicolon after it)
                if s.upper().startswith("BEGIN TRANSACTION"):
                    s = s[len("BEGIN TRANSACTION"):].strip()
                if not s or s.upper().startswith("END TRANSACTION"):
                    continue
                r = self._one(s)
                if r:
                    out.append(r)
            return "\n".join(out)

    def _one(self, s: str) -> str:
        u = s.upper()
        if u.startswith("CREATE TABLE"):
            name = re.search(r"CREATE TABLE IF NOT EXISTS (\w+)",
                             s, re.I).group(1)
            self.tables.setdefault(name, {})
            return ""
        m = re.match(r"INSERT INTO (\w+) \(([^)]*)\) VALUES "
                     r"\(([^)]*)\)$", s, re.I)
        if m:  # CQL insert = upsert
            t = m.group(1)
            cols = [c.strip() for c in m.group(2).split(",")]
            vals = [v.strip().strip("'") for v in m.group(3).split(",")]
            row = dict(zip(cols, vals))
            self.tables[t][row[cols[0]]] = row
            return ""
        m = re.match(r"UPDATE registers SET val = (\d+) WHERE "
                     r"id = (\w+) IF val = (\d+)$", s, re.I)
        if m:
            new, k, old = m.group(1), m.group(2), m.group(3)
            row = self.tables["registers"].get(k)
            if row and row.get("val") == old:
                row["val"] = new
                return " [applied]\n-----------\n      True"
            return " [applied]\n-----------\n     False"
        m = re.match(r"UPDATE counters SET count = count \+ (\d+) "
                     r"WHERE id = (\w+)$", s, re.I)
        if m:
            rows = self.tables.setdefault("counters", {})
            row = rows.setdefault(m.group(2), {"count": 0})
            row["count"] = int(row.get("count", 0)) + int(m.group(1))
            return ""
        m = re.match(r"UPDATE bank SET balance = balance ([+-]) "
                     r"(\d+) WHERE id = (\w+)$", s, re.I)
        if m:
            row = self.tables["bank"][m.group(3)]
            d = int(m.group(2))
            row["balance"] = int(row["balance"]) + (
                d if m.group(1) == "+" else -d)
            return ""
        m = re.match(r"SELECT val FROM registers WHERE id = (\w+)$",
                     s, re.I)
        if m:
            row = self.tables["registers"].get(m.group(1))
            body = str(row["val"]) if row and "val" in row else ""
            return f" val\n-----\n{body}\n\n(1 rows)"
        m = re.match(r"SELECT count FROM counters WHERE id = (\w+)$",
                     s, re.I)
        if m:
            row = self.tables.get("counters", {}).get(m.group(1))
            body = str(row["count"]) if row else ""
            return f" count\n-------\n{body}\n\n(1 rows)"
        if re.match(r"SELECT v FROM elements$", s, re.I):
            vals = "\n".join(str(r["v"]) for r in
                             self.tables.get("elements", {}).values())
            return f" v\n---\n{vals}\n\n(n rows)"
        m = re.match(r"SELECT id, balance FROM bank$", s, re.I)
        if m:
            rows = sorted(self.tables.get("bank", {}).items(),
                          key=lambda kv: int(kv[0]))
            body = "\n".join(f" {i} | {r['balance']}"
                             for i, r in rows)
            return f" id | balance\n----+--------\n{body}\n\n(8 rows)"
        m = re.match(r"SELECT k, v FROM lf WHERE k IN \(([^)]*)\)$",
                     s, re.I)
        if m:
            ks = [int(x) for x in m.group(1).split(",")]
            rows = [f" {k} | {r['v']}" for k, r in
                    sorted(self.tables.get("lf", {}).items(),
                           key=lambda kv: int(kv[0]))
                    if int(k) in ks]
            return " k | v\n---+---\n" + "\n".join(rows)
        raise AssertionError(f"fake ycql can't parse: {s!r}")


class FakeYcqlFactory:
    def __init__(self, state=None):
        self.state = state or FakeYcql()

    def __call__(self, test, node, timeout=10.0):
        return self.state


class TestYcqlDialect:
    def _wl(self, name, state, **opts):
        w, _ = yb.workload_for(name, dict(opts, api="ycql"))
        w["client"].runner_factory = FakeYcqlFactory(state)
        w["client"].runner = state
        w["client"].setup({})
        return w

    def test_single_key_acid_over_cql(self):
        t = run_clusterless(self._wl("single-key-acid", FakeYcql(),
                                     keys=[0, 1], ops_per_key=40,
                                     group_size=3, seed=7))
        assert t["results"]["valid?"] is True, t["results"]
        # non-vacuous: CAS ops really ran both ways
        oks = [o for o in t["history"]
               if o.type == "ok" and o.f == "cas"]
        fails = [o for o in t["history"]
                 if o.type == "fail" and o.f == "cas"]
        assert oks and fails

    def test_counter_over_cql(self):
        t = run_clusterless(self._wl("counter", FakeYcql(), ops=60))
        assert t["results"]["valid?"] is True, t["results"]
        reads = [o for o in t["history"]
                 if o.type == "ok" and o.f == "read"
                 and o.value and o.value > 0]
        assert reads, "counter reads must observe real values"

    def test_set_over_cql(self):
        t = run_clusterless(self._wl("set", FakeYcql(), ops=60))
        assert t["results"]["valid?"] is True, t["results"]

    def test_bank_over_cql(self):
        t = run_clusterless(self._wl("bank", FakeYcql(), ops=60))
        assert t["results"]["valid?"] is True, t["results"]
        reads = [o for o in t["history"]
                 if o.type == "ok" and o.f == "read" and o.value]
        assert reads and all(sum(r.value.values()) == 80
                             for r in reads)


class TestAppendNonVacuous:
    def test_append_reads_observe_values(self):
        t = run_clusterless(_wl("ysql/append", FakeYb(), ops=120))
        assert t["results"]["valid?"] is True, t["results"]
        seen = [v for o in t["history"]
                if o.type == "ok" and o.f == "txn"
                for f, k, v in o.value if f == "r" and v]
        assert seen, "append reads must observe appended lists"
