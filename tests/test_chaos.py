"""Chaos-rig suite: the harness survives faults injected into ITSELF.

Fixed-seed smoke for tier-1 (ISSUE 5): every test asserts the four
run-level invariants — the run terminates, the history stays
well-formed, teardown heals, the store validates — plus the analysis
invariant: the verdict is True/False/'unknown', never an exception.
"""

import json
import os

import pytest

from jepsen_tpu import chaos, checker, control, core, store, telemetry, testing
from jepsen_tpu import client as jclient
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as jnemesis
from jepsen_tpu import net as jnet
from jepsen_tpu.control import retry as retry_mod
from jepsen_tpu.control.core import (Action, Remote, Result,
                                     TransportError)
from jepsen_tpu.control.dummy import DummyRemote, DummySession
from jepsen_tpu.history import History, op
from jepsen_tpu.store import format as fmt

SEED = 1337


class RecordingNet(jnet.Net):
    """Counts heal/drop calls; never touches a real network."""

    def __init__(self):
        self.heals = 0
        self.drops = 0

    def drop(self, test, src, dest):
        self.drops += 1

    def drop_all(self, test, grudge):
        self.drops += 1

    def heal(self, test):
        self.heals += 1

    def slow(self, *a, **kw):
        pass

    def flaky(self, *a, **kw):
        pass

    def fast(self, *a, **kw):
        pass

    def shape(self, *a, **kw):
        pass


def assert_invariants(test, tmp_path, expect_results=True):
    """The four run-level chaos invariants over a finished run."""
    # 2. history well-formed
    problems = chaos.validate_history(test["history"])
    assert problems == []
    # 4. store validates: the op log is fully intact (no torn tail —
    # the writer sealed it) and every op reads back
    d = store.path(test)
    log = d / "history.jlog"
    assert fmt._valid_prefix_end(log) == log.stat().st_size
    assert len(list(fmt.read_ops(log))) == len(test["history"])
    if expect_results:
        assert (d / "results.json").exists()
        with open(d / "results.json") as f:
            results = json.load(f)
        # 5. analysis succeeded or degraded cleanly
        assert results["valid?"] in (True, False, "unknown")


def chaos_run(tmp_path, name, *, client_rates=None, nemesis=None,
              net=None, nodes=3, ops=120, quarantine=False,
              checker_=None):
    state = testing.AtomState()
    inner = testing.AtomClient(state, latency_s=0.0005)
    test = testing.noop_test()
    test.update(
        name=name, store_base=str(tmp_path),
        nodes=[f"n{i}" for i in range(1, nodes + 1)],
        concurrency=nodes,
        net=net if net is not None else RecordingNet(),
        db=testing.AtomDB(state),
        client=chaos.ChaosClient(inner, seed=SEED,
                                 rates=client_rates),
        checker=checker_ or checker.compose({
            "stats": checker.stats(),
            "exceptions": checker.unhandled_exceptions()}),
        generator=gen.clients(
            gen.limit(ops, lambda: {"f": "read"}),
            gen.limit(6, gen.cycle(gen.phases(
                gen.sleep(0.02), {"type": "info", "f": "start"},
                gen.sleep(0.02), {"type": "info", "f": "stop"})))))
    if nemesis is not None:
        test["nemesis"] = nemesis
    if quarantine:
        test["quarantine?"] = {"threshold": 2, "cooldown_s": 60}
    return core.run(test)  # invariant 1: this returns


class TestChaosClientRun:
    def test_seeded_chaos_run_keeps_invariants(self, tmp_path):
        telemetry.reset()
        t = chaos_run(tmp_path, "chaos-client",
                      nemesis=jnemesis.partition_random_node())
        assert_invariants(t, tmp_path)
        # the seed must actually have injected faults, or this suite
        # tests nothing
        tally = t["client"].tally
        assert sum(tally.values()) > 0
        # injected faults surfaced honestly in the history
        types = {o.type for o in t["history"]}
        assert "ok" in types

    def test_chaos_faults_map_to_honest_completions(self, tmp_path):
        t = chaos_run(tmp_path, "chaos-honest", client_rates={
            "drop-connection": 0.2, "command-timeout": 0.2,
            "exception": 0.1})
        assert_invariants(t, tmp_path)
        hist = t["history"]
        tally = t["client"].tally
        fails = sum(1 for o in hist if o.type == "fail")
        infos = sum(1 for o in hist if o.type == "info"
                    and isinstance(o.process, int))
        # drops became definite :fail; timeouts/exceptions :info
        assert fails >= tally["drop-connection"] > 0
        assert infos >= tally["command-timeout"] > 0
        assert tally["exception"] > 0

    def test_nemesis_teardown_crash_still_heals(self, tmp_path):
        """Invariant 3: a dead nemesis can't leak partitions — the
        final heal in run_case fires anyway."""
        net = RecordingNet()
        nem = chaos.CrashingNemesis(jnemesis.partition_halves())
        telemetry.reset()
        t = chaos_run(tmp_path, "chaos-nem-crash", net=net, nemesis=nem)
        assert_invariants(t, tmp_path)
        assert net.heals >= 1  # healed despite the teardown crash
        assert telemetry.get().counters().get(
            "chaos.nemesis-teardown-crashes", 0) >= 1


class TestChaosControlPlane:
    def test_retry_stack_absorbs_transport_chaos(self, monkeypatch,
                                                 tmp_path):
        """Commands through retry(chaos(dummy)) still succeed; the
        chaotic transport shows up as retries, not run failures."""
        monkeypatch.setattr(retry_mod, "BACKOFF_S", 0.001)
        crm = chaos.ChaosRemote(DummyRemote(), seed=SEED, rates={
            "drop-connection": 0.15, "command-timeout": 0.1})
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"],
                    remote=retry_mod.RetryingRemote(crm), ssh={})
        test = control.open_sessions(test)
        try:
            for _ in range(10):
                outs = control.on_nodes(
                    test, lambda t, n: control.exec_("true"))
                assert set(outs) == {"n1", "n2", "n3"}
        finally:
            control.close_sessions(test)
        assert sum(crm.tally.values()) > 0

    def test_quarantine_dead_node_run_degrades(self, tmp_path):
        """A node dead from the start: ops crash to :info, the run
        finishes with a :degraded marker instead of aborting."""

        class DeadNodeRemote(Remote):
            def connect(self, spec):
                if spec.get("host") == "n2":
                    raise TransportError("connection refused",
                                         node="n2")
                return DummySession(spec.get("host"))

        class CmdClient(jclient.Client):
            def __init__(self, node=None):
                self.node = node

            def open(self, test, node):
                return CmdClient(node)

            def invoke(self, test, op_):
                with control.with_session(test, self.node):
                    control.exec_("true")
                return op_.copy(type="ok")

        test = testing.noop_test()
        test.update(name="chaos-quarantine", store_base=str(tmp_path),
                    nodes=["n1", "n2"], concurrency=2,
                    remote=DeadNodeRemote(), ssh={},
                    net=RecordingNet(),
                    client=CmdClient(), checker=checker.stats(),
                    generator=gen.clients(
                        gen.limit(24, lambda: {"f": "read"})))
        test["quarantine?"] = {"threshold": 2, "cooldown_s": 60}
        t = core.run(test)
        res = t["results"]
        assert res["valid?"] in (True, False, "unknown")
        assert res["degraded"]["quarantined-nodes"] == ["n2"]
        assert chaos.validate_history(t["history"]) == []
        # n1's ops succeeded; n2's crashed fast to :info or failed
        assert any(o.type == "ok" for o in t["history"])

    def test_degraded_client_open_closes_half_open_client(
            self, tmp_path):
        """open() succeeded, then setup() died with a transport error
        under quarantine: the half-open client is closed, not leaked
        for the rest of the (continuing) run."""
        closed = []

        class HalfDeadClient(jclient.Client):
            def __init__(self, node=None):
                self.node = node

            def open(self, test, node):
                return HalfDeadClient(node)

            def setup(self, test):
                if self.node == "n2":
                    raise TransportError("died in setup", node="n2")

            def invoke(self, test, op_):
                return op_.copy(type="ok")

            def close(self, test):
                closed.append(self.node)

        test = testing.noop_test()
        test.update(name="chaos-half-open", store_base=str(tmp_path),
                    nodes=["n1", "n2"], concurrency=2,
                    net=RecordingNet(),
                    client=HalfDeadClient(), checker=checker.stats(),
                    generator=gen.clients(
                        gen.limit(8, lambda: {"f": "read"})))
        test["quarantine?"] = {"threshold": 2, "cooldown_s": 60}
        t = core.run(test)
        assert "n2" in closed
        assert t["results"]["valid?"] in (True, False, "unknown")

    def test_teardown_real_bug_not_masked_by_dead_node(self):
        """Every node's teardown is attempted: a dead node's transport
        failure must not hide a genuine teardown bug on a live one
        (on_nodes alone surfaces only the FIRST node's failure)."""
        from jepsen_tpu import util

        test = testing.noop_test()
        test.update(nodes=["n1", "n2"],
                    sessions={"n1": DummySession("n1"),
                              "n2": DummySession("n2")},
                    health=object())  # quarantine active

        def node_fn(t, n):
            if n == "n1":
                raise TransportError("down", node="n1")
            raise AssertionError("real teardown bug")

        with pytest.raises(util.RealPmapError) as e:
            core._teardown_tolerantly(test, "db", node_fn)
        kinds = {type(x) for x in e.value.errors}
        assert AssertionError in kinds
        assert TransportError in kinds

    def test_teardown_all_transport_degrades(self):
        test = testing.noop_test()
        test.update(nodes=["n1", "n2"],
                    sessions={"n1": DummySession("n1"),
                              "n2": DummySession("n2")},
                    health=object())
        telemetry.reset()

        def node_fn(t, n):
            raise TransportError("down", node=n)

        core._teardown_tolerantly(test, "db", node_fn)  # must not raise
        assert telemetry.get().counters()[
            "core.degraded-teardowns"] == 1

    def test_transport_failure_classification(self):
        """Raw network-errno OSErrors (EHOSTUNREACH et al., which
        Python does NOT map onto ConnectionError) degrade under
        quarantine; local misconfiguration never does."""
        import errno

        assert core._transport_failure(
            OSError(errno.EHOSTUNREACH, "no route to host"))
        assert core._transport_failure(ConnectionRefusedError())
        assert core._transport_failure(TransportError("down"))
        assert not core._transport_failure(
            FileNotFoundError(2, "missing client binary"))
        assert not core._transport_failure(TypeError("client bug"))

    def test_breaker_opens_and_heals(self):
        from jepsen_tpu.control import health

        b = health.CircuitBreaker("n1", threshold=2, cooldown_s=0.05)
        assert b.admit()
        b.failure()
        assert not b.is_open
        b.failure()
        assert b.is_open
        assert not b.admit()  # quarantined: fail fast
        import time
        time.sleep(0.06)
        assert b.admit()       # half-open probe
        assert not b.admit()   # only ONE probe
        b.success()
        assert not b.is_open
        assert b.admit()

    def test_lazy_connect_does_not_heal_circuit(self, monkeypatch):
        """The default stack's RetryingRemote.connect is lazy (no
        network I/O): it must not count as a breaker success, or a
        dead node's failure count resets on every per-op reconnect
        and the circuit never opens."""
        monkeypatch.setattr(retry_mod, "BACKOFF_S", 0.001)

        from jepsen_tpu.control import health

        class Dead(Remote):
            def connect(self, spec):
                class S(DummySession):
                    def execute(self, action):
                        raise TransportError("down")

                return S(spec.get("host"))

        reg = health.HealthRegistry(threshold=3)
        guarded = health.GuardedRemote(
            retry_mod.RetryingRemote(Dead(), budget_limit=2), reg)
        lazy = health.LazyConnectSession(guarded, {"host": "n1"})
        for _ in range(4):
            with pytest.raises(TransportError):
                lazy.execute(Action(cmd="true"))
        assert reg.quarantined() == ["n1"]

    def test_half_open_probe_frees_on_non_transport_error(self):
        """A probe that dies locally (OSError, a caller bug — not a
        transport verdict) must free the probe slot; otherwise the
        circuit wedges half-open and the node never heals."""
        import time

        from jepsen_tpu.control import health

        class LocalBoom(DummySession):
            def execute(self, action):
                raise OSError("disk full")

        b = health.CircuitBreaker("n1", threshold=1, cooldown_s=0.01)
        b.failure()
        assert b.is_open
        time.sleep(0.02)
        sess = health.GuardedSession(LocalBoom("n1"), b)
        with pytest.raises(OSError):
            sess.execute(Action(cmd="true"))
        assert b.is_open  # no verdict on the node: still quarantined
        assert b.admit()  # but the NEXT probe is admitted, not wedged

    def test_guarded_remote_counts_only_transport_errors(self):
        from jepsen_tpu.control import health

        class ExitingSession(DummySession):
            def execute(self, action):
                return Result(exit=1, out="", err="nope",
                              cmd=action.cmd)

        class R(Remote):
            def connect(self, spec):
                return ExitingSession(spec.get("host"))

        reg = health.HealthRegistry(threshold=1)
        sess = health.GuardedRemote(R(), reg).connect({"host": "n1"})
        for _ in range(5):
            sess.execute(Action(cmd="false"))  # nonzero exit, no raise
        assert reg.quarantined() == []  # command failures never count


class TestRetryBudget:
    def test_budget_exhaustion_fails_fast(self, monkeypatch):
        monkeypatch.setattr(retry_mod, "BACKOFF_S", 0.001)

        class AlwaysDown(Remote):
            def __init__(self):
                self.attempts = 0

            def connect(self, spec):
                outer = self

                class S(DummySession):
                    def execute(self, action):
                        outer.attempts += 1
                        raise TransportError("down")

                return S(spec.get("host"))

        down = AlwaysDown()
        remote = retry_mod.RetryingRemote(down, budget_limit=3)
        sess = remote.connect({"host": "n1"})
        telemetry.reset()
        with pytest.raises(TransportError):
            sess.execute(Action(cmd="true"))
        # initial try + 3 budgeted retries, NOT the full 5 retries
        first = down.attempts
        assert first == 4
        # budget is spent: the next command gets exactly one attempt
        with pytest.raises(TransportError):
            sess.execute(Action(cmd="true"))
        assert down.attempts == first + 1
        assert telemetry.get().counters()[
            "control.retry.budget-exhausted"] >= 1

    def test_decorrelated_jitter_bounds(self):
        import random

        rng = random.Random(7)
        s = retry_mod.BACKOFF_S
        for _ in range(100):
            s2 = retry_mod.decorrelated_jitter(s, rng=rng)
            assert retry_mod.BACKOFF_S <= s2 <= retry_mod.BACKOFF_CAP_S
            s = s2

    def test_budget_refunds_on_success(self, monkeypatch):
        """Alternating blip/success forever must never exhaust a small
        budget: each success refunds it, so a multi-hour run's nemesis
        windows can't starve late-run retries."""
        monkeypatch.setattr(retry_mod, "BACKOFF_S", 0.001)

        class Flaky(Remote):
            def __init__(self):
                self.calls = 0

            def connect(self, spec):
                outer = self

                class S(DummySession):
                    def execute(self, action):
                        outer.calls += 1
                        if outer.calls % 2 == 1:
                            raise TransportError("blip")
                        return Result(0, "ok", "", action.cmd)

                return S(spec.get("host"))

        remote = retry_mod.RetryingRemote(Flaky(), budget_limit=2)
        sess = remote.connect({"host": "n1"})
        for _ in range(10):  # 10 blips > budget 2, refunded each time
            assert sess.execute(Action(cmd="x")).out == "ok"
        assert not sess.budget.exhausted

    def test_budget_not_shared_across_sessions(self, monkeypatch):
        monkeypatch.setattr(retry_mod, "BACKOFF_S", 0.001)

        class Flaky(Remote):
            calls = 0

            def connect(self, spec):
                outer = self

                class S(DummySession):
                    def execute(self, action):
                        Flaky.calls += 1
                        if Flaky.calls % 2 == 1:
                            raise TransportError("blip")
                        return Result(0, "ok", "", action.cmd)

                return S(spec.get("host"))

        remote = retry_mod.RetryingRemote(Flaky(), budget_limit=2)
        s1 = remote.connect({"host": "n1"})
        s2 = remote.connect({"host": "n2"})
        assert s1.execute(Action(cmd="x")).out == "ok"
        assert s2.execute(Action(cmd="x")).out == "ok"
        assert s1.budget is not s2.budget


class TestCheckerTimeout:
    def test_hung_checker_degrades_to_unknown(self):
        import time

        class Hung(checker.Checker):
            def check(self, test, hist, opts=None):
                time.sleep(30)

        hist = History([op(type="invoke", process=0, f="read",
                           value=None),
                        op(type="ok", process=0, f="read", value=1)])
        telemetry.reset()
        c = checker.compose({"hung": Hung(), "stats": checker.stats()})
        res = c.check({"checker_timeout_s": 0.2}, hist, {})
        assert res["hung"]["valid?"] == "unknown"
        assert "timed out" in res["hung"]["error"]
        assert res["stats"]["valid?"] is True  # others still ran
        assert res["valid?"] == "unknown"
        assert telemetry.get().counters()["checker.timeouts"] >= 1

    def test_none_returning_checker_is_not_a_timeout(self):
        res = checker.check_safe(checker.noop(), {}, History([]),
                                 timeout_s=5.0)
        assert res is None


class TestDegradationLadder:
    def _hist(self, valid=True):
        ops = [op(index=0, time=0, type="invoke", process=0, f="write",
                  value=1),
               op(index=1, time=1, type="ok", process=0, f="write",
                  value=1),
               op(index=2, time=2, type="invoke", process=1, f="read",
                  value=None),
               op(index=3, time=3, type="ok", process=1, f="read",
                  value=1 if valid else 99)]
        return History(ops)

    def test_forced_oom_walks_ladder_to_host(self, monkeypatch):
        from jepsen_tpu.checker import models
        from jepsen_tpu.tpu import wgl

        m = models.register(0)
        want = wgl.analysis(m, self._hist())
        assert want["valid?"] is True

        def boom(*a, **kw):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

        monkeypatch.setattr(wgl, "_launch", boom)
        telemetry.reset()
        got = wgl.analysis(m, self._hist())
        assert got["valid?"] is want["valid?"]  # identical verdict
        assert got["degradation"][-1] == "host-fallback"
        assert "host-floor" in got["degradation"]
        c = telemetry.get().counters()
        assert c["wgl.ladder.oom"] >= 1
        assert c["wgl.ladder.host-floor"] >= 1

    def test_forced_oom_invalid_verdict_parity(self, monkeypatch):
        from jepsen_tpu.checker import models
        from jepsen_tpu.tpu import wgl

        m = models.register(0)
        want = wgl.analysis(m, self._hist(valid=False))
        assert want["valid?"] is False

        monkeypatch.setattr(wgl, "_launch", lambda *a, **kw: (_ for _ in
                            ()).throw(RuntimeError("RESOURCE_EXHAUSTED")))
        got = wgl.analysis(m, self._hist(valid=False))
        assert got["valid?"] is False
        assert "degradation" in got

    def test_compile_failure_classified(self):
        from jepsen_tpu.tpu import wgl

        class XlaRuntimeError(Exception):
            pass

        assert wgl.device_error_kind(
            RuntimeError("RESOURCE_EXHAUSTED: oom")) == "oom"
        assert wgl.device_error_kind(
            XlaRuntimeError("error during compilation")) == "compile"
        # XlaRuntimeError is ALSO jax's runtime-error type: an
        # execute-time failure is 'device' (degradable but loud),
        # not 'compile'
        assert wgl.device_error_kind(
            XlaRuntimeError("INTERNAL: device lost")) == "device"
        assert wgl.device_error_kind(ValueError("plain bug")) is None
        assert wgl.device_error_kind(wgl.RangeError("big")) is None

    def test_compile_failure_skips_batch_halving(self, monkeypatch):
        """A compile failure is deterministic for the shape: the
        batch-halving rung is skipped (each sub-batch would just
        re-fail compilation) and the ladder goes width-halve ->
        host floor."""
        from jepsen_tpu.checker import models
        from jepsen_tpu.tpu import encode, wgl

        m = models.register(0)
        encs = [encode.encode(m, self._hist()) for _ in range(4)]
        calls = {"n": 0}

        def boom(*a, **kw):
            calls["n"] += 1
            raise RuntimeError("error during compilation")

        monkeypatch.setattr(wgl, "_launch", boom)
        telemetry.reset()
        res = wgl.check_batch(encs)
        assert list(res) == [wgl.UNKNOWN] * 4
        # one failed attempt per width (32 -> 16 -> 8), never one per
        # halved sub-batch
        assert calls["n"] == 3
        c = telemetry.get().counters()
        assert "wgl.ladder.batch-halved" not in c
        assert c["wgl.ladder.width-halved"] >= 1

    def test_ladder_fork_keeps_own_provenance(self):
        """The scope's consecutive-dedup must not swallow a rung that
        belongs to a DIFFERENT result: chunk B's OOM right after chunk
        A's still lands in chunk B's own (forked) list."""
        from jepsen_tpu.tpu import wgl

        with wgl._ladder_scope() as steps:
            wgl._ladder_note("oom")          # chunk A's failure
            with wgl._ladder_fork() as sub:  # chunk B's own view
                wgl._ladder_note("oom")
                wgl._ladder_note("host-floor")
            assert sub == ["oom", "host-floor"]
            assert steps == ["oom", "host-floor"]  # merged, deduped

    def test_batch_halving_isolates_failure(self, monkeypatch):
        """A batch whose first launch OOMs splits and retries; the
        halves succeed on the real kernel."""
        from jepsen_tpu.checker import models
        from jepsen_tpu.tpu import encode, wgl

        m = models.register(0)
        encs = [encode.encode(m, self._hist()) for _ in range(4)]
        calls = {"n": 0}
        real = wgl._launch

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: oom")
            return real(*a, **kw)

        monkeypatch.setattr(wgl, "_launch", flaky)
        res = wgl.check_batch(encs)
        assert list(res) == [wgl.VALID] * 4
        assert calls["n"] >= 3  # failed once, then the two halves

    def test_streamed_degradation_stamped_per_chunk(self, monkeypatch):
        """Only the chunk the device actually failed on carries the
        rungs; verdicts produced by the healthy device stay clean."""
        from jepsen_tpu.checker import models
        from jepsen_tpu.tpu import wgl

        m = models.register(0)
        hists = [self._hist() for _ in range(4)]
        calls = {"n": 0}
        real = wgl._launch

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 2:  # the SECOND chunk's launch OOMs
                raise RuntimeError("RESOURCE_EXHAUSTED: oom")
            return real(*a, **kw)

        monkeypatch.setattr(wgl, "_launch", flaky)
        res = wgl.analysis_batch_streamed(m, hists, chunk=2)
        assert [r["valid?"] for r in res] == [True] * 4
        assert "degradation" not in res[0]
        assert "degradation" not in res[1]
        assert "degradation" in res[2]
        assert "degradation" in res[3]

    def test_elle_device_failure_falls_back_to_host(self, monkeypatch):
        from jepsen_tpu.tpu import elle, elle_device

        ops = []
        for i in range(3):
            ops.append(op(index=2 * i, time=2 * i, type="invoke",
                          process=i, f="txn",
                          value=[["append", "x", i]]))
            ops.append(op(index=2 * i + 1, time=2 * i + 1, type="ok",
                          process=i, f="txn",
                          value=[["append", "x", i]]))
        hist = History(ops)
        want = elle.check_list_append(hist, {"engine": "host"})

        def boom(h):
            raise RuntimeError("RESOURCE_EXHAUSTED: device oom")

        monkeypatch.setattr(elle_device, "check_list_append_device",
                            boom)
        telemetry.reset()
        got = elle.check_list_append(hist, {"engine": "device"})
        assert got["valid?"] == want["valid?"]
        assert got["degradation"] == ["oom", "host-fallback"]
        assert telemetry.get().counters()[
            "elle.ladder.host-fallback"] == 1


class TestChaosCertificates:
    """ISSUE-10 satellite: harness fault injection never yields a
    verdict whose certificate fails to validate — an honest
    `certificate: absent` is allowed (host floors, non-replayable
    models), a validating-but-wrong proof never is. The stamp itself
    runs inside core.analyze; these tests assert its outcome under
    seeded chaos."""

    def _cert_checker(self):
        from jepsen_tpu.checker import models

        return checker.compose({
            "linear": checker.linearizable(
                {"model": models.cas_register(0)}),
            "stats": checker.stats()})

    @staticmethod
    def assert_certificates_honest(results):
        from jepsen_tpu.tpu import certify

        seen = 0
        for path, res in certify.iter_certificates(results):
            seen += 1
            cert = res["certificate"]
            certify.validate_schema(cert)
            # the invariant: certified XOR honestly absent — never a
            # proof that failed validation
            if "absent" in cert:
                continue
            assert res.get("certified") is True, \
                (path, res.get("certificate-error"))
        assert seen >= 1, "no certificates to check — suite is moot"

    def test_chaos_run_certificates_validate(self, tmp_path):
        telemetry.reset()
        t = chaos_run(tmp_path, "chaos-certs", client_rates={
            "drop-connection": 0.15, "command-timeout": 0.1,
            "exception": 0.05}, checker_=self._cert_checker())
        assert_invariants(t, tmp_path)
        assert sum(t["client"].tally.values()) > 0  # faults really flew
        self.assert_certificates_honest(t["results"])

    def test_forced_device_failure_keeps_proofs_honest(
            self, tmp_path, monkeypatch):
        """The degradation ladder's host floor still produces a
        verdict whose certificate validates (extraction is host-side
        and kernel-independent) — or says absent; never a bad proof."""
        from jepsen_tpu.tpu import wgl

        def boom(*a, **kw):
            raise RuntimeError("RESOURCE_EXHAUSTED: chaos-forced oom")

        monkeypatch.setattr(wgl, "_launch", boom)
        telemetry.reset()
        t = chaos_run(tmp_path, "chaos-certs-floor",
                      checker_=self._cert_checker())
        assert_invariants(t, tmp_path)
        res = t["results"]
        assert res["linear"].get("degradation"), \
            "the ladder never walked — forcing failed"
        self.assert_certificates_honest(res)


class TestRecoverableFlag:
    def test_live_pid_suppresses_recoverable(self, tmp_path):
        """A quiet-but-running test (single checker computing for
        minutes without touching a file) must not be advertised as
        crashed; only a dead control process is recoverable."""
        import time as _time

        from jepsen_tpu import web

        td = tmp_path / "demo" / "t1"
        td.mkdir(parents=True)
        (td / "history.jlog").write_text("x")
        old = _time.time() - 3600
        os.utime(td / "history.jlog", (old, old))
        (td / "run.pid").write_text(str(os.getpid()))  # alive: us
        assert not web._looks_recoverable(td)
        (td / "run.pid").write_text("999999999")  # no such pid
        assert web._looks_recoverable(td)
        (td / "run.pid").unlink()  # old store: mtime heuristic
        assert web._looks_recoverable(td)

    def test_run_writes_pid_marker(self, tmp_path):
        t = chaos_run(tmp_path, "pid-marker", ops=8)
        d = store.path(t)
        assert int((d / "run.pid").read_text()) == os.getpid()


class TestValidateHistory:
    def test_clean_history_passes(self):
        hist = [op(index=0, type="invoke", process=0, f="r",
                   value=None),
                op(index=1, type="ok", process=0, f="r", value=1)]
        assert chaos.validate_history(hist) == []

    def test_detects_orphan_completion(self):
        hist = History([op(type="ok", process=0, f="r", value=1)])
        assert any("without invocation" in p
                   for p in chaos.validate_history(hist))

    def test_detects_f_mismatch(self):
        hist = History([op(type="invoke", process=0, f="r", value=None),
                        op(type="ok", process=0, f="w", value=1)])
        assert any("f=" in p for p in chaos.validate_history(hist))

    def test_detects_double_invoke(self):
        hist = History([op(type="invoke", process=0, f="r", value=None),
                        op(type="invoke", process=0, f="r", value=None)])
        assert any("already in flight" in p
                   for p in chaos.validate_history(hist))
