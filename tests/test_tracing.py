"""Per-op causal tracing: recorder semantics, schema validation for
optrace.jsonl and the Chrome-trace export, propagation through the
interpreter/client/control layers, and the anomaly-provenance loop
(op-indices -> explain excerpts -> pre-filtered trace views)."""

import json
import random
import threading

import pytest

from jepsen_tpu import checker as jchecker
from jepsen_tpu import client as jclient
from jepsen_tpu import control, core, interpreter, testing, tracing, util
from jepsen_tpu import generator as gen
from jepsen_tpu import store as jstore
from jepsen_tpu.control.core import (Action, Result, TransportError)
from jepsen_tpu.history import History, Op, op
from jepsen_tpu.reports import explain, timeline
from jepsen_tpu.reports import trace as rtrace
from jepsen_tpu.tpu import elle
from jepsen_tpu.workloads import register as register_wl


def _op(i, f="write", p=0):
    return Op(index=i, time=i, type="invoke", process=p, f=f, value=1)


# ---------------------------------------------------------------------------
# Recorder semantics
# ---------------------------------------------------------------------------

class TestTracer:
    def test_disabled_records_nothing(self):
        tr = tracing.Tracer(enabled=False)
        with tr.op_span(_op(0)) as rec:
            assert rec is None
            with tr.span("client", "client.write") as c:
                assert c is None
            tr.event("reconnect")
        assert tr.records() == []

    def test_op_span_mints_trace_context(self):
        tr = tracing.Tracer(enabled=True)
        with tr.op_span(_op(7, f="cas")) as rec:
            assert rec["trace"] == 7 and rec["op"] == 7
            assert rec["parent"] is None and rec["kind"] == "op"
            with tr.span("client", "client.cas") as c:
                assert c["trace"] == 7 and c["parent"] == rec["span"]
                with tr.span("remote", "remote.sh", cmd="sh x") as r:
                    assert r["parent"] == c["span"]
        kinds = [r["kind"] for r in tr.records()]
        assert kinds == ["remote", "client", "op"]  # close order
        tracing.validate_records(tr.records())

    def test_span_without_context_is_noop(self):
        tr = tracing.Tracer(enabled=True)
        with tr.span("remote", "remote.sh") as rec:
            assert rec is None
        assert tr.records() == []

    def test_event_with_and_without_context(self):
        tr = tracing.Tracer(enabled=True)
        tr.event("net.heal")  # setup-time event: context-free
        with tr.op_span(_op(3)):
            tr.event("reconnect", error="boom")
        recs = tr.records()
        assert recs[0]["trace"] is None and recs[0]["parent"] is None
        assert recs[1]["trace"] == 3 and recs[1]["parent"] is not None
        tracing.validate_records(recs)

    def test_annotate_hits_innermost_span(self):
        tr = tracing.Tracer(enabled=True)
        with tr.op_span(_op(0)):
            with tr.span("remote", "remote.sh"):
                tr.annotate(retries=2)
        remote = [r for r in tr.records() if r["kind"] == "remote"][0]
        assert remote["attrs"]["retries"] == 2

    def test_crashed_invoke_marks_status(self):
        tr = tracing.Tracer(enabled=True)
        with pytest.raises(RuntimeError):
            with tr.op_span(_op(0)):
                raise RuntimeError("client died")
        rec = tr.records()[0]
        assert rec["status"] == "crashed" and "t1" in rec

    def test_attach_carries_context_across_threads(self):
        tr = tracing.Tracer(enabled=True)
        with tr.op_span(_op(5)) as rec:
            def body():
                with tr.attach(rec):
                    with tr.span("remote", "remote.echo"):
                        pass
            t = threading.Thread(target=body)
            t.start()
            t.join()
        remote = [r for r in tr.records() if r["kind"] == "remote"][0]
        assert remote["trace"] == 5 and remote["parent"] == rec["span"]
        tracing.validate_records(tr.records())

    def test_straggler_span_from_before_reset_is_dropped(self):
        """A worker thread surviving an abnormal interpreter exit
        closes its span AFTER the next run reset the tracer: the
        record must not leak into the new run (its span id would
        collide with the restarted counter)."""
        tr = tracing.Tracer(enabled=True)
        cm = tr.op_span(_op(0))
        cm.__enter__()          # run A opens a span...
        tr.reset(enabled=True)  # ...run B resets the tracer
        with tr.op_span(_op(1)):
            pass
        cm.__exit__(None, None, None)  # run A's straggler closes
        recs = tr.records()
        assert [r["trace"] for r in recs] == [1]
        tracing.validate_records(recs)

    def test_streaming_and_readback(self, tmp_path):
        tr = tracing.Tracer(enabled=True)
        tr.open(tmp_path / tracing.TRACE_FILE)
        with tr.op_span(_op(0)):
            tr.event("net.drop", src="n1", dest="n2")
        tr.close()
        recs = list(tracing.read_records(tmp_path / tracing.TRACE_FILE))
        assert len(recs) == 2
        assert tracing.validate_records(recs) == 2

    def test_torn_tail_dropped_on_read(self, tmp_path):
        tr = tracing.Tracer(enabled=True)
        with tr.op_span(_op(0)):
            pass
        p = tr.save(tmp_path)
        with open(p, "a") as f:
            f.write('{"torn": ')
        recs = list(tracing.read_records(p))
        assert len(recs) == 1


class TestValidation:
    def _good(self):
        return [{"trace": 0, "span": 1, "parent": None, "kind": "op",
                 "name": "write", "op": 0, "process": "0",
                 "t0": 10, "t1": 30},
                {"trace": 0, "span": 2, "parent": 1, "kind": "client",
                 "name": "client.write", "op": 0, "process": "0",
                 "t0": 12, "t1": 25}]

    def test_good_records_pass(self):
        assert tracing.validate_records(self._good()) == 2

    def test_missing_key_rejected(self):
        recs = self._good()
        del recs[0]["t1"]
        with pytest.raises(ValueError, match="missing 't1'"):
            tracing.validate_records(recs)

    def test_non_monotonic_ts_rejected(self):
        recs = self._good()
        recs[1]["t1"] = 5
        with pytest.raises(ValueError, match="non-monotonic"):
            tracing.validate_records(recs)

    def test_dangling_parent_rejected(self):
        recs = self._good()
        recs[1]["parent"] = 99
        with pytest.raises(ValueError, match="parent 99"):
            tracing.validate_records(recs)

    def test_duplicate_span_id_rejected(self):
        recs = self._good()
        recs[1]["span"] = 1
        with pytest.raises(ValueError, match="duplicate"):
            tracing.validate_records(recs)

    def test_cross_trace_parent_rejected(self):
        recs = self._good()
        recs[1]["trace"] = 3
        with pytest.raises(ValueError, match="another trace"):
            tracing.validate_records(recs)

    def test_unknown_kind_rejected(self):
        recs = self._good()
        recs[0]["kind"] = "mystery"
        with pytest.raises(ValueError, match="unknown kind"):
            tracing.validate_records(recs)


# ---------------------------------------------------------------------------
# Propagation: control/retry/reconnect/net layers
# ---------------------------------------------------------------------------

class TestControlPropagation:
    def test_exec_records_remote_span(self):
        tr = tracing.get()
        tr.reset(enabled=True)
        try:
            test = {"ssh": {"dummy": True}}
            with tr.op_span(_op(0)):
                with control.with_session(test, "n1"):
                    control.exec_("echo", "hello")
            remote = [r for r in tr.records()
                      if r["kind"] == "remote"]
            assert len(remote) == 1
            rec = remote[0]
            assert rec["name"] == "remote.echo"
            assert rec["attrs"]["cmd"] == "echo hello"
            assert rec["attrs"]["node"] == "n1"
            assert rec["attrs"]["exit"] == 0
            tracing.validate_records(tr.records())
        finally:
            tr.reset(enabled=False)

    def test_on_nodes_carries_context_to_pool_threads(self):
        tr = tracing.get()
        tr.reset(enabled=True)
        try:
            test = {"ssh": {"dummy": True}, "nodes": ["n1", "n2"]}
            with tr.op_span(_op(4, f="start", p="nemesis")):
                control.on_nodes(
                    test, lambda t, n: control.exec_("date"))
            remote = [r for r in tr.records() if r["kind"] == "remote"]
            assert len(remote) == 2
            assert all(r["trace"] == 4 for r in remote)
            assert {r["attrs"]["node"] for r in remote} == {"n1", "n2"}
        finally:
            tr.reset(enabled=False)

    def test_retry_count_lands_on_span(self):
        from jepsen_tpu.control.retry import RetryingRemote

        calls = [0]

        class FlakyRemote(control.Remote):
            def connect(self, conn_spec):
                class S(control.Session):
                    def execute(self, action):
                        calls[0] += 1
                        if calls[0] < 3:
                            raise TransportError(
                                "flaky", cmd=action.cmd, node="n1")
                        return Result(exit=0, out="", err="",
                                      cmd=action.cmd)

                    def disconnect(self):
                        pass

                return S()

        tr = tracing.get()
        tr.reset(enabled=True)
        try:
            sess = RetryingRemote(FlakyRemote()).connect({"host": "n1"})
            with tr.op_span(_op(0)):
                res = control.core.traced_execute(
                    sess, Action(cmd="echo hi"), node="n1")
            assert res.exit == 0 and calls[0] == 3
            recs = tr.records()
            remote = [r for r in recs if r["kind"] == "remote"][0]
            assert remote["attrs"]["retries"] == 2
            retries = [r for r in recs if r["kind"] == "event"
                       and r["name"] == "remote-retry"]
            assert len(retries) == 2
            assert all(r["trace"] == 0 for r in retries)
            tracing.validate_records(recs)
        finally:
            tr.reset(enabled=False)

    def test_reconnect_records_event(self):
        from jepsen_tpu import reconnect

        tr = tracing.get()
        tr.reset(enabled=True)
        try:
            w = reconnect.Wrapper(open=lambda: object(),
                                  close=lambda c: None, name="db")
            with tr.op_span(_op(2)):
                with pytest.raises(RuntimeError):
                    with w.with_conn():
                        raise RuntimeError("conn died")
            evs = [r for r in tr.records() if r["kind"] == "event"]
            assert len(evs) == 1 and evs[0]["name"] == "reconnect"
            assert evs[0]["trace"] == 2
        finally:
            tr.reset(enabled=False)

    def test_partition_records_net_events(self):
        from jepsen_tpu import net

        tr = tracing.get()
        tr.reset(enabled=True)
        try:
            test = {"ssh": {"dummy": True}, "nodes": ["n1", "n2"],
                    "sessions": {}}
            with tr.op_span(_op(9, f="start", p="nemesis")):
                net.iptables.heal(test)
            evs = [r for r in tr.records() if r["kind"] == "event"]
            assert any(r["name"] == "net.heal" and r["trace"] == 9
                       for r in evs)
        finally:
            tr.reset(enabled=False)


# ---------------------------------------------------------------------------
# Pipeline: interpreter + core.run
# ---------------------------------------------------------------------------

def _register_test(tmp_path, name, n=40, **kw):
    state = testing.AtomState()
    rng = random.Random(7)
    t = testing.noop_test()
    t.update(
        name=name, store_base=str(tmp_path), nodes=["n1", "n2"],
        concurrency=4, monitor_interval_s=0.05,
        client=testing.AtomClient(state),
        checker=jchecker.stats(),
        generator=gen.clients(gen.limit(
            n, lambda: register_wl.cas_op_mix(rng, n_values=3))))
    t.update(kw)
    return t


class TestPipeline:
    def test_traced_run_streams_valid_optrace(self, tmp_path):
        test = _register_test(tmp_path, "trace-e2e", **{"trace?": True})
        test = core.run(test)
        assert test["results"]["valid?"] is True
        d = jstore.path(test)
        recs = jstore.load_optrace(d)
        assert tracing.validate_records(recs) == len(recs)
        ops = [r for r in recs if r["kind"] == "op"]
        clients = [r for r in recs if r["kind"] == "client"]
        # every client invocation got an op span + a client child span
        invokes = [o for o in test["history"] if o.type == "invoke"]
        assert len(ops) == len(invokes)
        assert len(clients) >= len(invokes)
        assert {r["status"] for r in ops} <= {"ok", "fail", "info"}
        # trace ids join the history: each op record names a real
        # invocation with the same f
        by_index = {o.index: o for o in test["history"]}
        for r in ops:
            assert by_index[r["op"]].f == r["name"]

    def test_untraced_run_writes_no_optrace(self, tmp_path):
        test = core.run(_register_test(tmp_path, "untraced"))
        d = jstore.path(test)
        assert not (d / tracing.TRACE_FILE).exists()
        assert jstore.load_optrace(d) == []

    def test_trace_clients_opt_out(self, tmp_path):
        test = _register_test(tmp_path, "no-client-spans",
                              **{"trace?": True,
                                 "trace_clients?": False})
        test = core.run(test)
        recs = jstore.load_optrace(jstore.path(test))
        kinds = {r["kind"] for r in recs}
        assert "op" in kinds and "client" not in kinds

    def test_exported_chrome_trace_validates_and_nests(self, tmp_path):
        test = core.run(_register_test(tmp_path, "trace-export",
                                       **{"trace?": True}))
        d = jstore.path(test)
        out = rtrace.write_trace(d)
        with open(out) as f:
            doc = json.load(f)
        rtrace.validate_chrome_trace(doc)
        evs = doc["traceEvents"]
        cats = {e.get("cat") for e in evs}
        assert {"op", "invoke", "client"} <= cats
        # client child slices sit on the same track as their op slice
        # and inside its time range
        op_slices = {}
        for e in evs:
            if e.get("cat") == "op":
                op_slices.setdefault(e["tid"], []).append(e)
        checked = 0
        for e in evs:
            if e.get("cat") != "client":
                continue
            hosts = [o for o in op_slices.get(e["tid"], [])
                     if o["ts"] <= e["ts"]
                     and e["ts"] + e["dur"] <= o["ts"] + o["dur"] + 1e-3]
            assert hosts, f"client slice {e} has no enclosing op slice"
            checked += 1
        assert checked > 0

    def test_ops_filter_restricts_client_tracks(self, tmp_path):
        test = core.run(_register_test(tmp_path, "trace-filter",
                                       **{"trace?": True}))
        d = jstore.path(test)
        full = json.load(open(rtrace.write_trace(d)))
        some_invoke = next(o for o in test["history"]
                           if o.type == "invoke")
        filt = json.load(open(rtrace.write_trace(
            d, out_path=d / "trace-filtered.json",
            ops=[some_invoke.index])))
        rtrace.validate_chrome_trace(filt)

        def op_count(doc):
            return sum(1 for e in doc["traceEvents"]
                       if e.get("cat") == "op")

        assert op_count(filt) == 1 < op_count(full)

    def test_timeline_hover_carries_trace_detail(self, tmp_path):
        test = _register_test(tmp_path, "trace-timeline",
                              **{"trace?": True})
        test["checker"] = jchecker.compose({
            "stats": jchecker.stats(),
            "timeline": jchecker.timeline()})
        test = core.run(test)
        html = (jstore.path(test) / "timeline.html").read_text()
        assert "— trace —" in html
        assert "client client." in html


# ---------------------------------------------------------------------------
# Anomaly provenance
# ---------------------------------------------------------------------------

def _g1a_history():
    """A failed append observed by a later read: G1a, with the ops at
    known indices."""
    return History([
        Op(0, 10, "invoke", 0, "txn", [["append", "x", 1]]),
        Op(1, 20, "fail", 0, "txn", [["append", "x", 1]]),
        Op(2, 30, "invoke", 1, "txn", [["r", "x", None]]),
        Op(3, 40, "ok", 1, "txn", [["r", "x", [1]]]),
    ], assign_indices=False)


class TestProvenance:
    def test_elle_attaches_invocation_indices(self):
        res = elle.check_list_append(_g1a_history(), {"engine": "host"})
        assert res["valid?"] is False
        rec = res["anomalies"]["G1a"][0]
        # writer (completion index 1) resolves to invocation 0; the
        # reading txn (completion 3) to invocation 2
        assert rec["op-indices"] == [0, 2]

    def test_wgl_witness_attaches_indices(self):
        from jepsen_tpu.checker import models
        from jepsen_tpu.tpu import wgl

        hist = History([
            op(type="invoke", process=0, f="write", value=1),
            op(type="ok", process=0, f="write", value=1),
            op(type="invoke", process=1, f="read", value=None),
            op(type="ok", process=1, f="read", value=2),
        ])
        out = wgl.analysis(models.cas_register(), hist,
                           algorithm="wgl")
        assert out["valid?"] is False
        assert out["op-indices"], out
        assert all(isinstance(i, int) for i in out["op-indices"])

    def test_set_full_lost_elements_carry_indices(self):
        hist = History([
            op(type="invoke", process=0, f="add", value=1),
            op(type="ok", process=0, f="add", value=1),
            op(type="invoke", process=1, f="read", value=None),
            op(type="ok", process=1, f="read", value=[1]),
            op(type="invoke", process=1, f="read", value=None),
            op(type="ok", process=1, f="read", value=[]),
        ])
        res = jchecker.set_full().check({}, hist, {})
        assert res["valid?"] is False and res["lost"] == [1]
        assert res["lost-op-indices"][1] == [0, 4]

    def _traced_records_for(self, indices):
        tr = tracing.Tracer(enabled=True)
        for i in indices:
            o = Op(index=i, time=i, type="invoke", process=0, f="txn",
                   value=None)
            with tr.op_span(o):
                with tr.span("remote", "remote.sh",
                             cmd="sh -c probe", node="n1") as r:
                    r["attrs"]["exit"] = 0
        return tr.records()

    def test_explain_excerpts_resolve_anomaly_ops(self, tmp_path):
        """ISSUE-4 acceptance: a failed elle check yields anomalies
        whose op references resolve to trace excerpts in the explain
        output."""
        res = elle.check_list_append(_g1a_history(), {"engine": "host"})
        recs = self._traced_records_for([0, 2])
        paths = explain.write_trace_excerpts(tmp_path, res,
                                             optrace=recs)
        assert len(paths) == 1 and "G1a-trace" in paths[0]
        body = open(paths[0]).read()
        assert "op 0:" in body and "op 2:" in body
        assert "remote remote.sh" in body and "exit=0" in body

    def test_linear_counterexample_excerpt(self, tmp_path):
        from jepsen_tpu.checker import models

        hist = History([
            op(type="invoke", process=0, f="write", value=1),
            op(type="ok", process=0, f="write", value=1),
            op(type="invoke", process=1, f="read", value=None),
            op(type="ok", process=1, f="read", value=2),
        ])
        test = {"store_dir": str(tmp_path)}
        # pre-seed the optrace artifact the checker resolves against
        tr = tracing.Tracer(enabled=True)
        for o in hist:
            if o.type == "invoke":
                with tr.op_span(o):
                    pass
        tr.save(tmp_path)
        out = jchecker.linearizable(
            {"model": models.cas_register(),
             "algorithm": "wgl"}).check(test, hist, {})
        assert out["valid?"] is False
        assert out.get("trace-excerpt")
        body = open(out["trace-excerpt"]).read()
        assert "participating ops" in body and "op read" in body

    def test_seeded_failure_resolves_end_to_end(self, tmp_path):
        """ISSUE-4 acceptance, full loop: a traced run with a seeded
        linearizability violation yields a counterexample whose op
        references resolve to a trace excerpt in the store dir AND to
        client child spans in the (pre-filtered) Perfetto export."""
        from jepsen_tpu.checker import models

        state = testing.AtomState()

        class CorruptingClient(jclient.Client):
            """Flips one mid-run read to an impossible value."""

            def __init__(self):
                self.inner = testing.AtomClient(state)
                self.reads = [0]

            def open(self, test, node):
                return self

            def invoke(self, test, op_):
                out = self.inner.invoke(test, op_)
                if op_.f == "read" and out.type == "ok":
                    self.reads[0] += 1
                    if self.reads[0] == 5:
                        return out.copy(value=999)
                return out

        test = _register_test(tmp_path, "provenance-e2e", n=30,
                              **{"trace?": True})
        test["client"] = CorruptingClient()
        test["checker"] = jchecker.compose({
            "stats": jchecker.stats(),
            "linear": jchecker.linearizable(
                {"model": models.cas_register(),
                 "algorithm": "wgl"})})
        test = core.run(test)
        res = test["results"]["linear"]
        assert res["valid?"] is False
        idxs = res["op-indices"]
        assert idxs
        d = jstore.path(test)
        # 1. trace excerpt written and naming the participating ops
        body = open(res["trace-excerpt"]).read()
        assert f"op {idxs[0]}:" in body and "client client." in body
        # 2. pre-filtered Perfetto export carries those ops' child
        # client spans
        doc = json.load(open(rtrace.write_trace(
            d, out_path=d / "trace-anomaly.json", ops=idxs)))
        rtrace.validate_chrome_trace(doc)
        traces = {e["args"].get("trace") for e in doc["traceEvents"]
                  if e.get("cat") == "client"}
        assert traces and traces <= set(idxs)
        # 3. the run page links the anomaly to both views
        from jepsen_tpu import web

        rel = f"provenance-e2e/{d.name}"
        html = web.dir_html(rel + "/", d)
        assert f"#op-{idxs[0]}" in html and "?ops=" in html

    def test_web_anomaly_index(self):
        from jepsen_tpu import web

        res = {"valid?": False,
               "workload": {
                   "valid?": False,
                   "anomalies": {"G1a": [{"op-indices": [0, 2]}],
                                 "G0": [{}]}},
               "linear": {"valid?": False, "op-indices": [5, 7]},
               "stats": {"valid?": True}}
        idx = dict(web.anomaly_index(res))
        assert idx["workload/G1a"] == [0, 2]
        assert idx["linear/counterexample"] == [5, 7]
        assert "workload/G0" not in idx  # no provenance, no link

    def test_run_page_links_anomalies(self, tmp_path):
        from jepsen_tpu import web

        d = tmp_path / "t" / "20260101T000000.0000"
        d.mkdir(parents=True)
        (d / "test.json").write_text("{}")
        (d / "results.json").write_text(json.dumps(
            {"valid?": False,
             "workload": {"valid?": False,
                          "anomalies": {
                              "G1a": [{"op-indices": [0, 2]}]}}}))
        html = web.dir_html("t/20260101T000000.0000/", d)
        assert "?ops=0,2" in html
        assert "timeline.html#op-0" in html