"""SPMD sharding tests (ISSUE-15): the checker kernels as true
multi-device programs.

Pins the whole acceptance surface of the SPMD rebuild:

  - sharded-vs-unsharded verdict AND certificate equivalence on seeded
    valid/invalid histories at mesh caps 0/1/2/4/8 (the conftest gives
    every test process a virtual 8-device CPU mesh; the caps ride the
    JEPSEN_TPU_SPMD / JEPSEN_TPU_SPMD_DEVICES knobs the launch sites
    re-read per call);
  - segment-level early exit: identical results with the waves on or
    off, and an early witness costs a fraction of the full search;
  - degradation-ladder behavior when the sharded program OOMs (the
    ladder steps down to single-device launches, verdicts stay right);
  - fleet `check_slices` cross-tenant parity;
  - the sharded SCC coloring kernel against the host union-find;
  - a fast fake-8-device smoke with per-device work attribution — the
    CI tripwire that fails sharding regressions before hardware does.
"""

from __future__ import annotations

import numpy as np
import pytest

from jepsen_tpu import telemetry
from jepsen_tpu.checker import models
from jepsen_tpu.history import History
from jepsen_tpu.tpu import certify, ensemble, profiler, scc, spmd, \
    synth, wgl
from jepsen_tpu.tpu.encode import balanced_groups, encode


@pytest.fixture(scope="module")
def devices8():
    import jax

    if len(jax.devices()) < 8:  # real-device run
        pytest.skip(f"needs 8 devices, have {len(jax.devices())}")
    return 8


def corrupt(hist, frac=1.0):
    """Flip one ok-read's value so the history becomes
    non-linearizable; frac places the flipped read at roughly that
    fraction of the history (early witnesses for the early-exit
    tests, late ones for everything else)."""
    ops = list(hist)
    idx = [i for i, o in enumerate(ops)
           if o.type == "ok" and o.f == "read" and o.value is not None]
    assert idx, "no ok read to corrupt"
    i = idx[min(int(len(idx) * frac), len(idx) - 1)]
    ops[i] = ops[i].copy(value=ops[i].value + 1000)
    return History(ops, assign_indices=False)


def _cap(monkeypatch, n: int) -> None:
    """Pin the sharded launch sites to an n-device mesh (0 = SPMD
    off: the plain single-device jit path, the differential
    reference)."""
    if n == 0:
        monkeypatch.setenv("JEPSEN_TPU_SPMD", "0")
    else:
        monkeypatch.delenv("JEPSEN_TPU_SPMD", raising=False)
        monkeypatch.setenv("JEPSEN_TPU_SPMD_DEVICES", str(n))


# ---------------------------------------------------------------------------
# plumbing: knobs, rule table, layout packing
# ---------------------------------------------------------------------------

class TestPlumbing:
    def test_spmd_knobs(self, monkeypatch, devices8):
        monkeypatch.setenv("JEPSEN_TPU_SPMD", "0")
        assert spmd.spmd_devices() == 0
        monkeypatch.delenv("JEPSEN_TPU_SPMD", raising=False)
        assert spmd.spmd_devices() >= 8
        monkeypatch.setenv("JEPSEN_TPU_SPMD_DEVICES", "4")
        assert spmd.spmd_devices() == 4
        monkeypatch.setenv("JEPSEN_TPU_SPMD_DEVICES", "junk")
        assert spmd.spmd_devices() >= 8  # bad cap ignored

    def test_mesh_memoized(self, devices8):
        assert spmd.mesh_for(2) is spmd.mesh_for(2)
        assert spmd.mesh_for(2).devices.size == 2
        assert spmd.mesh_for(2).axis_names == (spmd.AXIS,)

    def test_partition_rules_cover_kernel_args(self):
        from jax.sharding import PartitionSpec as P

        specs = spmd.match_partition_rules(spmd.WGL_RULES,
                                           ensemble.SHARD_ARGS)
        assert specs[ensemble.SHARD_ARGS.index("trans")] == \
            P(spmd.AXIS)
        assert specs[ensemble.SHARD_ARGS.index("inv_perm")] == P()
        specs = spmd.match_partition_rules(spmd.SCC_RULES,
                                           scc.SCC_ARGS)
        assert specs[scc.SCC_ARGS.index("active")] == P()
        assert specs[scc.SCC_ARGS.index("src")] == P(spmd.AXIS)

    def test_unmatched_arg_raises(self):
        with pytest.raises(ValueError, match="no partition rule"):
            spmd.match_partition_rules(spmd.WGL_RULES,
                                       ("trans", "mystery_arg"))

    def test_describe_partition_is_the_lint_view(self):
        d = spmd.describe_partition(spmd.WGL_RULES,
                                    ensemble.SHARD_ARGS)
        assert d["axis"] == spmd.AXIS
        # the R4 acceptance: every big tensor sharded, only the tiny
        # result permutation replicated
        assert set(d["sharded"]) == {"inv_t", "ret_t", "trans",
                                     "mseg", "sufmin", "row_seg",
                                     "st0"}
        assert d["replicated"] == ["inv_perm"]

    def test_compile_cache_knob(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_COMPILE_CACHE", "0")
        assert spmd.compile_cache_dir() is None
        monkeypatch.setenv("JEPSEN_TPU_COMPILE_CACHE", "/tmp/x")
        assert spmd.compile_cache_dir() == "/tmp/x"
        monkeypatch.delenv("JEPSEN_TPU_COMPILE_CACHE", raising=False)
        d = spmd.compile_cache_dir()
        assert d and d.endswith(".xla-cache")

    def test_balanced_groups(self):
        groups = balanced_groups([10, 1, 9, 2, 8, 3], 2)
        assert sorted(i for g in groups for i in g) == list(range(6))
        assert all(g == sorted(g) for g in groups)
        loads = [sum([10, 1, 9, 2, 8, 3][i] for i in g)
                 for g in groups]
        assert max(loads) <= min(loads) + 10  # LPT bound
        # fewer items than groups: every group still exists
        groups = balanced_groups([5], 4)
        assert len(groups) == 4
        assert sum(len(g) for g in groups) == 1
        assert balanced_groups([], 3) == [[], [], []]

    def test_shard_layout_restores_caller_order(self, devices8):
        m = models.cas_register()
        encs = [encode(m, synth.register_history(
            16 + 8 * i, n_procs=3, seed=i)) for i in range(5)]
        pb = wgl.PackedBatch(encs)
        rows = [(i, e.init_state) for i, e in enumerate(encs)]
        n_dev = 4
        lay = ensemble.shard_layout(pb, rows, n_dev)
        assert lay.n_dev == n_dev and lay.n_rows == len(rows)
        assert len(lay.device_entries) == n_dev
        k_blk = lay.mseg.shape[0] // n_dev      # K_loc + 1
        b_loc = len(lay.row_seg) // n_dev
        seen = set()
        for i, (k, _s) in enumerate(rows):
            pos = int(lay.inv_perm[i])
            assert pos not in seen  # a permutation, not a collapse
            seen.add(pos)
            d, slot = divmod(pos, b_loc)
            j = int(lay.row_seg[pos])
            assert j < k_blk - 1  # real segment, not the sentinel
            # the local block row really is caller segment k
            assert int(lay.mseg[d * k_blk + j]) == int(pb.m[k])

    def test_shard_layout_ships_only_referenced_segments(self,
                                                         devices8):
        m = models.cas_register()
        encs = [encode(m, synth.register_history(
            20 + 4 * i, n_procs=3, seed=40 + i)) for i in range(4)]
        pb = wgl.PackedBatch(encs)
        rows = [(0, encs[0].init_state), (2, encs[2].init_state)]
        lay = ensemble.shard_layout(pb, rows, 2)
        # only segments 0 and 2 ship; everything else in the blocked
        # tensor is the zero-length sentinel row
        assert int(lay.mseg.sum()) == int(pb.m[0]) + int(pb.m[2])


# ---------------------------------------------------------------------------
# sharded vs unsharded: verdicts and certificates
# ---------------------------------------------------------------------------

CAPS = (0, 1, 2, 4, 8)


class TestShardedParity:
    def test_check_batch_across_mesh_caps(self, monkeypatch,
                                          devices8):
        m = models.cas_register()
        hists = [synth.register_history(26, n_procs=3, seed=700 + i)
                 for i in range(12)]
        hists[3] = corrupt(hists[3])
        hists[9] = corrupt(hists[9])
        encs = [encode(m, h) for h in hists]
        by_cap = {}
        for n in CAPS:
            _cap(monkeypatch, n)
            by_cap[n] = list(map(int, wgl.check_batch(encs, W=16,
                                                      F=16)))
        for n in CAPS[1:]:
            assert by_cap[n] == by_cap[0], f"mesh cap {n} diverged"

    def test_check_segmented_and_certificates_across_caps(
            self, monkeypatch, devices8):
        m = models.cas_register()
        valid = synth.register_history(360, n_procs=4, seed=31)
        invalid = corrupt(synth.register_history(360, n_procs=4,
                                                 seed=32), frac=0.6)
        for hist in (valid, invalid):
            enc = encode(m, hist)
            results = {}
            for n in CAPS:
                _cap(monkeypatch, n)
                res = wgl.check_segmented(enc, target_len=48,
                                          witness=True)
                assert res is not None
                certify.attach_wgl(m, hist, enc, res)
                results[n] = res
            for n in CAPS[1:]:
                # the whole result — verdict, masks-derived chain,
                # witness AND certificate — bit-identical per cap
                assert results[n] == results[0], \
                    f"mesh cap {n} diverged on {hist is valid}"
            cert = results[0]["certificate"]
            assert "absent" not in cert, cert
            certify.validate(hist, cert)  # proof actually checks

    def test_analysis_certificates_across_caps(self, monkeypatch,
                                               devices8):
        m = models.cas_register()
        hists = [synth.register_history(30, n_procs=3, seed=55),
                 corrupt(synth.register_history(30, n_procs=3,
                                                seed=56))]
        for hist in hists:
            by_cap = {}
            for n in (0, 2, 8):
                _cap(monkeypatch, n)
                res = wgl.analysis(m, hist, certify=True)
                by_cap[n] = (res["valid?"], res["certificate"])
            assert by_cap[2] == by_cap[0]
            assert by_cap[8] == by_cap[0]
            certify.validate(hist, by_cap[0][1])

    def test_check_slices_cross_tenant_parity(self, monkeypatch,
                                              devices8):
        """The fleet scheduler's cross-run batching entry point: many
        tenants' (slice, start-state) rows in ONE launch must answer
        exactly what each tenant's solo single-device launch would."""
        m = models.cas_register()
        tenants = [encode(m, synth.register_history(
            40 + 10 * i, n_procs=3, seed=900 + i)) for i in range(4)]
        slices = [(enc, s) for enc in tenants
                  for s in range(min(enc.n_states, 3))]
        _cap(monkeypatch, 0)
        ref_out, ref_unk = wgl.check_slices(slices, W=16, F=16)
        for n in (2, 8):
            _cap(monkeypatch, n)
            out, unk = wgl.check_slices(slices, W=16, F=16)
            assert out.tolist() == ref_out.tolist()
            assert unk.tolist() == ref_unk.tolist()


# ---------------------------------------------------------------------------
# segment-level early exit
# ---------------------------------------------------------------------------

class TestEarlyExit:
    def test_wave_bounds(self):
        assert wgl._wave_bounds(5, True) == [(0, 5)]  # small K
        assert wgl._wave_bounds(20, False) == [(0, 20)]
        waves = wgl._wave_bounds(100, True)
        assert waves[0] == (0, 4)
        assert waves[-1][1] == 100
        for (alo, ahi), (blo, bhi) in zip(waves, waves[1:]):
            assert ahi == blo  # contiguous cover
            assert (bhi - blo) >= (ahi - alo)  # geometric growth

    def _rows_launched(self, monkeypatch):
        counted = []
        real = wgl._launch

        def counting(pb, rows, W, F, reach):
            counted.append(len(list(rows)))
            return real(pb, rows, W, F, reach)

        monkeypatch.setattr(wgl, "_launch", counting)
        return counted

    def test_early_witness_costs_a_fraction(self, monkeypatch,
                                            devices8):
        m = models.cas_register()
        hist = corrupt(synth.register_history(800, n_procs=4,
                                              seed=61), frac=0.1)
        enc = encode(m, hist)
        telemetry.reset()
        counted = self._rows_launched(monkeypatch)
        full = wgl.check_segmented(enc, target_len=24, witness=True,
                                   early_exit=False)
        rows_full = sum(counted)
        counted.clear()
        early = wgl.check_segmented(enc, target_len=24, witness=True,
                                    early_exit=True)
        rows_early = sum(counted)
        assert early == full  # verdict, witness, chain — identical
        assert full["valid?"] is False
        # an anomaly at ~10% of the history must cost a fraction of
        # the full search (the waves after the witness never launch)
        assert rows_early < rows_full * 0.7, (rows_early, rows_full)
        c = telemetry.get().counters()
        assert c.get("wgl.segmented.early-exit", 0) >= 1

    def test_valid_history_waves_match_single_launch(self,
                                                     monkeypatch,
                                                     devices8):
        m = models.cas_register()
        enc = encode(m, synth.register_history(500, n_procs=4,
                                               seed=62))
        full = wgl.check_segmented(enc, target_len=24,
                                   early_exit=False)
        early = wgl.check_segmented(enc, target_len=24,
                                    early_exit=True)
        assert early == full
        assert full["valid?"] is True

    def test_env_knob_disables(self, monkeypatch, devices8):
        monkeypatch.setenv("JEPSEN_TPU_EARLY_EXIT", "0")
        m = models.cas_register()
        enc = encode(m, synth.register_history(300, n_procs=3,
                                               seed=63))
        counted = self._rows_launched(monkeypatch)
        res = wgl.check_segmented(enc, target_len=24)
        assert res["valid?"] is True
        # one screen launch + one main launch, no waves
        assert len(counted) <= 2


# ---------------------------------------------------------------------------
# degradation ladder under shard failure
# ---------------------------------------------------------------------------

class TestShardOOMLadder:
    def test_sharded_oom_steps_down_to_single_device(
            self, monkeypatch, devices8):
        """The SPMD program OOMing must not cost correctness: the
        batch ladder halves down to single-row launches, which fall
        under spmd.MIN_ROWS and take the plain single-device path —
        slower, never wrong, and the rungs are counted."""
        m = models.cas_register()
        hists = [synth.register_history(24, n_procs=3, seed=80 + i)
                 for i in range(4)]
        hists[1] = corrupt(hists[1])
        encs = [encode(m, h) for h in hists]
        ref = list(map(int, wgl.check_batch(encs, W=16, F=16)))

        def boom(*a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: fake shard OOM")

        telemetry.reset()
        monkeypatch.setattr(ensemble, "sharded_launch", boom)
        got = list(map(int, wgl.check_batch(encs, W=16, F=16)))
        assert got == ref
        c = telemetry.get().counters()
        assert c.get("wgl.ladder.batch-halved", 0) >= 1

    def test_segmented_survives_shard_failure(self, monkeypatch,
                                              devices8):
        """A dead SPMD program under the segmented check: the wave
        resolver walks its host rungs (screen + floor) and composes
        the SAME masks the device would have produced."""
        m = models.cas_register()
        enc = encode(m, synth.register_history(300, n_procs=3,
                                               seed=85))
        ref = wgl.check_segmented(enc, target_len=32)

        def boom(*a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: fake shard OOM")

        telemetry.reset()
        monkeypatch.setattr(ensemble, "sharded_launch", boom)
        res = wgl.check_segmented(enc, target_len=32)
        assert res == ref
        c = telemetry.get().counters()
        assert any(k.startswith("wgl.ladder.segment-host")
                   for k in c), c


# ---------------------------------------------------------------------------
# sharded SCC coloring kernel
# ---------------------------------------------------------------------------

class TestSccSharded:
    def _graph(self, seed, n=2500, e=30_000):
        rng = np.random.default_rng(seed)
        src = rng.integers(0, n, e)
        dst = rng.integers(0, n, e)
        # a few guaranteed cycles so nontrivial SCCs exist
        ring = np.arange(40)
        src = np.concatenate([src, ring])
        dst = np.concatenate([dst, np.roll(ring, -1)])
        return n, src, dst

    def test_sharded_labels_match_host(self, monkeypatch, devices8):
        n, src, dst = self._graph(5)
        host = scc._scc_host(n, src, dst)
        _cap(monkeypatch, 8)
        dev = scc.scc_device(n, src, dst)
        assert dev is not None
        assert dev[:n].tolist() == host.tolist()

    def test_keyblock_layout_cannot_change_labels(self, monkeypatch,
                                                  devices8):
        n, src, dst = self._graph(6)
        ekey = np.random.default_rng(1).integers(-1, 5, len(src))
        _cap(monkeypatch, 8)
        telemetry.reset()
        with_key = scc.scc_device(n, src, dst, ekey=ekey)
        plain = scc.scc_device(n, src, dst)
        assert with_key is not None and plain is not None
        assert with_key[:n].tolist() == plain[:n].tolist()
        c = telemetry.get().counters()
        assert c.get("scc.keyblock-layouts", 0) >= 1

    def test_emask_subsets_survive_sharding(self, monkeypatch,
                                            devices8):
        n, src, dst = self._graph(7)
        emask = np.random.default_rng(2).random(len(src)) < 0.7
        _cap(monkeypatch, 0)
        ref = scc.scc(n, src, dst, emask=emask)
        _cap(monkeypatch, 8)
        got = scc.scc(n, src, dst, emask=emask)
        assert got.tolist() == ref.tolist()


# ---------------------------------------------------------------------------
# the tier-1 fake-8-device smoke (CI tripwire)
# ---------------------------------------------------------------------------

class TestFake8Smoke:
    def test_sharded_launch_spreads_work_over_8_devices(self,
                                                        devices8):
        """The regression tripwire: a sharded ensemble launch on the
        fake 8-device mesh must actually attribute work to all 8
        shards with a sane balance — if a refactor quietly
        re-serializes or re-replicates the launch, this fails in CI,
        not on hardware (doc/spmd.md)."""
        profiler.reset()
        telemetry.reset()
        m = models.cas_register()
        encs = [encode(m, synth.register_history(
            24, n_procs=3, seed=300 + i)) for i in range(16)]
        mesh = ensemble.default_mesh(8)
        res = ensemble.check_batch_sharded(encs, mesh=mesh, W=16,
                                           F=16)
        assert all(int(r) == wgl.VALID for r in res)
        recs = [r for r in profiler.get().records()
                if r["kernel"] == "wgl-sharded"]
        assert recs, "sharded launch left no profiler record"
        r = recs[0]
        assert r["devices"] == 8
        assert len(r["device_entries"]) == 8
        assert all(w > 0 for w in r["device_entries"]), \
            r["device_entries"]  # every shard got real rows
        assert r["balance"] and r["balance"] >= 0.5
        g = telemetry.get().gauges()
        assert g.get("wgl.spmd.devices") == 8

    def test_segmented_path_rides_the_mesh(self, devices8):
        """check_segmented (and through _launch, every wgl entry
        point) must land on the SPMD program when the process has
        devices — the headline 1M-event path scales only if this
        stays true."""
        telemetry.reset()
        m = models.cas_register()
        enc = encode(m, synth.register_history(400, n_procs=4,
                                               seed=71))
        res = wgl.check_segmented(enc, target_len=32)
        assert res is not None and res["valid?"] is True
        c = telemetry.get().counters()
        assert c.get("wgl.spmd.launches", 0) >= 1
