"""CrateDB suite tests: DB config emission via the dummy remote, the
_sql-over-curl reply handling, conditional-UPDATE CAS semantics, and
clusterless end-to-end register runs (mirrors aphyr/jepsen
crate/src/jepsen/crate.clj)."""

import threading

from jepsen_tpu import control, core, suites, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import crate as cr


class TestRegistry:
    def test_registered(self):
        assert "crate" in suites.SUITES
        assert suites.load("crate") is cr


def _sql_responder(node, action):
    """install_archive's probe commands + a success reply for the
    schema-create curl on the primary (the disque test's responder
    pattern)."""
    from jepsen_tpu.control.core import Result

    if action.cmd.startswith("curl"):
        return '{"rows": [], "rowcount": 1}'
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "crate-5.7.2"
    return None


class TestDB:
    def test_setup_commands(self):
        seen = []

        def responder(node, action):
            seen.append(action.cmd)
            return _sql_responder(node, action)

        remote = DummyRemote(responder)
        nodes = ["n1", "n2", "n3"]
        test = testing.noop_test()
        test.update(nodes=nodes, remote=remote,
                    sessions={n: remote.connect({"host": n})
                              for n in nodes})
        with control.with_session(test, "n1"):
            cr.CrateDB("5.7.2").setup(test, "n1")
        got = " ; ".join(seen)
        assert "crate-5.7.2.tar.gz" in got
        assert "-Cdiscovery.seed_hosts=n1:4300,n2:4300,n3:4300" in got
        # the primary creates the schema with full replication (the
        # schema curl runs on CrateSql's own session, hence the
        # responder-side capture)
        assert "CREATE TABLE IF NOT EXISTS jepsen_r" in got
        assert "number_of_replicas = 2" in got

    def test_non_primary_skips_schema(self):
        remote = DummyRemote(_sql_responder)
        nodes = ["n1", "n2"]
        test = testing.noop_test()
        test.update(nodes=nodes, remote=remote,
                    sessions={n: remote.connect({"host": n})
                              for n in nodes})
        with control.with_session(test, "n2"):
            cr.CrateDB().setup(test, "n2")
        got = " ; ".join(a.cmd for a in test["sessions"]["n2"].log
                         if isinstance(a, Action))
        assert "CREATE TABLE" not in got


class FakeCrate:
    """An in-memory register speaking _sql JSON replies, including
    the conditional-UPDATE rowcount contract."""

    def __init__(self):
        self.lock = threading.Lock()
        self.value = None

    def stmt(self, sql, args=None):
        args = args or []
        s = sql.strip().upper()
        with self.lock:
            if s.startswith("REFRESH"):
                return {"rows": [], "rowcount": 0}
            if s.startswith("SELECT"):
                rows = [] if self.value is None else [[self.value]]
                return {"rows": rows, "rowcount": len(rows)}
            if s.startswith("INSERT"):
                self.value = int(args[0])
                return {"rows": [], "rowcount": 1}
            if s.startswith("UPDATE"):
                to, frm = int(args[0]), int(args[1])
                if self.value is not None and self.value == frm:
                    self.value = to
                    return {"rows": [], "rowcount": 1}
                return {"rows": [], "rowcount": 0}
            raise AssertionError(f"unexpected {sql}")


class FakeSqlFactory:
    def __init__(self, state=None):
        self.state = state or FakeCrate()

    def __call__(self, test, node, timeout=8.0):
        state = self.state

        class _C:
            def stmt(self, sql, args=None):
                return state.stmt(sql, args)

            def close(self):
                pass

        return _C()


def run_register(opts, factory):
    w = cr.register_workload(opts)
    w["client"].sql_factory = factory
    test = testing.noop_test()
    test.update(nodes=["n1", "n2"],
                concurrency=opts.get("concurrency", 4),
                client=w["client"], checker=w["checker"],
                generator=gen.clients(
                    gen.stagger(0.0004, w["generator"])))
    return core.run(test)


class TestEndToEnd:
    def test_register_linearizable(self):
        test = run_register({"ops": 150, "seed": 9},
                            FakeSqlFactory())
        assert test["results"]["valid?"] is True
        assert test["results"]["anomaly-classes"][
            "nonlinearizable"] == "clean"

    def test_lost_update_detected(self):
        class LostUpdates(FakeCrate):
            """Every 4th acknowledged write silently reverts — the
            version-divergence shape the reference analysis found."""

            def __init__(self):
                super().__init__()
                self.writes = 0

            def stmt(self, sql, args=None):
                out = super().stmt(sql, args)
                if sql.strip().upper().startswith("INSERT"):
                    self.writes += 1
                    if self.writes % 4 == 0:
                        with self.lock:
                            self.value = 97
                return out

        test = run_register({"ops": 200, "seed": 11},
                            FakeSqlFactory(LostUpdates()))
        assert test["results"]["valid?"] is False
        assert test["results"]["anomaly-classes"][
            "nonlinearizable"] == "witnessed"


class TestClient:
    def test_cas_rowcount_contract(self):
        state = FakeCrate()
        state.value = 2
        c = cr.CrateRegisterClient(FakeSqlFactory(state)).open(
            {}, "n1")
        op = Op(index=0, time=0, type="invoke", process=0, f="cas",
                value=[3, 4])
        assert c.invoke({}, op).type == "fail"  # rowcount 0: definite
        op2 = Op(index=0, time=0, type="invoke", process=0, f="cas",
                 value=[2, 4])
        assert c.invoke({}, op2).type == "ok"
        assert state.value == 4

    def test_sql_error_reply_is_definite_fail(self):
        class Rejecting:
            def __call__(self, test, node, timeout=8.0):
                class _C:
                    def stmt(self, sql, args=None):
                        raise cr.CrateSqlError(
                            "blocked by: [FORBIDDEN/12/index "
                            "read-only]")

                    def close(self):
                        pass

                return _C()

        c = cr.CrateRegisterClient(Rejecting()).open({}, "n1")
        op = Op(index=0, time=0, type="invoke", process=0, f="write",
                value=1)
        assert c.invoke({}, op).type == "fail"

    def test_opaque_sql_error_on_write_is_indeterminate(self):
        """An internal shard-failure error during a partition may
        have applied on the primary — never a definite :fail (the
        rethinkdb-suite classification rule)."""

        class Opaque:
            def __call__(self, test, node, timeout=8.0):
                class _C:
                    def stmt(self, sql, args=None):
                        raise cr.CrateSqlError(
                            "SQLActionException: shard failure, "
                            "primary unavailable")

                    def close(self):
                        pass

                return _C()

        c = cr.CrateRegisterClient(Opaque()).open({}, "n1")
        op = Op(index=0, time=0, type="invoke", process=0, f="write",
                value=1)
        assert c.invoke({}, op).type == "info"
        # reads always fail safely
        rd = Op(index=0, time=0, type="invoke", process=0, f="read",
                value=None)
        assert c.invoke({}, rd).type == "fail"

    def test_transport_error_on_write_is_indeterminate(self):
        class Dying:
            def __call__(self, test, node, timeout=8.0):
                class _C:
                    def stmt(self, sql, args=None):
                        from jepsen_tpu.control.core import \
                            RemoteError

                        raise RemoteError("timed out", exit=28,
                                          out="", err="timed out",
                                          cmd="curl", node=node)

                    def close(self):
                        pass

                return _C()

        c = cr.CrateRegisterClient(Dying()).open({}, "n1")
        op = Op(index=0, time=0, type="invoke", process=0, f="write",
                value=1)
        assert c.invoke({}, op).type == "info"

    def test_non_json_reply_raises_remote_error(self):
        responder_out = []

        def responder(node, action):
            responder_out.append(action.cmd)
            return "<html>502 bad gateway</html>"

        remote = DummyRemote(responder)
        test = testing.noop_test()
        test.update(nodes=["n1"], remote=remote,
                    sessions={"n1": remote.connect({"host": "n1"})})
        with control.with_session(test, "n1"):
            sql = cr.CrateSql(test, "n1")
            import pytest

            from jepsen_tpu.control.core import RemoteError

            with pytest.raises(RemoteError):
                sql.stmt("SELECT 1")
