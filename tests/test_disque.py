"""Disque suite tests: DB command emission via the dummy remote, a
scripted disque CLI, and clusterless end-to-end queue runs (mirrors
aphyr/jepsen disque/src/jepsen/disque.clj)."""

import threading

from jepsen_tpu import control, core, suites, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.suites import disque as dq


def responder(node, action):
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "disque-1.0-rc1"
    return None


class TestRegistry:
    def test_disque_registered(self):
        assert "disque" in suites.SUITES
        assert suites.load("disque") is dq

    def test_unknown_suite_raises(self):
        import pytest

        with pytest.raises(KeyError):
            suites.load("no-such-db")


class TestDB:
    def test_setup_commands(self):
        remote = DummyRemote(responder)
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"], remote=remote,
                    sessions={n: remote.connect({"host": n})
                              for n in ["n1", "n2", "n3"]})
        db = dq.DisqueDB("1.0-rc1")
        with control.with_session(test, "n2"):
            db.setup(test, "n2")
        got = " ; ".join(a.cmd for a in test["sessions"]["n2"].log
                         if isinstance(a, Action))
        assert "1.0-rc1.tar.gz" in got
        assert "make" in got
        assert "--port 7711" in got
        # meets every OTHER node, not itself
        assert "cluster meet n1 7711" in got
        assert "cluster meet n3 7711" in got
        assert "cluster meet n2 7711" not in got


class FakeDisque:
    """In-memory broker speaking disque CLI reply strings: ADDJOB
    assigns ids, GETJOB reserves (redelivers unless ACKed), ACKJOB
    deletes."""

    def __init__(self):
        self.lock = threading.Lock()
        self.jobs: dict = {}     # id -> body
        self.order: list = []    # FIFO of unreserved ids
        self.n = 0

    def run(self, *args):
        cmd = args[0].lower()
        with self.lock:
            if cmd == "addjob":
                self.n += 1
                jid = f"DI{self.n:08d}SQ"
                self.jobs[jid] = args[2]
                self.order.append(jid)
                return jid
            if cmd == "getjob":
                if not self.order:
                    return ""
                jid = self.order.pop(0)
                return f"{args[-1]}\n{jid}\n{self.jobs[jid]}"
            if cmd == "ackjob":
                self.jobs.pop(args[1], None)
                return "1"
            if cmd == "cluster":
                return "OK"
            raise AssertionError(f"unexpected {args}")


class FakeCliFactory:
    def __init__(self, state=None):
        self.state = state or FakeDisque()

    def __call__(self, test, node, timeout=5.0):
        factory = self

        class _C:
            def run(self, *args):
                return factory.state.run(*args)

            def close(self):
                pass

        return _C()


def run_queue(opts, factory):
    w = dq.queue_workload(opts)
    w["client"].cli_factory = factory
    test = testing.noop_test()
    test.update(nodes=["n1", "n2"],
                concurrency=opts.get("concurrency", 4),
                client=w["client"], checker=w["checker"],
                generator=gen.clients(
                    gen.stagger(0.0004, w["generator"])))
    return core.run(test)


class TestEndToEnd:
    def test_queue_conserves(self):
        test = run_queue({"ops": 150}, FakeCliFactory())
        assert test["results"]["valid?"] is True
        tq = test["results"]["total-queue"]
        assert tq["lost-count"] == 0 and tq["unexpected-count"] == 0
        # coverage taxonomy tags ride on the verdict
        assert tq["anomaly-classes"]["queue-lost"] == "clean"

    def test_queue_detects_lost_jobs(self):
        class Dropping(FakeDisque):
            def run(self, *args):
                out = super().run(*args)
                if args[0].lower() == "addjob" and self.n % 5 == 0:
                    # ack'd the job, then lost it
                    with self.lock:
                        jid = self.order.pop()
                        self.jobs.pop(jid, None)
                return out

        test = run_queue({"ops": 200}, FakeCliFactory(Dropping()))
        tq = test["results"]["total-queue"]
        assert test["results"]["valid?"] is False
        assert tq["lost-count"] > 0
        assert tq["anomaly-classes"]["queue-lost"] == "witnessed"

    def test_unacked_getjob_redelivers_as_duplicate_never_lost(self):
        class LostAck(FakeDisque):
            """Every 7th GETJOB's ACK is dropped and the job
            redelivered — the crashed-dequeue path."""

            def __init__(self):
                super().__init__()
                self.acks = 0

            def run(self, *args):
                if args[0].lower() == "ackjob":
                    self.acks += 1
                    if self.acks % 7 == 0:
                        with self.lock:
                            if args[1] in self.jobs:
                                self.order.append(args[1])
                        return "1"
                return super().run(*args)

        test = run_queue({"ops": 200}, FakeCliFactory(LostAck()))
        tq = test["results"]["total-queue"]
        assert tq["lost-count"] == 0
        assert tq["anomaly-classes"]["queue-lost"] == "clean"


class TestClientErrors:
    def test_broker_error_reply_is_definite_fail(self):
        class Rejecting:
            def __call__(self, test, node, timeout=5.0):
                class _C:
                    def run(self, *args):
                        return "NOREPLICA Not enough reachable nodes"

                    def close(self):
                        pass

                return _C()

        c = dq.DisqueQueueClient(Rejecting()).open({}, "n1")
        from jepsen_tpu.history import Op

        op = Op(index=0, time=0, type="invoke", process=0,
                f="enqueue", value=7)
        done = c.invoke({}, op)
        assert done.type == "fail"

    def test_transport_error_on_enqueue_is_indeterminate(self):
        class Dying:
            def __call__(self, test, node, timeout=5.0):
                class _C:
                    def run(self, *args):
                        from jepsen_tpu.control.core import RemoteError

                        raise RemoteError("broken pipe", exit=1,
                                          out="", err="broken pipe",
                                          cmd="addjob", node=node)

                    def close(self):
                        pass

                return _C()

        c = dq.DisqueQueueClient(Dying()).open({}, "n1")
        from jepsen_tpu.history import Op

        op = Op(index=0, time=0, type="invoke", process=0,
                f="enqueue", value=7)
        done = c.invoke({}, op)
        assert done.type == "info"
