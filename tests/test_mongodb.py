"""MongoDB suite tests: DB/replica-set command emission via the dummy
remote, runCommand semantics against an in-memory replica document
store, and clusterless end-to-end document-cas runs (mirrors
mongodb-smartos/src/jepsen/mongodb_smartos/{core,document_cas}.clj)."""

import threading

from jepsen_tpu import control, core, independent, testing
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import Action, RemoteError, Result
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.history import Op
from jepsen_tpu.suites import mongodb as mdb


def responder(node, action):
    if action.cmd.startswith("stat "):
        return Result(exit=1, out="", err="no such file",
                      cmd=action.cmd)
    if action.cmd.startswith("dirname "):
        return action.cmd.split()[-1].rsplit("/", 1)[0]
    if action.cmd.startswith("ls -A"):
        return "mongodb-linux-x86_64"
    return None


def make_test(nodes=("n1", "n2", "n3")):
    remote = DummyRemote(responder)
    t = testing.noop_test()
    t.update(nodes=list(nodes), remote=remote,
             sessions={n: remote.connect({"host": n}) for n in nodes})
    return core.prepare_test(t)


def cmds(test, node):
    return [a.cmd for a in test["sessions"][node].log
            if isinstance(a, Action)]


class TestDB:
    def test_setup_commands(self):
        test = make_test()
        db = mdb.MongoDB("7.0.14", shell_factory=None)
        control.on_nodes(test, lambda t, n: db.setup(t, n))
        got = " ; ".join(cmds(test, "n2"))
        assert "mongodb-linux-x86_64-debian11-7.0.14.tgz" in got
        assert "mongosh-2.3.1-linux-x64.tgz" in got
        assert "--replSet rs0" in got
        assert "--bind_ip_all" in got
        assert "--dbpath /var/lib/mongodb" in got

    def test_teardown_wipes(self):
        test = make_test()
        db = mdb.MongoDB(shell_factory=None)
        with control.with_session(test, "n1"):
            db.teardown(test, "n1")
        got = " ; ".join(cmds(test, "n1"))
        assert "/var/lib/mongodb" in got

    def test_initiate_runs_on_primary_only(self):
        calls = []

        class Shell:
            def __init__(self, test, node, direct=False, timeout=10.0):
                self.node = node

            def run_command(self, command, admin=False):
                calls.append((self.node, next(iter(command))))
                if "replSetInitiate" in command:
                    return {"ok": 1}
                return {"ok": 1, "isWritablePrimary": True}

            def close(self):
                pass

        test = make_test()
        db = mdb.MongoDB(shell_factory=Shell)
        control.on_nodes(test, lambda t, n: db.setup(t, n))
        assert ("n1", "replSetInitiate") in calls
        assert not any(n != "n1" for n, c in calls
                       if c == "replSetInitiate")
        assert ("n1", "hello") in calls


class FakeMongo:
    """In-memory document store speaking the runCommand subset the
    suite uses (find/update with upsert + query guards)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.docs: dict = {}  # _id -> value
        self.commands: list = []

    def run_command(self, command, admin=False):
        self.commands.append(command)
        with self.lock:
            if "find" in command:
                k = command["filter"]["_id"]
                if k in self.docs:
                    batch = [{"_id": k, "value": self.docs[k]}]
                else:
                    batch = []
                return {"ok": 1, "cursor": {"firstBatch": batch}}
            if "update" in command:
                u = command["updates"][0]
                q, upd = u["q"], u["u"]
                matched = (q["_id"] in self.docs
                           and all(self.docs[q["_id"]] == v
                                   for key, v in q.items()
                                   if key == "value"))
                if "value" in q:  # guarded cas
                    if not matched:
                        return {"ok": 1, "n": 0, "nModified": 0}
                    self.docs[q["_id"]] = upd["$set"]["value"]
                    return {"ok": 1, "n": 1, "nModified": 1}
                # plain upsert write
                self.docs[q["_id"]] = upd["value"]
                return {"ok": 1, "n": 1, "nModified": 1}
            raise AssertionError(f"unexpected command {command}")


class FakeShellFactory:
    def __init__(self, state=None):
        self.state = state or FakeMongo()

    def __call__(self, test, node, direct=False, timeout=10.0):
        factory = self

        class _Shell:
            def run_command(self, command, admin=False):
                return factory.state.run_command(command, admin)

            def close(self):
                pass

        return _Shell()


def kop(f, k, v=None):
    return Op(type="invoke", process=0, f=f,
              value=independent.ktuple(k, v))


class TestClient:
    def _client(self, state=None):
        f = FakeShellFactory(state)
        c = mdb.MongoCasClient(shell_factory=f).open(
            {"nodes": ["n1"]}, "n1")
        return c, f.state

    def test_read_write_cas_roundtrip(self):
        c, _ = self._client()
        assert c.invoke({}, kop("read", 0)).value == \
            independent.ktuple(0, None)
        assert c.invoke({}, kop("write", 0, 3)).type == "ok"
        assert c.invoke({}, kop("read", 0)).value == \
            independent.ktuple(0, 3)
        assert c.invoke({}, kop("cas", 0, [3, 4])).type == "ok"
        assert c.invoke({}, kop("cas", 0, [3, 9])).type == "fail"
        assert c.invoke({}, kop("read", 0)).value == \
            independent.ktuple(0, 4)

    def test_write_concern_threads_through(self):
        c, state = self._client()
        c.invoke({}, kop("write", 0, 1))
        wc = state.commands[-1]["writeConcern"]
        assert wc == {"w": "majority"}

    def test_numeric_write_concern(self):
        f = FakeShellFactory()
        c = mdb.MongoCasClient(shell_factory=f,
                               write_concern="1").open(
            {"nodes": ["n1"]}, "n1")
        c.invoke({}, kop("write", 0, 1))
        assert f.state.commands[-1]["writeConcern"] == {"w": 1}

    def test_read_concern_on_reads(self):
        c, state = self._client()
        c.invoke({}, kop("read", 0))
        assert state.commands[-1]["readConcern"] == {
            "level": "linearizable"}

    def test_write_errors_in_ok_reply_are_fail(self):
        """Mongo answers ok:1 with per-document writeErrors (e.g.
        E11000 upsert race): the write did NOT apply — definite fail,
        never :ok."""

        class Racy(FakeMongo):
            def run_command(self, command, admin=False):
                if "update" in command:
                    return {"ok": 1, "n": 0, "writeErrors": [
                        {"index": 0, "code": 11000,
                         "errmsg": "E11000 duplicate key"}]}
                return super().run_command(command, admin)

        c, _ = self._client(Racy())
        r = c.invoke({}, kop("write", 0, 3))
        assert r.type == "fail" and "11000" in r.error
        r = c.invoke({}, kop("cas", 0, [1, 2]))
        assert r.type == "fail" and "11000" in r.error

    def test_write_concern_error_is_info(self):
        """Applied locally but durability unmet: indeterminate."""

        class Undurable(FakeMongo):
            def run_command(self, command, admin=False):
                res = super().run_command(command, admin)
                if "update" in command:
                    res["writeConcernError"] = {
                        "code": 64, "errmsg": "waiting for replication"}
                return res

        c, _ = self._client(Undurable())
        assert c.invoke({}, kop("write", 0, 3)).type == "info"
        assert c.invoke({}, kop("cas", 0, [3, 4])).type == "info"

    def test_unapplied_upsert_is_fail(self):
        class Noop(FakeMongo):
            def run_command(self, command, admin=False):
                if "update" in command and \
                        "value" not in command["updates"][0]["q"]:
                    return {"ok": 1, "n": 0, "nModified": 0}
                return super().run_command(command, admin)

        c, _ = self._client(Noop())
        assert c.invoke({}, kop("write", 0, 3)).type == "fail"

    def test_not_primary_is_definite_fail(self):
        class Down:
            def __call__(self, test, node, direct=False, timeout=10.0):
                class _Shell:
                    def run_command(self, command, admin=False):
                        raise RemoteError(
                            "mongosh failed", exit=1, out="",
                            err="NotWritablePrimary", cmd="mongosh",
                            node=node)

                    def close(self):
                        pass

                return _Shell()

        c = mdb.MongoCasClient(shell_factory=Down()).open(
            {"nodes": ["n1"]}, "n1")
        assert c.invoke({}, kop("write", 0, 1)).type == "fail"

    def test_timeout_write_is_info(self):
        class Slow:
            def __call__(self, test, node, direct=False, timeout=10.0):
                class _Shell:
                    def run_command(self, command, admin=False):
                        raise RemoteError("mongosh timed out",
                                          cmd="mongosh", node=node)

                    def close(self):
                        pass

                return _Shell()

        c = mdb.MongoCasClient(shell_factory=Slow()).open(
            {"nodes": ["n1"]}, "n1")
        assert c.invoke({}, kop("write", 0, 1)).type == "info"
        assert c.invoke({}, kop("read", 0)).type == "fail"


class TestEndToEnd:
    def _run(self, factory, opts):
        w = mdb.cas_workload(opts)
        w["client"].shell_factory = factory
        test = testing.noop_test()
        test.update(nodes=["n1", "n2", "n3"],
                    concurrency=opts["concurrency"],
                    client=w["client"], checker=w["checker"],
                    generator=gen.clients(
                        gen.stagger(0.0005, w["generator"])))
        return core.run(test)

    def test_cas_workload_valid(self):
        test = self._run(FakeShellFactory(),
                         {"concurrency": 6, "keys": 2,
                          "ops_per_key": 60, "seed": 7})
        assert test["results"]["valid?"] is True
        fs = {op.f for op in test["history"]}
        assert fs == {"read", "write", "cas"}

    def test_stale_read_detected(self):
        """A fake that serves every read from a stale snapshot is not
        linearizable once writes land."""

        class Stale(FakeMongo):
            def __init__(self):
                super().__init__()
                self.snapshot: dict = {}
                self.reads = 0

            def run_command(self, command, admin=False):
                if "find" in command:
                    self.reads += 1
                    if self.reads > 10:  # serve from frozen state
                        k = command["filter"]["_id"]
                        batch = ([{"_id": k,
                                   "value": self.snapshot.get(k, -7)}]
                                 if True else [])
                        return {"ok": 1,
                                "cursor": {"firstBatch": batch}}
                return super().run_command(command, admin)

        test = self._run(FakeShellFactory(Stale()),
                         {"concurrency": 6, "keys": 1,
                          "ops_per_key": 80, "seed": 3})
        assert test["results"]["valid?"] is False


class TestCli:
    def test_test_map_shape(self):
        opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                "ssh": {"dummy": True}, "time_limit": 5}
        test = mdb.mongodb_test(opts)
        assert test["name"] == "mongodb-cas"
        assert isinstance(test["db"], mdb.MongoDB)

    def test_concerns_reach_client(self):
        opts = {"nodes": ["n1"], "concurrency": 2,
                "ssh": {"dummy": True}, "write_concern": "1",
                "read_concern": "majority"}
        test = mdb.mongodb_test(opts)
        assert test["client"].write_concern == "1"
        assert test["client"].read_concern == "majority"
