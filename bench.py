#!/usr/bin/env python
"""Headline benchmark: linearizability-check throughput on a 1M-event
CAS-register history (BASELINE.md north-star config 2: check in < 60 s;
the reference's knossos CPU checker times out at this scale).

Prints ONE JSON line:
  {"metric": ..., "value": ops/sec checked, "unit": "ops/s",
   "vs_baseline": speedup vs the 60 s target}

Timed region: history -> encode -> device check (the full checking
pipeline a test run would execute after the interpreter finishes).
History generation is untimed setup. BENCH_OPS overrides the event count
(e.g. BENCH_OPS=100000 for a smoke run on CPU).
"""

import json
import os
import sys
import time


def main():
    n_events = int(os.environ.get("BENCH_OPS", "1000000"))
    n_invocations = n_events // 2
    target_s = 60.0 * (n_events / 1_000_000)  # baseline scales with size

    from jepsen_tpu.checker import models
    from jepsen_tpu.tpu import synth, wgl
    from jepsen_tpu.tpu.encode import encode

    t0 = time.time()
    hist = synth.register_history(n_invocations, n_procs=5, seed=42)
    n_events = len(hist)
    gen_s = time.time() - t0
    print(f"# generated {n_events} events in {gen_s:.1f}s",
          file=sys.stderr)

    t1 = time.time()
    enc = encode(models.cas_register(), hist)
    enc_s = time.time() - t1

    # First check pays one-time XLA compilation (cached on disk across
    # runs); report steady-state and note compile separately.
    t_c = time.time()
    wgl.check_segmented(enc, target_len=2048)
    first_s = time.time() - t_c

    t2 = time.time()
    res = wgl.check_segmented(enc, target_len=2048)
    if res is None:
        res = {"valid?": bool(wgl.check_batch([enc])[0] == wgl.VALID)}
    check_s = time.time() - t2
    elapsed = enc_s + check_s
    print(f"# first check (incl. compile) {first_s:.2f}s",
          file=sys.stderr)

    assert res["valid?"] is True, f"expected valid history: {res}"
    print(f"# encode {enc_s:.2f}s  check {check_s:.2f}s  "
          f"segments={res.get('segments')}  m={enc.m}", file=sys.stderr)
    print(json.dumps({
        "metric": "linearizability check throughput "
                  f"({n_events // 1000}k-event CAS register history)",
        "value": round(n_events / elapsed, 1),
        "unit": "ops/s",
        "vs_baseline": round(target_s / elapsed, 2),
    }))


if __name__ == "__main__":
    main()
