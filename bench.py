#!/usr/bin/env python
"""Benchmarks against BASELINE.md's north-star configs.

Headline (printed LAST, the line the driver records):
  config 2 — linearizability-check throughput on a 1M-event CAS-register
  history (< 60 s target on TPU; the reference's knossos CPU checker
  times out at this scale). Timed region: encode -> segmented device
  check, median of 5 runs so one noisy pair can't flip the artifact
  (round-2 verdict: the single-shot bench recorded a below-baseline
  outlier); per-rep times + spread ride in the JSON, and a >20% median
  drop vs the BEST of the last 3 rounds (perf ledger + BENCH_r*.json)
  fails loudly (REGRESSION banner + regression fields). Every full-size
  round appends a per-kernel entry to bench_ledger.jsonl; an EWMA
  slow-bleed detector (jepsen_tpu.ledger) flags gradual drifts the
  per-round gate can't see, attributed per kernel (wgl/elle/encode).

Also printed (one JSON line each, config 2 last):
  config 3 — elle list-append dependency-cycle check, 100k txns
             (device engine: interned arrays + batched SCC)
  config 3b — elle rw-register cycle check, 100k txns (device SCC)
  config 4 — bank balance-conservation check, 500k txns (array fold)
  config 5 — 1024-history ensemble checked in one batched launch
  config 6 — time-to-first-anomaly: seeded invalid read at 85% of a
             1M-event history, localized via segment reach masks

Baselines: config 2's is the 60 s target scaled to history size; the
others use the host reference engines (pure-Python elle / per-op fold)
measured in-process, so vs_baseline = host_time / device_time.

EVERY config reports the median of 3 timed runs and prints the
individual run times (the box shows up to ~30% run-to-run noise; a
single-run figure can hide a real regression or fake one).

BENCH_OPS scales config 2 (e.g. BENCH_OPS=100000 for a CPU smoke run);
BENCH_SKIP_EXTRAS=1 runs the headline config only.
"""

import json
import os
import statistics
import sys
import time


def _log(msg):
    print(f"# {msg}", file=sys.stderr)


def _bench_elle(label, metric, hist, check_fn):
    """Shared elle-config protocol: warm once, median of 3 device runs
    vs median of 3 host-engine runs."""
    check_fn(hist)  # warm: XLA compile out of timed region
    times = []
    for _ in range(3):
        t0 = time.time()
        res = check_fn(hist)
        times.append(time.time() - t0)
    assert res["valid?"] is True, res
    dev = statistics.median(times)
    host_times = []
    for _ in range(3):
        t0 = time.time()
        host = check_fn(hist, {"engine": "host"})
        host_times.append(time.time() - t0)
    host_s = statistics.median(host_times)
    assert host["valid?"] is True
    _log(f"{label}: device runs {['%.2f' % t for t in times]} "
         f"median {dev:.2f}s | host runs "
         f"{['%.2f' % t for t in host_times]} median {host_s:.2f}s")
    return {
        "metric": metric,
        "value": round(len(hist) // 2 / dev, 1),
        "unit": "txns/s",
        "vs_baseline": round(host_s / dev, 2),
    }


def bench_list_append(n_txns=100_000):
    from jepsen_tpu.tpu import elle, synth

    t0 = time.time()
    hist = synth.list_append_history(n_txns, seed=11)
    _log(f"config3: generated {n_txns} txns in {time.time() - t0:.1f}s")
    return _bench_elle(
        "config3", f"elle list-append cycle check ({n_txns // 1000}k txns)",
        hist, elle.check_list_append)


def bench_rw_register(n_txns=100_000):
    from jepsen_tpu.tpu import elle, synth

    t0 = time.time()
    hist = synth.rw_register_history(n_txns, seed=17)
    _log(f"config3b: generated {n_txns} rw txns in {time.time() - t0:.1f}s")
    return _bench_elle(
        "config3b", f"elle rw-register cycle check ({n_txns // 1000}k txns)",
        hist, elle.check_rw_register)


def bench_bank(n_txns=500_000):
    from jepsen_tpu.tpu import synth
    from jepsen_tpu.workloads import bank

    t0 = time.time()
    hist = synth.bank_history(n_txns, seed=11)
    _log(f"config4: generated {n_txns} txns in {time.time() - t0:.1f}s")
    total = 8 * 10
    bank.check_fast(hist, total)  # warm
    times = []
    for _ in range(3):
        t0 = time.time()
        res = bank.check_fast(hist, total)
        times.append(time.time() - t0)
    assert res["valid?"] is True, res
    dev = statistics.median(times)

    # host baseline: the reference-shaped per-op fold
    host_times = []
    for _ in range(3):
        t0 = time.time()
        bad = 0
        reads = 0
        for op in hist:
            if (op.type == "ok" and op.f == "read"
                    and op.value is not None):
                reads += 1
                balances = list(op.value.values())
                if sum(balances) != total or any(b < 0
                                                 for b in balances):
                    bad += 1
        host_times.append(time.time() - t0)
    host_s = statistics.median(host_times)
    assert bad == 0 and reads == res["read-count"]
    _log(f"config4: device runs {['%.2f' % t for t in times]} "
         f"median {dev:.2f}s | host-fold runs "
         f"{['%.2f' % t for t in host_times]} median {host_s:.2f}s")
    return {
        "metric": f"bank balance-conservation check ({n_txns // 1000}k txns)",
        "value": round(n_txns / dev, 1),
        "unit": "txns/s",
        "vs_baseline": round(host_s / dev, 2),
    }


def bench_ensemble(n_hists=1024, ops_each=400, crash_p=0.15):
    """Crashed (:info) ops are where batched search pays: the host
    search branches exponentially on indeterminate ops while the
    kernel's discard action costs nothing extra."""
    from jepsen_tpu.checker import models
    from jepsen_tpu.tpu import synth, wgl

    t0 = time.time()
    hists = [synth.register_history(ops_each, n_procs=4, seed=1000 + i,
                                    crash_p=crash_p)
             for i in range(n_hists)]
    total_ops = sum(len(h) for h in hists)
    _log(f"config5: generated {n_hists} histories "
         f"({total_ops} events) in {time.time() - t0:.1f}s")
    model = models.cas_register()
    # streamed: chunk i+1's encode overlaps chunk i's device search
    wgl.analysis_batch_streamed(model, hists, chunk=128)  # warm
    times = []
    for _ in range(3):
        t0 = time.time()
        results = wgl.analysis_batch_streamed(model, hists, chunk=128)
        times.append(time.time() - t0)
    assert all(r["valid?"] for r in results)
    dev = statistics.median(times)
    # host baseline: exhaustive WGL search per history, on a sample
    # (extrapolated — running all on host would dominate bench time)
    from jepsen_tpu.tpu.encode import encode
    sample = hists[:max(n_hists // 32, 8)]
    t0 = time.time()
    for h in sample:
        wgl.search_host(encode(model, h))
    host_s = (time.time() - t0) * (n_hists / len(sample))
    _log(f"config5: {n_hists} histories device runs "
         f"{['%.2f' % t for t in times]} median {dev:.2f}s "
         f"host-extrapolated {host_s:.1f}s")
    return {
        "metric": f"ensemble linearizability ({n_hists} histories, "
                  f"{ops_each} ops each, {int(crash_p * 100)}% crashes)",
        "value": round(total_ops / dev, 1),
        "unit": "ops/s",
        "vs_baseline": round(host_s / dev, 2),
    }


def bench_warm_start():
    """ISSUE-15 satellite: what the persistent compilation cache buys.
    Times the process's FIRST device check (which pays the wgl kernel
    compile) against the steady-state relaunch of the same bucket. On
    a warm cache (any prior bench/test round against the same dir)
    XLA serves the executable from disk and first-check wall collapses
    to ~steady — the line records cache state so rounds are
    comparable. MUST run before every other device bench (main()
    orders it first) or 'first' isn't first."""
    import jax

    from jepsen_tpu.checker import models
    from jepsen_tpu.tpu import synth, wgl
    from jepsen_tpu.tpu.encode import encode

    cache_dir = jax.config.jax_compilation_cache_dir
    warm = bool(cache_dir) and os.path.isdir(cache_dir) and \
        any(os.scandir(cache_dir))
    model = models.cas_register()
    encs = [encode(model, synth.register_history(
        200, n_procs=3, seed=9000 + i)) for i in range(8)]
    t0 = time.time()
    res = wgl.check_batch(encs)
    first = time.time() - t0
    assert all(int(r) == wgl.VALID for r in res)
    t0 = time.time()
    wgl.check_batch(encs)
    steady = time.time() - t0
    _log(f"warm-start: cache={'warm' if warm else 'cold'} "
         f"first={first:.3f}s steady={steady:.3f}s dir={cache_dir}")
    return {
        "metric": "warm-start first-check wall (8x200-op histories; "
                  "persistent XLA cache serves the compile when warm)",
        "value": round(first, 3),
        "unit": "s",
        "steady_s": round(steady, 3),
        "compile_overhead_x": (round(first / steady, 2)
                               if steady > 0 else None),
        "cache": "warm" if warm else "cold",
    }


def bench_anomaly(n_events):
    """Config 6: time-to-first-anomaly. A 1M-event register history
    with ONE seeded impossible read at ~85% depth; the checker must
    localize and explain it in bounded time (BASELINE.md names the
    metric; the reference's knossos pays unbounded search + 'writing
    these can take hours' on this path, checker.clj:222-233). The
    timed region is the full user path: encode -> analysis -> witness."""
    from jepsen_tpu.checker import models
    from jepsen_tpu.tpu import synth, wgl

    n_invocations = n_events // 2
    target_s = 60.0 * (n_events / 1_000_000)
    t0 = time.time()
    hist = synth.register_history(n_invocations, n_procs=5, seed=42)
    hist, bad_idx = synth.corrupt_register_history(hist, at_frac=0.85)
    _log(f"config6: {len(hist)} events, seeded anomaly at event "
         f"{bad_idx}, generated in {time.time() - t0:.1f}s")
    model = models.cas_register()
    wgl.analysis(model, hist)  # warm
    times = []
    for _ in range(3):
        t1 = time.time()
        res = wgl.analysis(model, hist)
        times.append(time.time() - t1)
        assert res["valid?"] is False, res
    assert "failed-segment" in res, res
    elapsed = statistics.median(times)
    search = res.get("search") or {}
    _log(f"config6: runs {['%.2f' % t for t in times]} median "
         f"{elapsed:.2f}s failed-segment={res['failed-segment']} "
         f"range={res.get('segment-range')} "
         f"witness-position={search.get('witness-position')}")
    line = {
        "metric": "time-to-first-anomaly "
                  f"({len(hist) // 1000}k-event history, seeded invalid read)",
        "value": round(elapsed, 2),
        "unit": "s",
        "vs_baseline": round(target_s / elapsed, 2),
    }
    # search-shape fields for the ledger: how early the anomaly
    # localized — ROADMAP-3's early-exit works off exactly this
    if search.get("witness-position") is not None:
        line["witness_position"] = search["witness-position"]
    return line


def bench_coverage_overhead(n_events=200_000):
    """Per-run coverage-record tax (jepsen_tpu.coverage): building +
    validating the fault × workload × anomaly record over a synthetic
    headline-scale history, vs the headline's ~60s/1M-event check
    budget. The record is one history pass (schedule features + the
    offline fault fold) plus a result walk — vs_baseline reports the
    fraction of the headline budget it costs (≈0 = free)."""
    from jepsen_tpu import coverage
    from jepsen_tpu.tpu import synth

    hist = synth.register_history(n_events // 2, n_procs=5, seed=42)
    test = {"name": "bench-coverage", "concurrency": 5,
            "spec": {"workload": "register", "opts": {}},
            "history": hist,
            "results": {"valid?": True,
                        "workload": {"valid?": True,
                                     "anomaly-classes": {
                                         "nonlinearizable": "clean"}}}}
    times = []
    for _ in range(3):
        t0 = time.time()
        rec = coverage.build_record(test,
                                    recorder=coverage.Recorder())
        coverage.validate_record(rec)
        coverage.atlas_entry(rec)
        times.append(time.time() - t0)
    elapsed = statistics.median(times)
    budget_s = 60.0 * (len(hist) / 1_000_000)
    _log(f"coverage-overhead: record over {len(hist)} events in "
         f"{elapsed:.3f}s ({elapsed / budget_s:.4f}x of the headline "
         "budget)")
    return {
        "metric": "coverage-record build+validate over a "
                  f"{len(hist) // 1000}k-event history",
        "value": round(len(hist) / max(elapsed, 1e-9), 1),
        "unit": "events/s",
        "vs_baseline": round(elapsed / budget_s, 4),
    }


def bench_certify_overhead(n_events=200_000):
    """Verdict-certificate tax (jepsen_tpu.tpu.certify): extracting a
    per-segment linearization proof from a segmented device check and
    independently re-validating it against the raw history, priced
    against the headline's 60s/1M-event budget (ISSUE-10 target:
    < 2% — whatever it really costs, this line records it). Runs the
    checker path (certify=True) on a headline-shaped history; the raw
    kernel configs above never pay this."""
    from jepsen_tpu.checker import models
    from jepsen_tpu.tpu import certify, synth, wgl

    hist = synth.register_history(n_events // 2, n_procs=5, seed=42)
    model = models.cas_register()
    wgl.analysis(model, hist)  # warm compile out of the timed region
    base_times, cert_times, val_times = [], [], []
    for _ in range(3):
        t0 = time.time()
        wgl.analysis(model, hist)
        base_times.append(time.time() - t0)
        t0 = time.time()
        res = wgl.analysis(model, hist, certify=True)
        cert_times.append(time.time() - t0)
        assert "absent" not in res["certificate"], res["certificate"]
        t0 = time.time()
        certify.validate(hist, res["certificate"])
        val_times.append(time.time() - t0)
    base = statistics.median(base_times)
    extract = statistics.median(cert_times) - base
    val = statistics.median(val_times)
    overhead = max(extract, 0) + val
    budget_s = 60.0 * (len(hist) / 1_000_000)
    _log(f"certify-overhead: analysis {base:.2f}s, +extract "
         f"{extract:.2f}s, +validate {val:.2f}s "
         f"({overhead / budget_s:.4f}x of the headline budget)")
    return {
        "metric": "certificate extraction+validation overhead "
                  f"({len(hist) // 1000}k-event valid history)",
        "value": round(overhead, 3),
        "unit": "s",
        "vs_baseline": round(overhead / budget_s, 4),
    }


def bench_headline(n_events):
    """Config 2: 1M-event register history, segmented device check.
    Median of 5 timed reps (the headline is the line the driver's
    regression tracking records — 3 reps let one noisy pair flip it);
    per-rep times and spread ride in the JSON so a regression report
    can tell noise from a real drop."""
    from jepsen_tpu.checker import models
    from jepsen_tpu.tpu import synth, wgl
    from jepsen_tpu.tpu.encode import encode

    n_invocations = n_events // 2
    target_s = 60.0 * (n_events / 1_000_000)

    t0 = time.time()
    hist = synth.register_history(n_invocations, n_procs=5, seed=42)
    n_events = len(hist)
    _log(f"config2: generated {n_events} events in {time.time() - t0:.1f}s")

    # First check pays one-time XLA compilation (cached on disk across
    # runs); report steady-state, note compile separately.
    t0 = time.time()
    enc = encode(models.cas_register(), hist)
    wgl.check_segmented(enc, target_len=8192)
    _log(f"config2: first check (incl. compile) {time.time() - t0:.2f}s")

    times, enc_times, chk_times = [], [], []
    for _ in range(5):
        t1 = time.time()
        enc = encode(models.cas_register(), hist)
        t_enc = time.time() - t1
        res = wgl.check_segmented(enc, target_len=8192)
        if res is None:
            res = {"valid?": bool(wgl.check_batch([enc])[0] == wgl.VALID)}
        t_all = time.time() - t1
        times.append(t_all)
        enc_times.append(t_enc)
        chk_times.append(t_all - t_enc)
        assert res["valid?"] is True, res
    elapsed = statistics.median(times)
    _log(f"config2: encode+check runs {['%.2f' % t for t in times]} "
         f"median {elapsed:.2f}s (encode "
         f"{statistics.median(enc_times):.2f}s + check "
         f"{statistics.median(chk_times):.2f}s) "
         f"segments={res.get('segments')} m={enc.m}")
    line = {
        "metric": "linearizability check throughput "
                  f"({n_events // 1000}k-event CAS register history)",
        "value": round(n_events / elapsed, 1),
        "unit": "ops/s",
        "vs_baseline": round(target_s / elapsed, 2),
        "runs_s": [round(t, 3) for t in times],
        "spread": round((max(times) - min(times)) / elapsed, 3),
        # per-kernel attribution for the ledger: a headline drop is a
        # regression in encode (host) or in the device check — name it
        "encode_s": round(statistics.median(enc_times), 3),
        "check_s": round(statistics.median(chk_times), 3),
    }
    return _check_regression(line)


REGRESSION_THRESHOLD = 0.20
"""Headline medians more than this far below the best of the last
GATE_WINDOW rounds fail loudly in the report."""

GATE_WINDOW = 3
"""How many previous rounds the gate considers. Comparing against the
BEST of the window (not just the previous round) closes the
two-consecutive-15%-drops hole: the second drop is still measured
against the pre-bleed value."""


def _bench_rounds():
    """[(round, headline-dict, source)] from the driver's BENCH_r<NN>
    artifacts, round order."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    paths = sorted(
        glob.glob(os.path.join(here, "BENCH_r*.json")),
        key=lambda p: int(re.search(r"r(\d+)", os.path.basename(p))
                          .group(1)))
    out = []
    for p in paths:
        try:
            with open(p) as f:
                parsed = json.load(f).get("parsed")
            if isinstance(parsed, dict) and parsed.get("value"):
                rnd = int(re.search(r"r(\d+)", os.path.basename(p))
                          .group(1))
                out.append((rnd, parsed, os.path.basename(p)))
        except (OSError, ValueError):
            continue
    return out


def _ledger_path():
    from jepsen_tpu import ledger

    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(here, ledger.LEDGER_FILE)


def _previous_headlines(metric):
    """The last GATE_WINDOW rounds' headline values for `metric`,
    merged from the perf ledger and the BENCH_r artifacts (the ledger
    wins when both carry a round — it's written by this script, the
    artifacts by the driver). Returns [(round, value, source)]."""
    from jepsen_tpu import ledger

    by_round = {}
    for rnd, parsed, src in _bench_rounds():
        if parsed.get("metric") == metric:
            by_round[rnd] = (parsed["value"], src)
    for e in ledger.read_entries(_ledger_path()):
        hl = e.get("headline") or {}
        if (hl.get("metric") == metric
                and isinstance(hl.get("value"), (int, float))
                and isinstance(e.get("round"), int)):
            by_round[e["round"]] = (hl["value"], "ledger")
    rounds = sorted(by_round)[-GATE_WINDOW:]
    return [(r, by_round[r][0], by_round[r][1]) for r in rounds]


def _check_regression(line):
    """Compares the new headline median against the BEST of the last
    GATE_WINDOW rounds (ledger + BENCH artifacts); a >20% drop fails
    loudly (REGRESSION banner on stderr + regression fields in the
    JSON, so the report can't read a real drop as routine noise).
    Skipped when history sizes differ (BENCH_OPS smoke runs aren't
    comparable)."""
    prev = _previous_headlines(line.get("metric"))
    if not prev:
        _log("regression check skipped: no previous round measured "
             f"{line.get('metric')!r}")
        return line
    best_round, best, src = max(prev, key=lambda t: t[1])
    ratio = line["value"] / best
    line["prev_value"] = best
    line["prev_rounds"] = [r for r, _v, _s in prev]
    line["vs_prev"] = round(ratio, 3)
    if ratio < 1.0 - REGRESSION_THRESHOLD:
        line["regression"] = True
        _log("!!! REGRESSION: headline "
             f"{line['value']} {line.get('unit')} is "
             f"{(1 - ratio) * 100:.1f}% below the best of the last "
             f"{len(prev)} rounds ({best} at r{best_round:02d}, "
             f"{src}); per-rep times "
             f"{line.get('runs_s')} spread {line.get('spread')}")
    else:
        _log(f"regression check: {ratio:.2f}x vs best of last "
             f"{len(prev)} rounds ({best} at r{best_round:02d}, "
             f"{src})")
    return line


# graftlint aggregates from bench_lint_wall, folded into the perf
# ledger entry so the SPMD PR can show R3/R4 going to zero.
_LINT_AGGREGATES: dict = {}


def bench_lint_wall():
    """graftlint tax (jepsen_tpu.analysis): the full static pass —
    abstract kernel traces at the default shape buckets, R1-R6, the
    host-feeder dtype audit, the concurrency lint, and the committed
    baseline gate — exactly what tier-1 runs. The first pass pays
    one-time jax tracing of every kernel (cached in-process after,
    like the headline's compile note); the BENCH value is that cold
    wall, priced against the headline's 60s/1M-event budget
    (vs_baseline = lint-seconds per budget; the ISSUE-12 bound is
    < 0.02 — the gate must stay ~free next to a real run)."""
    import statistics as _st

    from jepsen_tpu.analysis import driver

    here = os.path.dirname(os.path.abspath(__file__))
    baseline = os.path.join(here, "lint-baseline.json")
    import jax  # noqa: F401 — process startup, not lint cost: in a
    # bench run jax is imported long before this line; don't bill its
    # one-time import to the first lint pass when run standalone

    t0 = time.time()
    rep = driver.run_lint()
    cold = time.time() - t0
    warm = []
    for _ in range(3):
        t0 = time.time()
        rep = driver.run_lint()
        warm.append(time.time() - t0)
    if os.path.exists(baseline):
        driver.gate(rep, baseline)
    new = len(rep.ratchet["new"]) if rep.ratchet is not None else None
    _LINT_AGGREGATES.update(rep.aggregates())
    agg = rep.aggregates()
    budget_s = 60.0
    fraction = cold / budget_s
    _log(f"lint-wall: cold {cold:.2f}s warm median "
         f"{_st.median(warm):.2f}s ({fraction:.4f}x of the headline "
         f"budget) — {len(rep.findings)} finding(s), "
         f"{new if new is not None else '?'} new vs baseline, "
         f"R3 non-donated {agg['non_donated_bytes'] // 1024} KiB, "
         f"R4 unsharded axes {agg['unsharded_axes']}")
    line = {
        "metric": "graftlint full static pass wall time (kernel "
                  "traces + R1-R6 + concurrency lint + baseline "
                  "gate; cold, first pass in process)",
        "value": round(cold, 3),
        "unit": "s",
        "vs_baseline": round(fraction, 4),
        "warm_s": round(_st.median(warm), 3),
        "findings": len(rep.findings),
    }
    if new is not None:
        line["new_findings"] = new
    return line


def bench_monitor_overhead(n_ops=4000):
    """Live-monitor + watchdog tax on the interpreter hot loop: the
    same dummy-client run with and without the observers attached.
    vs_baseline = monitored_rate / bare_rate (1.0 = free; the ISSUE-3
    acceptance bound is 'rate-floor still passes', this line records
    the actual delta)."""
    import statistics as _st

    from jepsen_tpu import client as jclient
    from jepsen_tpu import interpreter, monitor, testing, util, watchdog
    from jepsen_tpu import generator as gen

    def one_run(monitored: bool) -> float:
        t = testing.noop_test()
        t.update(concurrency=8, client=jclient.noop,
                 generator=gen.clients(gen.limit(
                     n_ops, gen.repeat({"f": "write", "value": 1}))))
        if monitored:
            t["monitor"] = monitor.Monitor(t, interval_s=0.25)
            t["watchdog"] = watchdog.from_test(
                {"watchdog": ["register", "counter", "set"]})
            t["monitor"].start()
        util.init_relative_time()
        t0 = time.time()
        t = interpreter.run(dict(t))
        dt = time.time() - t0
        assert len(t["history"]) == 2 * n_ops
        if monitored:
            t["monitor"].stop()
        return n_ops / dt

    one_run(True)  # warm
    bare = _st.median([one_run(False) for _ in range(3)])
    mon = _st.median([one_run(True) for _ in range(3)])
    _log(f"monitor-overhead: bare {bare:.0f} ops/s "
         f"monitored {mon:.0f} ops/s ({mon / bare:.3f}x)")
    return {
        "metric": f"interpreter throughput with live monitor + "
                  f"watchdog attached ({n_ops} dummy ops)",
        "value": round(mon, 1),
        "unit": "ops/s",
        "vs_baseline": round(mon / bare, 3),
    }


def bench_nodeprobe_overhead(n_ticks=200, n_nodes=5):
    """Node-observability-plane tax (jepsen_tpu.nodeprobe): the probe
    runs on its own threads with its own sessions — it never touches
    the interpreter hot loop — so its cost is per-tick control-plane
    work (compound /proc probe + log tail + parse + record). This
    measures the median tick across a 5-node synthetic cluster, then
    prices the production cadence (1 tick/node/s) against the
    headline's 60s/1M-event budget: vs_baseline = probe-seconds per
    budget-second (the ISSUE-9 acceptance bound is < 0.02 — no silent
    overhead; whatever the plane costs, this line records it)."""
    import statistics as _st

    from jepsen_tpu import nodeprobe, testing, util
    from jepsen_tpu.control.dummy import DummyRemote

    nodes = [f"n{i + 1}" for i in range(n_nodes)]
    t = testing.noop_test()
    t.update(nodes=nodes,
             remote=DummyRemote(nodeprobe.synthetic_responder()),
             node_log_files=["/var/log/db.log"])
    util.init_relative_time()
    probe = nodeprobe.NodeProbe(t, interval_s=1.0)
    times = []
    for _ in range(n_ticks):
        t0 = time.time()
        for node in nodes:
            probe.tick(node)
        times.append((time.time() - t0) / n_nodes)
    probe.stop()
    assert probe.records()  # the plane actually sampled
    per_tick = _st.median(times)
    # production cadence: each node ticks once per wall second, so the
    # plane spends (per_tick * n_nodes) probe-seconds per second
    fraction = per_tick * n_nodes
    _log(f"nodeprobe-overhead: {per_tick * 1e3:.2f}ms/tick across "
         f"{n_nodes} nodes ({fraction:.4f}x of the headline budget "
         "at the 1s production cadence)")
    return {
        "metric": f"node-probe tick cost ({n_nodes} synthetic nodes, "
                  "compound /proc probe + log tail + record)",
        "value": round(per_tick * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(fraction, 4),
    }


def bench_trace_overhead(n_ops=4000):
    """Per-op causal-tracing tax on the interpreter hot loop: the same
    dummy-client run with the tracer DISABLED (the default state — one
    enabled check per op — which IS the bare baseline, so there is no
    separate 'bare' mode to compare) and with it ENABLED streaming
    optrace.jsonl (op + client spans per op, serialized off-thread).
    vs_baseline = traced_rate / disabled_rate. NOTE this is the worst
    case — dummy ops do zero work, so the fixed per-span cost IS the
    op; against real (ms-scale) clients the same fixed cost is <5%,
    and the headline checker config doesn't touch the tracer at
    all."""
    import statistics as _st
    import tempfile

    from jepsen_tpu import client as jclient
    from jepsen_tpu import interpreter, testing, tracing, util
    from jepsen_tpu import generator as gen

    def one_run(mode: str) -> float:
        t = testing.noop_test()
        t.update(concurrency=8, client=jclient.noop,
                 generator=gen.clients(gen.limit(
                     n_ops, gen.repeat({"f": "write", "value": 1}))))
        tracer = tracing.get()
        td = None
        if mode == "enabled":
            t["trace?"] = True
            td = tempfile.TemporaryDirectory()
            tracer.reset(enabled=True)
            tracer.open(os.path.join(td.name, tracing.TRACE_FILE))
        else:
            tracer.reset(enabled=False)
        util.init_relative_time()
        t0 = time.time()
        t = interpreter.run(dict(t))
        dt = time.time() - t0
        assert len(t["history"]) == 2 * n_ops
        if mode == "enabled":
            assert len(tracer.records()) >= n_ops
        tracer.reset(enabled=False)
        if td is not None:
            td.cleanup()
        return n_ops / dt

    one_run("enabled")  # warm
    disabled = _st.median([one_run("disabled") for _ in range(3)])
    traced = _st.median([one_run("enabled") for _ in range(3)])
    _log(f"trace-overhead: tracer disabled {disabled:.0f} ops/s, "
         f"enabled {traced:.0f} ops/s ({traced / disabled:.3f}x)")
    return {
        "metric": "interpreter throughput with per-op tracing enabled "
                  f"(optrace stream, {n_ops} dummy ops)",
        "value": round(traced, 1),
        "unit": "ops/s",
        "vs_baseline": round(traced / disabled, 3),
    }


def bench_watchdog_latency(n_ops=200_000):
    """Online-violation detection cost: per-op observe() time through
    all three adapters on a synthetic register stream, and the time
    from feeding a violating completion to the watchdog tripping.
    Baseline 1µs/op (well under a fast client round-trip)."""
    import statistics as _st

    from jepsen_tpu import watchdog
    from jepsen_tpu.history import Op

    ops = []
    for i in range(n_ops // 2):
        v = i % 5
        ops.append(Op(index=2 * i, time=2 * i, type="invoke",
                      process=i % 8, f="write", value=v))
        ops.append(Op(index=2 * i + 1, time=2 * i + 1, type="ok",
                      process=i % 8, f="read", value=v))
    times = []
    for _ in range(3):
        wd = watchdog.from_test(
            {"watchdog": ["register", "counter", "set"]})
        t0 = time.time()
        for op in ops:
            wd.observe(op)
        times.append(time.time() - t0)
        assert not wd.tripped
    per_op_us = _st.median(times) / len(ops) * 1e6
    # detection latency: one violating completion, observe -> tripped
    det = []
    for _ in range(5):
        wd = watchdog.from_test({"watchdog": ["register"]})
        for op in ops[:64]:
            wd.observe(op)
        bad = Op(index=65, time=65, type="ok", process=0, f="read",
                 value=999_999)
        t0 = time.time()
        wd.observe(bad)
        det.append(time.time() - t0)
        assert wd.tripped
    det_us = _st.median(det) * 1e6
    _log(f"watchdog: {per_op_us:.2f}µs/op through 3 adapters, "
         f"{det_us:.1f}µs observe->tripped")
    return {
        "metric": "watchdog online-check latency "
                  f"(per-op observe, {n_ops // 1000}k-op stream)",
        "value": round(per_op_us, 3),
        "unit": "us/op",
        "vs_baseline": round(1.0 / per_op_us, 2) if per_op_us else 0.0,
    }


def bench_fallback_overhead(n_hists=64, ops_each=300):
    """Degradation-ladder floor cost (ISSUE 5): the same ensemble
    checked on the device vs with the device FORCED DOWN — every kernel
    launch raises RESOURCE_EXHAUSTED, so analysis walks the ladder
    (batch-halve -> width-halve -> host floor). Verdict parity between
    the two passes is asserted; vs_baseline = device_time / forced_host
    _time (the fraction of normal speed a dead device leaves you)."""
    import statistics as _st

    from jepsen_tpu.checker import models
    from jepsen_tpu.tpu import synth, wgl

    hists = [synth.register_history(ops_each, n_procs=4,
                                    seed=2000 + i, crash_p=0.1)
             for i in range(n_hists)]
    total_ops = sum(len(h) for h in hists)
    model = models.cas_register()
    wgl.analysis_batch_streamed(model, hists, chunk=32)  # warm
    dev_times = []
    for _ in range(3):
        t0 = time.time()
        dev_res = wgl.analysis_batch_streamed(model, hists, chunk=32)
        dev_times.append(time.time() - t0)
    dev = _st.median(dev_times)

    def boom(*a, **kw):
        raise RuntimeError("RESOURCE_EXHAUSTED: bench-forced "
                           "device failure")

    orig = wgl._launch
    wgl._launch = boom
    try:
        host_times = []
        for _ in range(3):
            t0 = time.time()
            host_res = wgl.analysis_batch_streamed(model, hists,
                                                   chunk=32)
            host_times.append(time.time() - t0)
    finally:
        wgl._launch = orig
    host_s = _st.median(host_times)
    mismatches = sum(1 for a, b in zip(dev_res, host_res)
                     if a["valid?"] != b["valid?"])
    assert mismatches == 0, f"{mismatches} verdicts changed on fallback"
    assert all("degradation" in r for r in host_res)
    _log(f"fallback-overhead: device {dev:.2f}s forced-host "
         f"{host_s:.2f}s ({host_s / dev:.1f}x slower), verdict parity "
         f"{n_hists}/{n_hists}")
    return {
        "metric": f"forced-host degradation-ladder throughput "
                  f"({n_hists} histories, verdict parity asserted)",
        "value": round(total_ops / host_s, 1),
        "unit": "ops/s",
        "vs_baseline": round(dev / host_s, 3),
    }


def bench_fleet_throughput(n_runs=8, ops_each=3000):
    """Checking-as-a-service throughput (ISSUE 13): N concurrent
    seeded runs streamed through ONE fleet server (chunked over the
    socket, WAL'd, continuously batched across tenants into shared
    device launches) vs the same N histories checked solo,
    sequentially, one launch each — the baseline a tenant pool without
    a fleet pays. Verdict parity is asserted per run. vs_baseline =
    fleet aggregate ops/s over solo aggregate ops/s (>1 = the shared
    pool beats N separate checkers); device utilization rides along
    as mean histories per FINAL launch (slice launches reported
    separately — the old blended average over-stated utilization)
    plus the flight recorder's per-class packed-rows/capacity
    occupancy. The last round's stats feed the fleet-latency line and
    the ledger's fleet block (_fleet_latency_line)."""
    import shutil
    import statistics as _st
    import tempfile
    import threading as _th

    from jepsen_tpu.checker import models
    from jepsen_tpu.fleet import client as fclient
    from jepsen_tpu.fleet import scheduler as fsched
    from jepsen_tpu.fleet import server as fserver
    from jepsen_tpu.tpu import synth, wgl

    hists = [synth.register_history(ops_each, seed=3000 + i)
             for i in range(n_runs)]
    total_ops = sum(len(h) for h in hists)
    model = models.cas_register()

    # solo baseline: each run checked alone (one launch per history)
    wgl.analysis(model, hists[0])  # warm the kernel cache
    t0 = time.time()
    solo_res = [wgl.analysis(model, h) for h in hists]
    solo_s = time.time() - t0

    def one_round():
        base = tempfile.mkdtemp(prefix="fleet-bench-")
        sched = fsched.Scheduler(window_s=0.1)
        srv = fserver.FleetServer(
            base, scheduler=sched,
            quotas=fserver.Quotas(max_tenants=n_runs + 1,
                                  max_total_streams=2 * n_runs),
            stream_checks=False).start()
        out = {}
        barrier = _th.Barrier(n_runs)

        def tenant(i):
            c = fclient.FleetClient(srv.addr, f"bench{i}", "r",
                                    model="cas-register")
            ops = list(hists[i])
            for j in range(0, len(ops), 512):
                c.send_chunk(ops[j:j + 512])
            barrier.wait(timeout=60)
            out[i] = c.finish(timeout_s=300)
            c.close()

        t0 = time.time()
        threads = [_th.Thread(target=tenant, args=(i,))
                   for i in range(n_runs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0
        st = srv.stats()
        srv.stop()
        shutil.rmtree(base, ignore_errors=True)  # WALs per round add up
        return wall, out, st

    one_round()  # warm (fleet path compiles its own shape buckets)
    walls = []
    for _ in range(3):
        wall, out, st = one_round()
        walls.append(wall)
    fleet_s = _st.median(walls)
    mism = sum(1 for i, r in enumerate(solo_res)
               if out[i]["result"]["valid?"] != r["valid?"])
    assert mism == 0, f"{mism} fleet verdicts diverged from solo"
    _FLEET_ROUND.clear()
    _FLEET_ROUND.update(st)
    sch = st["scheduler"]
    finals = max(sch.get("final_launches", 0), 1)
    util = sch["final_hists"] / finals
    fr = st.get("flightrec") or {}
    occ = {c: (v or {}).get("occupancy", 0.0)
           for c, v in (fr.get("classes") or {}).items()}
    _log(f"fleet-throughput: {n_runs} tenants fleet {fleet_s:.2f}s "
         f"vs solo {solo_s:.2f}s, {util:.1f} hists/final-launch over "
         f"{sch.get('final_launches', 0)} final + "
         f"{sch.get('slice_launches', 0)} slice launches, occupancy "
         f"slice {occ.get('slice', 0.0):.0%} "
         f"final {occ.get('final', 0.0):.0%} "
         f"(cross-tenant launches: {sch['cross_tenant_launches']})")
    return {
        "metric": f"fleet-throughput ({n_runs} concurrent tenants vs "
                  f"{n_runs} solo checks, verdict parity asserted)",
        "value": round(total_ops / fleet_s, 1),
        "unit": "ops/s",
        "vs_baseline": round((total_ops / fleet_s)
                             / (total_ops / solo_s), 3),
        "hists_per_launch": round(util, 2),
        "slice_launches": sch.get("slice_launches", 0),
        "final_launches": sch.get("final_launches", 0),
        "occupancy": {c: round(v, 3) for c, v in occ.items()},
    }


# the newest measured fleet round's stats() (scheduler + flightrec):
# bench_fleet_throughput fills it; the fleet-latency line and the
# ledger's fleet block read it
_FLEET_ROUND: dict = {}


def _fleet_latency_line():
    """The fleet-latency BENCH line: verdict/ack latency quantiles,
    launch-weighted mean occupancy, and the scheduler decision log
    from the throughput rounds' flight recorder. An observation line
    (vs_baseline 1.0), not a race."""
    fr = _FLEET_ROUND.get("flightrec") or {}
    v = fr.get("verdict_ms") or {}
    if not fr.get("enabled") or not v.get("n"):
        return []
    classes = fr.get("classes") or {}
    launches = sum((c or {}).get("launches", 0)
                   for c in classes.values())
    mean_occ = sum((c or {}).get("occupancy", 0.0)
                   * (c or {}).get("launches", 0)
                   for c in classes.values()) / max(launches, 1)
    ack = fr.get("ack_ms") or {}
    dec = fr.get("decisions") or {}
    _log(f"fleet-latency: verdict p50 {v.get('p50')}ms "
         f"p99 {v.get('p99')}ms ack p99 {ack.get('p99')}ms over "
         f"{v.get('n')} verdicts, mean occupancy {mean_occ:.0%}, "
         "decisions " + " ".join(f"{r}={dec.get(r, 0)}"
                                 for r in sorted(dec)))
    return [{
        "metric": f"fleet-latency verdict p99 "
                  f"({v.get('n')} verdicts, flight recorder)",
        "value": v.get("p99"),
        "unit": "ms",
        "vs_baseline": 1.0,
        "p50": v.get("p50"),
        "ack_p99": ack.get("p99"),
        "occupancy": {c: round((d or {}).get("occupancy", 0.0), 3)
                      for c, d in classes.items()},
        "mean_occupancy": round(mean_occ, 3),
        "decisions": dict(dec),
    }]


def bench_flightrec_overhead(n_runs=4, ops_each=600):
    """Flight-recorder overhead (ISSUE 17): the identical multi-tenant
    fleet round with the recorder instrumented vs disabled
    (FleetServer(flightrec=False)). Verdict parity is asserted between
    the two modes; vs_baseline = disabled/instrumented wall, and a
    ratio beyond the 2% budget gets a loud banner."""
    import shutil
    import statistics as _st
    import tempfile
    import threading as _th

    from jepsen_tpu.fleet import client as fclient
    from jepsen_tpu.fleet import scheduler as fsched
    from jepsen_tpu.fleet import server as fserver
    from jepsen_tpu.tpu import synth

    hists = [synth.register_history(ops_each, seed=4200 + i)
             for i in range(n_runs)]

    def one_round(flightrec):
        base = tempfile.mkdtemp(prefix="flightrec-bench-")
        sched = fsched.Scheduler(window_s=0.05)
        srv = fserver.FleetServer(
            base, scheduler=sched,
            quotas=fserver.Quotas(max_tenants=n_runs + 1,
                                  max_total_streams=2 * n_runs),
            stream_checks=False, flightrec=flightrec).start()
        out = {}

        def tenant(i):
            c = fclient.FleetClient(srv.addr, f"ovh{i}", "r",
                                    model="cas-register")
            ops = list(hists[i])
            for j in range(0, len(ops), 128):
                c.send_chunk(ops[j:j + 128])
            out[i] = c.finish(timeout_s=120)
            c.close()

        t0 = time.time()
        threads = [_th.Thread(target=tenant, args=(i,))
                   for i in range(n_runs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0
        srv.stop()
        shutil.rmtree(base, ignore_errors=True)
        return wall, out

    one_round(True)  # warm
    on_walls, off_walls = [], []
    on_out = off_out = None
    for _ in range(3):
        w, on_out = one_round(True)
        on_walls.append(w)
        w, off_out = one_round(False)
        off_walls.append(w)
    on_s, off_s = _st.median(on_walls), _st.median(off_walls)
    mism = sum(1 for i in range(n_runs)
               if on_out[i]["result"] != off_out[i]["result"])
    assert mism == 0, \
        f"{mism} verdicts changed with the recorder on"
    ratio = on_s / max(off_s, 1e-9)
    if ratio > 1.02:
        _log(f"!!! flightrec-overhead: {ratio:.3f}x exceeds the 2% "
             "budget")
    _log(f"flightrec-overhead: instrumented {on_s:.2f}s disabled "
         f"{off_s:.2f}s ({ratio:.3f}x), verdict parity "
         f"{n_runs}/{n_runs}")
    return {
        "metric": f"flightrec-overhead (instrumented vs disabled "
                  f"fleet round, {n_runs} tenants, verdict parity "
                  "asserted)",
        "value": round(ratio, 4),
        "unit": "x",
        "vs_baseline": round(off_s / max(on_s, 1e-9), 3),
    }


def bench_checkpoint_extend(n_pairs=8000):
    """The checkpoint-and-extend BENCH line (doc/robustness.md): a
    grown run re-checked from the ckpt store pays O(suffix), not
    O(history). Geometry: a 90% prefix is checked and checkpointed,
    then the grown (full) history is re-checked two ways — resumed
    from the prefix record, and from scratch through the same extend
    entry point (what a torn/stale record honestly degrades to).
    vs_baseline = full_recheck / suffix_recheck (target >=5x). The
    checkpoint write itself is timed separately and logged as a
    fraction of the full check (<2% budget) — durability must not
    tax the verdict path."""
    import tempfile
    from pathlib import Path

    from jepsen_tpu.checker import models
    from jepsen_tpu.tpu import ckpt as tckpt
    from jepsen_tpu.tpu import synth, wgl

    model = models.cas_register()
    ops = list(synth.register_history(n_pairs, seed=7))
    cut = int(len(ops) * 0.9)
    cut -= cut % 2  # invoke/complete pairs: keep the cut aligned
    prefix = ops[:cut]
    with tempfile.TemporaryDirectory() as td:
        store = Path(td) / "bench.ckpt"
        wgl.analysis_extend(model, prefix, store_path=store)
        seed_bytes = store.read_bytes()
        wgl.analysis_extend(model, ops, store_path=store)  # warm
        full = _timed(lambda: wgl.analysis_extend(model, ops))
        store.write_bytes(seed_bytes)
        suffix = _timed(
            lambda: wgl.analysis_extend(model, ops,
                                        store_path=store))
        rec = tckpt.read(store)
        wtmp = Path(td) / "write-probe.ckpt"
        write_s = _timed(lambda: tckpt.write(wtmp, rec))
    speedup = full / max(suffix, 1e-9)
    wfrac = write_s / max(full, 1e-9)
    if speedup < 5.0:
        _log(f"!!! checkpoint-extend: suffix re-check only "
             f"{speedup:.1f}x cheaper (target >=5x)")
    if wfrac > 0.02:
        _log(f"!!! checkpoint-extend: checkpoint write {wfrac:.1%} "
             "of the full check exceeds the 2% budget")
    _log(f"checkpoint-extend: full {full:.2f}s suffix {suffix:.2f}s "
         f"({speedup:.1f}x), ckpt write {write_s * 1e3:.1f}ms "
         f"({wfrac:.2%} of full)")
    return {
        "metric": f"checkpoint-extend suffix re-check "
                  f"({n_pairs}-op grown run, 10% suffix, ckpt write "
                  f"{wfrac:.2%} of full)",
        "value": round(suffix, 3),
        "unit": "s",
        "vs_baseline": round(speedup, 2),
    }


def bench_analyze_resume(n_ops=2000):
    """analyze --resume wall time (ISSUE 5): a stored run re-analyzed
    offline, resumed vs from scratch. vs_baseline = fresh_time /
    resume_time (>1 = resuming beats re-analyzing)."""
    import statistics as _st
    import tempfile

    from jepsen_tpu import checker, core, resume, store, testing
    from jepsen_tpu import generator as gen

    with tempfile.TemporaryDirectory() as td:
        state = testing.AtomState()
        test = testing.noop_test()
        test.update(
            name="bench-resume", store_base=td, nodes=["n1", "n2"],
            concurrency=4, db=testing.AtomDB(state),
            client=testing.AtomClient(state, latency_s=0.0),
            checker=checker.compose({"stats": checker.stats()}),
            spec={"workload": "register",
                  "opts": {"workload": "register",
                           "nodes": ["n1", "n2"], "concurrency": 4,
                           "ssh": {"dummy": True}, "ops": n_ops,
                           "rate": 1e9, "time_limit": 60}},
            generator=gen.clients(gen.limit(n_ops,
                                            lambda: {"f": "read"})))
        test = core.run(test)
        d = store.path(test)
        fresh = _st.median([_timed(lambda: resume.analyze_run(
            d, resume=False)) for _ in range(3)])
        resumed = _st.median([_timed(lambda: resume.analyze_run(
            d, resume=True)) for _ in range(3)])
    _log(f"analyze-resume: fresh {fresh:.2f}s resumed {resumed:.2f}s "
         f"({n_ops} ops)")
    return {
        "metric": f"analyze --resume wall time ({n_ops}-op stored run)",
        "value": round(resumed, 3),
        "unit": "s",
        "vs_baseline": round(fresh / max(resumed, 1e-9), 2),
    }


def _timed(f) -> float:
    t0 = time.time()
    f()
    return time.time() - t0


def _telemetry_lines():
    """Kernel-profile lines derived from the run's telemetry: the
    process-global recorder accumulated compile/execute time and batch
    occupancy across every config above. Serialized to metrics.json
    and read back — the same artifact a stored test run carries — so
    the perf trajectory records what the observability layer reports.
    vs_baseline is 1.0: these are profile observations, not races."""
    import tempfile

    from jepsen_tpu import telemetry

    lines = []
    try:
        with tempfile.TemporaryDirectory() as td:
            _trace, mpath = telemetry.save(td)
            with open(mpath) as f:
                metrics = json.load(f)
        c = metrics.get("counters", {})
        compile_ns = c.get("wgl.kernel.compile_ns", 0)
        execute_ns = c.get("wgl.kernel.execute_ns", 0)
        if compile_ns or execute_ns:
            _log(f"telemetry: kernel compile {compile_ns / 1e9:.2f}s "
                 f"execute {execute_ns / 1e9:.2f}s over "
                 f"{c.get('wgl.kernel.launches', 0)} launches "
                 f"({c.get('wgl.kernel.iterations', 0)} iterations)")
            lines.append({
                "metric": "wgl kernel compile share of device time "
                          "(compile_ns / (compile_ns + execute_ns))",
                "value": round(compile_ns / (compile_ns + execute_ns), 4),
                "unit": "fraction",
                "vs_baseline": 1.0,
            })
        entries = c.get("wgl.batch.entries", 0)
        slots = c.get("wgl.batch.slots", 0)
        if slots:
            _log(f"telemetry: batch occupancy {entries}/{slots} slots")
            lines.append({
                "metric": "wgl batch slot occupancy "
                          "(history entries / padded kernel slots)",
                "value": round(entries / slots, 4),
                "unit": "fraction",
                "vs_baseline": 1.0,
            })
    except Exception as e:  # noqa: BLE001 — profile lines are extras
        _log(f"telemetry lines failed: {e!r}")
    return lines


# bench-line metric substrings -> ledger kernel names (value direction
# rides along: ops/s-style lines are higher-is-better)
_KERNEL_METRICS = (
    ("elle list-append", "elle-append", True),
    ("elle rw-register", "elle-rw", True),
    ("bank balance-conservation", "bank", True),
    ("ensemble linearizability", "wgl-ensemble", True),
    ("time-to-first-anomaly", "anomaly", False),
    ("fleet-throughput", "fleet", True),
    ("fleet-latency", "fleet-latency", False),
    ("flightrec-overhead", "flightrec-overhead", False),
    ("checkpoint-extend", "ckpt-extend", False),
)


def _ledger_entry(lines, headline):
    """One perf-ledger entry for this round: the headline plus a
    per-kernel breakdown (config lines mapped through _KERNEL_METRICS,
    and the headline's own encode/check split), so the slow-bleed
    detector can attribute a drift to wgl-vs-elle-vs-encode."""
    from jepsen_tpu import ledger

    kernels = {}
    for ln in lines:
        metric = str(ln.get("metric", ""))
        for sub, name, higher in _KERNEL_METRICS:
            if sub in metric and isinstance(ln.get("value"),
                                            (int, float)):
                kernels[name] = {"value": ln["value"],
                                 "unit": ln.get("unit"),
                                 "higher_is_better": higher}
    for field, name in (("encode_s", "encode"),
                        ("check_s", "wgl-segmented")):
        if isinstance(headline.get(field), (int, float)):
            kernels[name] = {"value": headline[field], "unit": "s",
                             "higher_is_better": False}
    # search-shape drift: witness position (config 6) + the run's
    # frontier/dedup aggregates from the process-global telemetry, so
    # the ledger can show a search whose SHAPE moved even when its
    # wall time didn't (doc/observability.md, search explorer)
    search: dict = {}
    for ln in lines:
        if isinstance(ln.get("witness_position"), (int, float)):
            search["witness_position"] = ln["witness_position"]
    try:
        from jepsen_tpu import telemetry

        c = telemetry.get().counters()
        g = telemetry.get().gauges()
        if c.get("wgl.search.states"):
            search["states_explored"] = int(c["wgl.search.states"])
            search["dedup_hits"] = int(c.get("wgl.search.dedup-hits",
                                             0))
        if g.get("wgl.search.frontier-peak"):
            search["frontier_peak"] = int(
                g["wgl.search.frontier-peak"])
    except Exception as e:  # noqa: BLE001 — search stats are extras
        _log(f"search stats unavailable: {e!r}")
    entries = ledger.read_entries(_ledger_path())
    floor = max((r for r, _p, _s in _bench_rounds()), default=0)
    out = {
        "round": ledger.next_round(entries, floor=floor),
        "kind": "bench",
        "headline": {k: headline.get(k) for k in
                     ("metric", "value", "unit", "runs_s", "spread")},
        "kernels": kernels,
    }
    if search:
        out["search"] = search
    # the fleet flight recorder's SLO/utilization round summary
    # (ISSUE 17): verdict/ack quantiles + per-class occupancy +
    # decision log, tracked per round like the kernels
    fr = (_FLEET_ROUND.get("flightrec") or {})
    if fr.get("enabled") and (fr.get("verdict_ms") or {}).get("n"):
        out["fleet"] = {
            "verdict_p50_ms": (fr.get("verdict_ms") or {}).get("p50"),
            "verdict_p99_ms": (fr.get("verdict_ms") or {}).get("p99"),
            "ack_p99_ms": (fr.get("ack_ms") or {}).get("p99"),
            "occupancy": {
                c: (d or {}).get("occupancy")
                for c, d in (fr.get("classes") or {}).items()},
            "decisions": dict(fr.get("decisions") or {}),
        }
    if _LINT_AGGREGATES:
        # the R3/R4 aggregates the SPMD rebuild (ROADMAP items 1-2)
        # must drive to zero, tracked per round like the kernels
        out["lint"] = {
            "non_donated_bytes": _LINT_AGGREGATES["non_donated_bytes"],
            "replicated_bytes": _LINT_AGGREGATES["replicated_bytes"],
            "unsharded_axes": _LINT_AGGREGATES["unsharded_axes"],
            "findings": dict(_LINT_AGGREGATES.get("findings", {})),
        }
    return out


def _ledger_update(lines, headline):
    """Appends this round to bench_ledger.jsonl and runs the
    slow-bleed detector over the whole ledger: a kernel whose EWMA has
    drifted >15% below its recent best gets a SLOW-BLEED banner and a
    `slow_bleed` field on the headline line — the gradual regressions
    the per-round >20% gate can't see. Skipped for BENCH_OPS smoke
    runs (incomparable sizes would poison the series)."""
    from jepsen_tpu import ledger

    try:
        entry = _ledger_entry(lines, headline)
        path = _ledger_path()
        ledger.append_entry(path, entry)
        entries = ledger.read_entries(path)
        ledger.validate_entries(entries)
        _log(f"ledger: appended round {entry['round']} "
             f"({len(entries)} entries)")
        verdicts = ledger.detect(entries)
        bleeding = {k: v for k, v in verdicts.items()
                    if v.get("bleeding")}
        for name, v in sorted(bleeding.items()):
            _log(f"!!! SLOW-BLEED: {name} EWMA is "
                 f"{v['drop'] * 100:.1f}% below its best of the last "
                 f"{ledger.BEST_WINDOW} rounds (the per-round "
                 f"{REGRESSION_THRESHOLD:.0%} gate never tripped)")
        if bleeding:
            headline["slow_bleed"] = {
                k: v["drop"] for k, v in sorted(bleeding.items())}
    except Exception as e:  # noqa: BLE001 — ledger must not sink bench
        _log(f"ledger update failed: {e!r}")
    return headline


def _multichip_lines():
    """Scaling-attribution line from the newest MULTICHIP_r*.json: the
    dry run prints `parallel_efficiency {...}` into its tail
    (__graft_entry__.dryrun_multichip); bench re-checks it so a flat
    mesh sweep fails loudly in every report, not just the sweep's."""
    import glob
    import re

    from jepsen_tpu.tpu import profiler

    here = os.path.dirname(os.path.abspath(__file__))
    paths = sorted(
        glob.glob(os.path.join(here, "MULTICHIP_r*.json")),
        key=lambda p: int(re.search(r"r(\d+)", os.path.basename(p))
                          .group(1)))
    eff = None
    src = None
    bench_line = None
    for p in reversed(paths):
        try:
            with open(p) as f:
                doc = json.load(f)
            tail = str(doc.get("tail", ""))
            raw = doc.get("parallel_efficiency")
            if raw is None:
                m = re.search(r"parallel_efficiency (\{[^}\n]*\})",
                              tail)
                raw = json.loads(m.group(1)) if m else None
            if isinstance(raw, dict) and raw:
                eff = {int(k): float(v) for k, v in raw.items()}
                src = os.path.basename(p)
                # the dry run's sharded-ensemble headline rides the
                # same tail (BENCH {...}); lift it into the report
                m = re.search(r"^BENCH (\{.*\})$", tail, re.M)
                if m:
                    try:
                        bench_line = json.loads(m.group(1))
                        bench_line["source"] = src
                    except ValueError:
                        bench_line = None
                break
        except (OSError, ValueError):
            continue
    if not eff:
        return []
    bad = profiler.check_efficiency(eff, log=lambda m: _log(
        f"!!! {src}: {m}"))
    n_max = max(eff)
    _log(f"multichip efficiency ({src}): " + " ".join(
        f"mesh{n}={e}" for n, e in sorted(eff.items())))
    lines = [{
        "metric": f"multichip parallel efficiency at {n_max} devices "
                  f"(mesh1_time / (mesh{n_max}_time x {n_max}), "
                  f"from {src})",
        "value": eff[n_max],
        "unit": "fraction",
        "vs_baseline": round(eff[n_max] / 1.0, 4),
        "flat_mesh": bool(bad),
    }]
    if bench_line:
        lines.append(bench_line)
    return lines


def _enable_compile_cache():
    """Persistent XLA compilation cache (jepsen_tpu.tpu.spmd): repeat
    bench runs skip the ~35s one-time kernel compiles. The shared knob
    is JEPSEN_TPU_COMPILE_CACHE (default under store/); the legacy
    JAX_COMPILATION_CACHE_DIR still wins for existing bench rigs."""
    legacy = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if legacy:
        os.environ.setdefault("JEPSEN_TPU_COMPILE_CACHE", legacy)
    try:
        from jepsen_tpu.tpu import spmd

        d = spmd.enable_compile_cache()
        _log(f"compilation cache: {d or 'disabled'}")
    except Exception as e:  # noqa: BLE001 — cache is best-effort
        _log(f"compilation cache unavailable: {e!r}")


def main():
    from jepsen_tpu.tpu import dist

    dist.ensure_initialized()  # before the first JAX computation
    _enable_compile_cache()
    n_events = int(os.environ.get("BENCH_OPS", "1000000"))
    small = n_events < 1_000_000
    lines = []
    if not os.environ.get("BENCH_SKIP_EXTRAS"):
        for fn, args in ((bench_warm_start, ()),
                         (bench_monitor_overhead, ()),
                         (bench_lint_wall, ()),
                         (bench_trace_overhead, ()),
                         (bench_nodeprobe_overhead, ()),
                         (bench_coverage_overhead,
                          (50_000 if small else 200_000,)),
                         (bench_watchdog_latency, ()),
                         (bench_fallback_overhead,
                          (32 if small else 64,)),
                         (bench_certify_overhead,
                          (50_000 if small else 200_000,)),
                         (bench_analyze_resume, ()),
                         (bench_checkpoint_extend,
                          (4000 if small else 8000,)),
                         (bench_fleet_throughput,
                          ((8, 600) if small else (8, 3000))),
                         (bench_flightrec_overhead,
                          ((4, 300) if small else (4, 600))),
                         (bench_list_append,
                          (10_000 if small else 100_000,)),
                         (bench_rw_register,
                          (10_000 if small else 100_000,)),
                         (bench_bank, (50_000 if small else 500_000,)),
                         (bench_ensemble, (128 if small else 1024,)),
                         (bench_anomaly, (n_events,))):
            try:
                lines.append(fn(*args))
            except Exception as e:  # extras must never sink the headline
                _log(f"{fn.__name__} failed: {e!r}")
        try:
            lines.extend(_fleet_latency_line())
        except Exception as e:  # noqa: BLE001 — observation line only
            _log(f"fleet-latency line failed: {e!r}")
    headline = bench_headline(n_events)
    lines.extend(_telemetry_lines())
    try:
        lines.extend(_multichip_lines())
    except Exception as e:  # noqa: BLE001 — attribution lines are extras
        _log(f"multichip lines failed: {e!r}")
    if not small and not os.environ.get("BENCH_NO_LEDGER"):
        # cross-run perf ledger + slow-bleed detection (full-size
        # rounds only: smoke-run numbers would poison the series)
        headline = _ledger_update(lines, headline)
    lines.append(headline)  # the driver records the LAST line
    for ln in lines:
        print(json.dumps(ln))


if __name__ == "__main__":
    main()
